"""CLI entry point.

Mirrors the reference's ``simulator.py``:

    python3 simulator.py --config-name fed_avg/mnist.yaml ++fed_avg.round=1 ...
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.dirname(__file__)))

from distributed_learning_simulator_tpu.config import load_config
from distributed_learning_simulator_tpu.training import train, train_with_recovery

if __name__ == "__main__":
    config = load_config(sys.argv[1:])
    if dict(config.fault_tolerance or {}).get("auto_resume"):
        # ++<algo>.fault_tolerance.auto_resume=True: run under the
        # self-healing supervisor — a crashed/preempted run relaunches
        # from its newest loadable checkpoint instead of waiting for an
        # operator (bounded by fault_tolerance.max_restarts)
        result = train_with_recovery(config=config)
    else:
        result = train(config=config)
    print(result.get("performance", {}))
