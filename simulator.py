"""CLI entry point.

Mirrors the reference's ``simulator.py``:

    python3 simulator.py --config-name fed_avg/mnist.yaml ++fed_avg.round=1 ...
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.dirname(__file__)))

from distributed_learning_simulator_tpu.config import load_config
from distributed_learning_simulator_tpu.training import train

if __name__ == "__main__":
    config = load_config(sys.argv[1:])
    result = train(config=config)
    print(result.get("performance", {}))
