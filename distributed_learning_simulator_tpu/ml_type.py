"""Core enums and exceptions.

TPU-native equivalent of ``cyy_torch_toolbox.ml_type`` (imported by the
reference's workers, e.g. ``simulation_lib/worker/aggregation_worker.py:4``).
"""

import enum

try:  # python >= 3.11
    _StrEnum = enum.StrEnum
except AttributeError:  # python 3.10: str+Enum mixin has the same semantics

    class _StrEnum(str, enum.Enum):
        def __str__(self) -> str:  # StrEnum prints the value, not the name
            return str(self.value)


class MachineLearningPhase(_StrEnum):
    Training = "training"
    Validation = "validation"
    Test = "test"


class ExecutorHookPoint(_StrEnum):
    """Hook points fired by the trainer engine (reference hook points used:
    AFTER_BATCH, AFTER_EPOCH, AFTER_EXECUTE, OPTIMIZER_STEP — SURVEY.md §2.13)."""

    BEFORE_EXECUTE = "before_execute"
    BEFORE_EPOCH = "before_epoch"
    BEFORE_BATCH = "before_batch"
    AFTER_BATCH = "after_batch"
    OPTIMIZER_STEP = "optimizer_step"
    AFTER_EPOCH = "after_epoch"
    AFTER_EXECUTE = "after_execute"


class StopExecutingException(Exception):
    """Raised by hooks to stop the executor (reference:
    ``cyy_torch_toolbox.ml_type.StopExecutingException``)."""


class TaskAbortedError(Exception):
    """Internal: another executor of the task failed; unwind this thread."""
