"""Federated GNN with boundary-embedding sharing
(reference ``simulation_lib/method/fed_gnn/__init__.py:4-8``)."""

from ...server.graph_server import GraphNodeServer
from ...worker.graph_worker import GraphWorker
from ..algorithm_factory import CentralizedAlgorithmFactory


class FedGCNWorker(GraphWorker):
    """FedGCN paper variant: feature sharing forced on (reference
    ``simulation_lib/method/fed_gcn/worker.py:4-7``)."""

    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        self._share_feature = True


CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_gnn",
    client_cls=GraphWorker,
    server_cls=GraphNodeServer,
)

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_gcn",
    client_cls=FedGCNWorker,
    server_cls=GraphNodeServer,
)
