"""Shapley plumbing shared by the GTG and multi-round methods.

TPU-native equivalent of
``simulation_lib/method/shapley_value/shapley_value_algorithm.py:13-92``:
non-accumulating FedAvg whose ``aggregate_worker_data`` lazily builds the SV
engine (players + round-0 metric, which exists because the server sets
``need_init_performance``), computes per-round SVs with a metric callback
that re-aggregates each player subset and runs central inference, optionally
filters the round's aggregation to the best subset, and dumps
``shapley_values.json`` on exit.
"""

import copy
import json
import os
from typing import Any

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...message import Message
from ...utils.logging import get_logger


class ShapleyValueAlgorithm(FedAVGAlgorithm):
    def __init__(self, sv_algorithm_cls: type, server=None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._server = server
        self.accumulate = False
        self.metric_type: str = "accuracy"
        self.sv_algorithm = None
        self.sv_algorithm_cls = sv_algorithm_cls
        self.shapley_values: dict = {}
        self.shapley_values_S: dict = {}

    @property
    def config(self):
        return self._server.config

    @property
    def choose_best_subset(self) -> bool:
        return self.config.algorithm_kwargs.get("choose_best_subset", False)

    def _get_players(self):
        return sorted(self._all_worker_data.keys())

    def _sv_engine_kwargs(self) -> dict:
        """Engine ctor kwargs beyond (players, last_round_metric);
        subclasses add their config surface (e.g. hierarchical grouping)."""
        return dict(self.config.algorithm_kwargs.get("sv_kwargs", {}))

    def aggregate_worker_data(self) -> Message:
        if self.sv_algorithm is None:
            assert self._server.round_number == 1
            self.sv_algorithm = self.sv_algorithm_cls(
                players=self._get_players(),
                last_round_metric=self._server.performance_stat[
                    self._server.round_number - 1
                ][f"test_{self.metric_type}"],
                **self._sv_engine_kwargs(),
            )
        self.sv_algorithm.set_metric_function(self._get_subset_metric)
        self.sv_algorithm.compute(round_number=self._server.round_number)
        round_number = self._server.round_number
        self.shapley_values[round_number] = copy.deepcopy(
            self._convert_shapley_values(
                self.sv_algorithm.shapley_values[round_number]
            )
        )
        self.shapley_values_S[round_number] = self._convert_shapley_values(
            self.sv_algorithm.shapley_values_S[round_number]
        )
        if self.choose_best_subset:
            best_subset = set(self.shapley_values_S[round_number].keys())
            if best_subset:
                get_logger().info("use subset %s", best_subset)
                self._all_worker_data = {
                    k: v for k, v in self._all_worker_data.items() if k in best_subset
                }
        return super().aggregate_worker_data()

    def _convert_shapley_values(self, shapley_values: dict) -> dict:
        return shapley_values

    def _get_subset_metric(self, subset) -> float:
        assert subset
        worker_data = FedAVGAlgorithm._aggregate_worker_data(
            {k: v for k, v in self._all_worker_data.items() if k in subset}
        )
        return self._server.get_metric(worker_data, keep_performance_logger=False)[
            self.metric_type
        ]

    def exit(self) -> None:
        if self.sv_algorithm is None:
            return
        with open(
            os.path.join(self.config.save_dir, "shapley_values.json"),
            "wt",
            encoding="utf8",
        ) as f:
            json.dump({str(k): v for k, v in self.shapley_values.items()}, f)
        if self.choose_best_subset:
            with open(
                os.path.join(self.config.save_dir, "shapley_values_S.json"),
                "wt",
                encoding="utf8",
            ) as f:
                json.dump({str(k): v for k, v in self.shapley_values_S.items()}, f)
