"""Shapley plumbing shared by the GTG and multi-round methods.

TPU-native equivalent of
``simulation_lib/method/shapley_value/shapley_value_algorithm.py:13-92``:
non-accumulating FedAvg whose ``aggregate_worker_data`` lazily builds the SV
engine (players + round-0 metric, which exists because the server sets
``need_init_performance``), computes per-round SVs with a metric callback
that re-aggregates each player subset and runs central inference, optionally
filters the round's aggregation to the best subset, and dumps
``shapley_values.json`` on exit.
"""

import copy
import json
import os
from typing import Any

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...message import Message
from ...utils.logging import get_logger


class ShapleyValueAlgorithm(FedAVGAlgorithm):
    def __init__(self, sv_algorithm_cls: type, server=None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._server = server
        self.accumulate = False
        self.metric_type: str = "accuracy"
        self.sv_algorithm = None
        self.sv_algorithm_cls = sv_algorithm_cls
        self.shapley_values: dict = {}
        self.shapley_values_S: dict = {}

    @property
    def config(self):
        return self._server.config

    @property
    def choose_best_subset(self) -> bool:
        return self.config.algorithm_kwargs.get("choose_best_subset", False)

    def _get_players(self):
        return sorted(self._all_worker_data.keys())

    def _sv_engine_kwargs(self) -> dict:
        """Engine ctor kwargs beyond (players, last_round_metric);
        subclasses add their config surface (e.g. hierarchical grouping)."""
        from ...shapley import sv_engine_kwargs

        return sv_engine_kwargs(self.config, hierarchical=False)

    def aggregate_worker_data(self) -> Message:
        if self.sv_algorithm is None:
            assert self._server.round_number == 1
            self.sv_algorithm = self.sv_algorithm_cls(
                players=self._get_players(),
                last_round_metric=self._server.performance_stat[
                    self._server.round_number - 1
                ][f"test_{self.metric_type}"],
                **self._sv_engine_kwargs(),
            )
        self.sv_algorithm.set_metric_function(self._get_subset_metric)
        if hasattr(self.sv_algorithm, "set_batch_metric_function"):
            self.sv_algorithm.set_batch_metric_function(self._get_subset_metrics)
        self.sv_algorithm.compute(round_number=self._server.round_number)
        round_number = self._server.round_number
        self.shapley_values[round_number] = copy.deepcopy(
            self._convert_shapley_values(
                self.sv_algorithm.shapley_values[round_number]
            )
        )
        self.shapley_values_S[round_number] = self._convert_shapley_values(
            self.sv_algorithm.shapley_values_S[round_number]
        )
        if self.choose_best_subset:
            best_subset = set(self.shapley_values_S[round_number].keys())
            if best_subset:
                get_logger().info("use subset %s", best_subset)
                self._all_worker_data = {
                    k: v for k, v in self._all_worker_data.items() if k in best_subset
                }
        return super().aggregate_worker_data()

    def _convert_shapley_values(self, shapley_values: dict) -> dict:
        return shapley_values

    def _get_subset_metric(self, subset) -> float:
        assert subset
        worker_data = FedAVGAlgorithm._aggregate_worker_data(
            {k: v for k, v in self._all_worker_data.items() if k in subset}
        )
        return self._server.get_metric(worker_data, keep_performance_logger=False)[
            self.metric_type
        ]

    def _get_subset_metrics(self, subsets: list) -> list[float]:
        """Batched subset metrics: ONE vmapped program aggregates every
        subset (a 0/1 worker mask) and runs central inference on all of them
        concurrently — vs the reference's one full test inference per subset
        per round (``shapley_value_algorithm.py:67-76``, SURVEY.md §3.3 'HOT')."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ...engine.batching import make_epoch_batches
        from ...ml_type import MachineLearningPhase as Phase

        workers = sorted(self._all_worker_data)
        data = self._all_worker_data
        weights = jnp.asarray(
            [float(data[w].dataset_size) for w in workers], jnp.float32
        )
        stacked = {
            k: jnp.stack(
                [jnp.asarray(data[w].parameter[k], jnp.float32) for w in workers]
            )
            for k in data[workers[0]].parameter
        }
        engine = self._server.tester.engine
        test = self._server.tester.dataset_collection.get_dataset(Phase.Test)
        batches = make_epoch_batches(test, self.config.batch_size)

        # subset-eval chunk: bound live memory at chunk × model params.
        # ``algorithm_kwargs.sv_batch_chunk`` trades HBM for fewer
        # dispatches on large-player rounds (2^N − 1 subsets): a bigger
        # chunk evaluates more masks per compiled program; the default
        # keeps the historical 16.
        chunk = max(
            1, int(self.config.algorithm_kwargs.get("sv_batch_chunk", 16) or 16)
        )

        # stacked params / test batches enter as arguments — closing over
        # them would bake the arrays into the HLO as constants
        @jax.jit
        def eval_masks(masks, stacked, weights, batches):
            def agg_one(mask):
                w = mask * weights
                tw = jnp.maximum(jnp.sum(w), 1e-12)
                return {
                    k: jnp.einsum("w,w...->...", w, v) / tw
                    for k, v in stacked.items()
                }

            params = jax.vmap(agg_one)(masks)
            return jax.vmap(lambda p: engine.eval_fn(p, batches))(params)

        results: list[float] = []
        masks = np.asarray(
            [[1.0 if w in set(s) else 0.0 for w in workers] for s in subsets],
            np.float32,
        )
        for start in range(0, len(subsets), chunk):
            part = masks[start : start + chunk]
            if part.shape[0] < chunk:  # pad for a single compiled shape
                part = np.pad(part, ((0, chunk - part.shape[0]), (0, 0)))
                part[len(masks) - start :, 0] = 1.0  # avoid all-zero masks
            out = eval_masks(jnp.asarray(part), stacked, weights, batches)
            correct = np.asarray(out["correct"])
            count = np.maximum(np.asarray(out["count"]), 1.0)
            loss = np.asarray(out["loss_sum"]) / count
            acc = correct / count
            values = loss if self.metric_type == "loss" else acc
            results.extend(float(v) for v in values[: len(masks) - start])
        return results[: len(subsets)]

    def exit(self) -> None:
        if self.sv_algorithm is None:
            return
        with open(
            os.path.join(self.config.save_dir, "shapley_values.json"),
            "wt",
            encoding="utf8",
        ) as f:
            json.dump({str(k): v for k, v in self.shapley_values.items()}, f)
        if self.choose_best_subset:
            with open(
                os.path.join(self.config.save_dir, "shapley_values_S.json"),
                "wt",
                encoding="utf8",
            ) as f:
                json.dump({str(k): v for k, v in self.shapley_values_S.items()}, f)
