"""Shapley-value contribution evaluation methods
(reference ``simulation_lib/method/shapley_value/__init__.py:6-15``)."""

from ...worker.aggregation_worker import AggregationWorker
from ..algorithm_factory import CentralizedAlgorithmFactory
from .servers import (
    GTGShapleyValueServer,
    HierarchicalShapleyValueServer,
    MultiRoundShapleyValueServer,
)

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="multiround_shapley_value",
    client_cls=AggregationWorker,
    server_cls=MultiRoundShapleyValueServer,
)
CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="GTG_shapley_value",
    client_cls=AggregationWorker,
    server_cls=GTGShapleyValueServer,
)
CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="Hierarchical_shapley_value",
    client_cls=AggregationWorker,
    server_cls=HierarchicalShapleyValueServer,
)
