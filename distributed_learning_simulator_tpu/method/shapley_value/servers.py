"""Shapley servers (reference
``simulation_lib/method/shapley_value/shapley_value_server.py:4-7`` +
``GTG_shapley_value_server.py:5-7`` + ``multiround_shapley_value_server.py:5-9``)."""

from typing import Any

from ...server.aggregation_server import AggregationServer
from ...shapley.gtg_shapley_value import GTGShapleyValue
from ...shapley.multiround_shapley_value import MultiRoundShapleyValue
from .shapley_value_algorithm import ShapleyValueAlgorithm


class ShapleyValueServer(AggregationServer):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self.need_init_performance = True


class GTGShapleyValueAlgorithm(ShapleyValueAlgorithm):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(GTGShapleyValue, *args, **kwargs)


class MultiRoundShapleyValueAlgorithm(ShapleyValueAlgorithm):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(MultiRoundShapleyValue, *args, **kwargs)


class GTGShapleyValueServer(ShapleyValueServer):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs, algorithm=GTGShapleyValueAlgorithm(server=self))


class MultiRoundShapleyValueServer(ShapleyValueServer):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(
            **kwargs, algorithm=MultiRoundShapleyValueAlgorithm(server=self)
        )
