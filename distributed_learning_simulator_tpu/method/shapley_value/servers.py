"""Shapley servers (reference
``simulation_lib/method/shapley_value/shapley_value_server.py:4-7`` +
``GTG_shapley_value_server.py:5-7`` + ``multiround_shapley_value_server.py:5-9``)."""

from typing import Any

from ...server.aggregation_server import AggregationServer
from ...shapley.gtg_shapley_value import GTGShapleyValue
from ...shapley.hierarchical_shapley_value import HierarchicalShapleyValue
from ...shapley.multiround_shapley_value import MultiRoundShapleyValue
from .shapley_value_algorithm import ShapleyValueAlgorithm


class ShapleyValueServer(AggregationServer):
    #: Shapley subset sampling needs every selected upload per round — a
    #: staleness-discounted partial flush has no valuation semantics
    _buffered_capable = False

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self.need_init_performance = True


class GTGShapleyValueAlgorithm(ShapleyValueAlgorithm):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(GTGShapleyValue, *args, **kwargs)


class MultiRoundShapleyValueAlgorithm(ShapleyValueAlgorithm):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(MultiRoundShapleyValue, *args, **kwargs)


class HierarchicalShapleyValueAlgorithm(ShapleyValueAlgorithm):
    """Two-level SV over worker groups (``conf/hierarchical_sv/mnist.yaml``:
    ``part_number``, ``vp_size`` live directly in ``algorithm_kwargs``)."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(HierarchicalShapleyValue, *args, **kwargs)

    def _sv_engine_kwargs(self) -> dict:
        from ...shapley import sv_engine_kwargs

        return sv_engine_kwargs(self.config, hierarchical=True)


class GTGShapleyValueServer(ShapleyValueServer):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs, algorithm=GTGShapleyValueAlgorithm(server=self))


class MultiRoundShapleyValueServer(ShapleyValueServer):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(
            **kwargs, algorithm=MultiRoundShapleyValueAlgorithm(server=self)
        )


class HierarchicalShapleyValueServer(ShapleyValueServer):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(
            **kwargs, algorithm=HierarchicalShapleyValueAlgorithm(server=self)
        )
