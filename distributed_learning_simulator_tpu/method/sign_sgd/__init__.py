"""sign-SGD: per-step sign-compressed gradients with majority-vote
aggregation (Bernstein et al., signSGD with majority vote).

The reference ships configs (``conf/sign_sgd/*.yaml``) and the
``GradientWorker`` substrate but the method registration itself was removed
from the snapshot (SURVEY.md §2.9); this build supplies it as a first-class
method, per BASELINE.json's north star.
"""

from ..algorithm_factory import CentralizedAlgorithmFactory
from .server import GradientServer, SignSGDAlgorithm
from .worker import SignSGDWorker

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="sign_SGD",
    client_cls=SignSGDWorker,
    server_cls=GradientServer,
    algorithm_cls=SignSGDAlgorithm,
)
