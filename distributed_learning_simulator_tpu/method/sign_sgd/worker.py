"""sign-SGD client: ships sign(gradient) each optimizer step
(reference substrate: ``simulation_lib/worker/gradient_worker.py:13-131``
with ``_process_gradient`` = sign)."""

import jax
import jax.numpy as jnp

from ...worker.gradient_worker import GradientWorker


class SignSGDWorker(GradientWorker):
    def _process_gradient(self, gradient: jax.Array) -> jax.Array:
        return jnp.sign(gradient)
