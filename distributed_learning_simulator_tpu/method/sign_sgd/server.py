"""Gradient-aggregating server for per-step methods.

The reference snapshot has no gradient server (the sign_SGD method was
removed — SURVEY.md §3.5 note); this supplies one: gather all workers'
gradient messages each optimizer step, aggregate (majority vote for
sign-SGD), broadcast the result ``in_round``; stop when every worker has
sent ``end_training``.
"""

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ...algorithm.aggregation_algorithm import AggregationAlgorithm
from ...message import Message
from ...server.server import Server
from ...utils.logging import get_logger


@jax.jit
def _majority_vote(stacked: jax.Array) -> jax.Array:
    return jnp.sign(jnp.sum(stacked, axis=0))


@functools.partial(jax.jit, static_argnums=())
def _weighted_mean(stacked: jax.Array, weights: jax.Array) -> jax.Array:
    weights = weights / jnp.sum(weights)
    return jnp.einsum("w,wn->n", weights, stacked)


class SignSGDAlgorithm(AggregationAlgorithm):
    """Majority vote: sign of the sum of worker signs."""

    use_majority_vote = True

    def __init__(self, server=None) -> None:
        super().__init__(server=server)
        self.ended_workers: set[int] = set()

    def process_worker_data(self, worker_id, worker_data, **kwargs) -> None:
        if worker_data is not None and worker_data.end_training:
            self.ended_workers.add(worker_id)
        super().process_worker_data(worker_id, worker_data, **kwargs)

    def aggregate_worker_data(self) -> Message:
        gradient_messages = {
            w: d
            for w, d in self._all_worker_data.items()
            if isinstance(d, Message) and "gradient" in d.other_data
        }
        if not gradient_messages:
            return Message(end_training=True)
        stacked = jnp.stack(
            [gradient_messages[w].other_data["gradient"] for w in sorted(gradient_messages)]
        )
        if self.use_majority_vote:
            aggregated = _majority_vote(stacked)
        else:
            weights = jnp.asarray(
                [
                    float(gradient_messages[w].other_data["dataset_size"])
                    for w in sorted(gradient_messages)
                ],
                dtype=jnp.float32,
            )
            aggregated = _weighted_mean(stacked, weights)
        return Message(in_round=True, other_data={"gradient": aggregated})


class GradientServer(Server):
    """Event loop over per-step gradient messages.

    Workers may finish their epochs at different times (unequal batch
    counts); an ``end_training`` message permanently retires a worker — each
    optimizer step aggregates over the workers still running, and the loop
    stops once every worker has retired.
    """

    def __init__(self, algorithm: AggregationAlgorithm, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._algorithm = algorithm
        self._algorithm.set_server(self)
        self._algorithm.set_config(self.config)
        self._worker_flag: set[int] = set()
        self._ended: set[int] = set()
        self._end = False
        self._round_number = 1
        self._final_params = None
        self._stat: dict[int, dict] = {}

    @property
    def algorithm(self) -> AggregationAlgorithm:
        return self._algorithm

    def _process_worker_data(self, worker_id: int, data: Message | None) -> None:
        if data is not None and data.end_training:
            self._ended.add(worker_id)
            if getattr(data, "parameter", None):
                self._final_params = data.parameter
                data = Message(end_training=True, other_data=data.other_data)
            self._algorithm.process_worker_data(worker_id=worker_id, worker_data=data)
            if len(self._ended) >= self.worker_number:
                self._end = True
                get_logger().info("all workers ended; gradient server stops")
            self._maybe_aggregate()
            return
        self._algorithm.process_worker_data(worker_id=worker_id, worker_data=data)
        self._worker_flag.add(worker_id)
        self._maybe_aggregate()

    def _maybe_aggregate(self) -> None:
        expected = self.worker_number - len(self._ended)
        if expected == 0 or len(self._worker_flag) < expected:
            return
        result = self._algorithm.aggregate_worker_data()
        if result.end_training:
            self._end = True
        else:
            self._send_result(result)
        self._worker_flag.clear()
        self._algorithm.clear_worker_data()

    def _active_workers(self) -> set[int]:
        return set(range(self.worker_number)) - self._ended

    def _select_workers(self) -> set[int]:
        # per-step collectives reach every still-running worker
        return set(range(self.worker_number)) - self._ended

    def _stopped(self) -> bool:
        return self._end

    @property
    def performance_stat(self) -> dict[int, dict]:
        return self._stat

    def _server_exit(self) -> None:
        if self._final_params is not None:
            import os

            from ...util.checkpoint import atomic_json_dump

            metric = self.get_metric(self._final_params)
            self._stat[1] = {f"test_{k}": v for k, v in metric.items()}
            atomic_json_dump(
                os.path.join(self.save_dir, "round_record.json"), self._stat
            )
        self._algorithm.exit()
