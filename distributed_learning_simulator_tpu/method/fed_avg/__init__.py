"""Canonical FedAvg with delta uploads
(reference ``simulation_lib/method/fed_avg/__init__.py:5-10``)."""

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...server.aggregation_server import AggregationServer
from ...worker.aggregation_worker import AggregationWorker
from ..algorithm_factory import CentralizedAlgorithmFactory

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_avg",
    client_cls=AggregationWorker,
    server_cls=AggregationServer,
    algorithm_cls=FedAVGAlgorithm,
)
