"""The algorithm registry.

TPU-native equivalent of ``simulation_lib/method/algorithm_factory.py:6-79``:
``register_algorithm(name, client_cls, server_cls, client_endpoint_cls,
server_endpoint_cls, algorithm_cls)`` plus ``create_client``/``create_server``
that construct the endpoint and then the role, auto-instantiating the
aggregation algorithm into the server kwargs.
"""

import dataclasses
from typing import Any

from ..topology.central_topology import CentralTopology, ClientEndpoint, ServerEndpoint


@dataclasses.dataclass
class _Registration:
    algorithm_name: str
    client_cls: type
    server_cls: type
    client_endpoint_cls: type
    server_endpoint_cls: type
    algorithm_cls: type | None
    # TPU build: optional SPMD round program for the fast path (parallel/)
    spmd_program_cls: type | None = None


class CentralizedAlgorithmFactory:
    config: dict[str, _Registration] = {}

    @classmethod
    def register_algorithm(
        cls,
        algorithm_name: str,
        client_cls: type,
        server_cls: type,
        client_endpoint_cls: type = ClientEndpoint,
        server_endpoint_cls: type = ServerEndpoint,
        algorithm_cls: type | None = None,
        spmd_program_cls: type | None = None,
    ) -> None:
        assert algorithm_name not in cls.config, f"duplicate algorithm {algorithm_name}"
        cls.config[algorithm_name] = _Registration(
            algorithm_name=algorithm_name,
            client_cls=client_cls,
            server_cls=server_cls,
            client_endpoint_cls=client_endpoint_cls,
            server_endpoint_cls=server_endpoint_cls,
            algorithm_cls=algorithm_cls,
        )
        cls.config[algorithm_name].spmd_program_cls = spmd_program_cls

    @classmethod
    def has_algorithm(cls, algorithm_name: str) -> bool:
        return algorithm_name in cls.config

    @classmethod
    def get_registration(cls, algorithm_name: str) -> _Registration:
        return cls.config[algorithm_name]

    @classmethod
    def create_client(
        cls,
        algorithm_name: str,
        topology: CentralTopology,
        worker_id: int,
        endpoint_kwargs: dict | None = None,
        kwargs: dict | None = None,
    ) -> Any:
        reg = cls.config[algorithm_name]
        endpoint = reg.client_endpoint_cls(topology, worker_id, **(endpoint_kwargs or {}))
        return reg.client_cls(endpoint=endpoint, **(kwargs or {}))

    @classmethod
    def create_server(
        cls,
        algorithm_name: str,
        topology: CentralTopology,
        endpoint_kwargs: dict | None = None,
        kwargs: dict | None = None,
    ) -> Any:
        reg = cls.config[algorithm_name]
        endpoint = reg.server_endpoint_cls(topology, **(endpoint_kwargs or {}))
        kwargs = dict(kwargs or {})
        if reg.algorithm_cls is not None and "algorithm" not in kwargs:
            kwargs["algorithm"] = reg.algorithm_cls()
        return reg.server_cls(endpoint=endpoint, **kwargs)
