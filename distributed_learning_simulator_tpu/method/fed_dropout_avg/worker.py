"""FedDropoutAvg client (reference
``simulation_lib/method/fed_dropout_avg/worker.py:10-30``): before upload,
each parameter element is zeroed with probability ``dropout_rate``; the send
count is logged for the communication cost model
(``analysis/analyze_log.py``)."""

from typing import Any

import jax
import jax.numpy as jnp

from ...message import ParameterMessage
from ...utils.logging import get_logger
from ...worker.aggregation_worker import AggregationWorker


class FedDropoutAvgWorker(AggregationWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._dropout_rate: float = self.config.algorithm_kwargs["dropout_rate"]
        self._drop_round = 0

    def _get_sent_data(self) -> ParameterMessage:
        self._send_parameter_diff = False
        sent_data = super()._get_sent_data()
        assert isinstance(sent_data, ParameterMessage)
        self._drop_round += 1
        parameter = sent_data.parameter
        aligned = getattr(self.trainer, "reserved_quant_rng", None)
        if aligned is not None:
            # the SPMD stream (parallel/spmd_sparse.py local_train): the
            # reserved per-round rng, folded by leaf POSITION in insertion
            # order — identical mask bits, tight cross-executor parity
            items = [
                (i, name, jax.random.fold_in(aligned, i))
                for i, name in enumerate(parameter)
            ]
        else:
            key = jax.random.PRNGKey(
                self.config.seed * 1_000_003
                + self.worker_id * 1009
                + self._drop_round
            )
            items = []
            for i, name in enumerate(sorted(parameter)):
                key, sub = jax.random.split(key)
                items.append((i, name, sub))
        total_num = 0
        send_num = 0
        for _i, name, sub in items:
            keep = jax.random.bernoulli(
                sub, p=1.0 - self._dropout_rate, shape=parameter[name].shape
            )
            parameter[name] = parameter[name] * keep
            total_num += int(parameter[name].size)
            send_num += int(jnp.count_nonzero(parameter[name]))
        get_logger().info("send_num %s", send_num)
        get_logger().info("total_num %s", total_num)
        return sent_data
