"""FedDropoutAvg (arXiv 2111.13230): per-element Bernoulli dropout of the
full uploaded parameters; aggregation weight = nonzero mask × dataset size
(reference ``simulation_lib/method/fed_dropout_avg/__init__.py:7-12``)."""

from ...server.aggregation_server import AggregationServer
from ..algorithm_factory import CentralizedAlgorithmFactory
from .algorithm import FedDropoutAvgAlgorithm
from .worker import FedDropoutAvgWorker

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_dropout_avg",
    client_cls=FedDropoutAvgWorker,
    server_cls=AggregationServer,
    algorithm_cls=FedDropoutAvgAlgorithm,
)
