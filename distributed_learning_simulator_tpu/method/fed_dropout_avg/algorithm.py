"""FedDropoutAvg aggregation (reference
``simulation_lib/method/fed_dropout_avg/algorithm.py:8-19``): per-element
weights = (parameter != 0) × dataset_size, with a divide-by-zero guard."""

import jax.numpy as jnp

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm


class FedDropoutAvgAlgorithm(FedAVGAlgorithm):
    def _get_weight(self, dataset_size: int, name: str, parameter):
        return (parameter != 0).astype(jnp.float32) * dataset_size

    def _apply_total_weight(self, name: str, parameter, total_weight):
        total_weight = jnp.where(total_weight == 0, 1.0, total_weight)
        return super()._apply_total_weight(
            name=name, parameter=parameter, total_weight=total_weight
        )
