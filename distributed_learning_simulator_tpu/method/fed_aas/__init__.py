"""fed_aas: subgraph federated learning with per-round neighbor sampling.

The reference ships configs for this method (``conf/fed_aas/*.yaml``:
GCN models, ``share_feature: false``, aggressive ``edge_drop_rate``,
``num_neighbor`` fan-in caps) but its registration was removed from the
snapshot (SURVEY.md §2.9 "configs with no registration").  Re-created here
from the config surface: a :class:`GraphWorker` that trains on its local
subgraph only (no boundary-embedding exchange) and, when ``num_neighbor``
is set (``algorithm_kwargs`` or ``extra_hyper_parameters``), resamples a
bounded-fan-in edge subset every round (GraphSAGE-style neighbor sampling,
the reference's ``num_neighbor`` dataloader kwarg,
``simulation_lib/worker/graph_worker.py:98-101``).
"""

import numpy as np

from ...server.graph_server import GraphNodeServer
from ...utils.logging import get_logger
from ...worker.graph_worker import GraphWorker
from ..algorithm_factory import CentralizedAlgorithmFactory


def cap_fan_in(
    base_mask: np.ndarray, dst: np.ndarray, limit: int, rng
) -> np.ndarray:
    """Cap incoming fan-in per destination node at ``limit``: random
    permutation, stable-sort by destination, keep rank-within-destination
    < limit (vectorized — edge lists are large).  Shared by the threaded
    worker and the SPMD session so their RNG streams stay identical."""
    candidates = rng.permutation(np.nonzero(base_mask)[0])
    keep = np.zeros_like(base_mask, dtype=bool)
    if len(candidates):
        d = dst[candidates]
        by_dst = np.argsort(d, kind="stable")
        sorted_d = d[by_dst]
        first_idx = np.r_[0, np.nonzero(np.diff(sorted_d))[0] + 1]
        group_id = np.cumsum(np.r_[0, (np.diff(sorted_d) != 0).astype(np.int64)])
        rank = np.arange(len(sorted_d)) - first_idx[group_id]
        keep[candidates[by_dst[rank < limit]]] = True
    return keep


class FedAASWorker(GraphWorker):
    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        # local-subgraph training: never exchange boundary embeddings
        self._share_feature = False
        self._num_neighbor = self.config.algorithm_kwargs.get(
            "num_neighbor",
            self.config.extra_hyper_parameters.get("num_neighbor"),
        )

    def _before_round(self) -> None:
        super()._before_round()
        if self._num_neighbor is None:
            return
        graph = self.training_dataset.inputs
        edge_index = graph["edge_index"]
        dst = edge_index[1]
        base = self._local_edge_mask.astype(bool)
        rng = np.random.default_rng(
            self.config.seed * 1013 + self.worker_id * 97 + self._round_num
        )
        keep = cap_fan_in(base, dst, int(self._num_neighbor), rng)
        graph["edge_mask"] = keep.astype(np.float32)
        get_logger().debug(
            "%s round %d: neighbor sampling kept %d/%d local edges",
            self.name,
            self._round_num,
            int(keep.sum()),
            int(base.sum()),
        )


CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_aas",
    client_cls=FedAASWorker,
    server_cls=GraphNodeServer,
)
