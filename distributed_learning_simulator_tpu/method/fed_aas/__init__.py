"""fed_aas: subgraph federated learning with per-round neighbor sampling.

The reference ships configs for this method (``conf/fed_aas/*.yaml``:
GCN models, ``share_feature: false``, aggressive ``edge_drop_rate``,
``num_neighbor`` fan-in caps) but its registration was removed from the
snapshot (SURVEY.md §2.9 "configs with no registration").  Re-created here
from the config surface: a :class:`GraphWorker` that trains on its local
subgraph only (no boundary-embedding exchange) and, when ``num_neighbor``
is set (``algorithm_kwargs`` or ``extra_hyper_parameters``), resamples a
bounded-fan-in edge subset every round (GraphSAGE-style neighbor sampling,
the reference's ``num_neighbor`` dataloader kwarg,
``simulation_lib/worker/graph_worker.py:98-101``).
"""

import numpy as np

from ...ops.graph_sampling import cap_fan_in
from ...server.graph_server import GraphNodeServer
from ...utils.logging import get_logger
from ...worker.graph_worker import GraphWorker
from ..algorithm_factory import CentralizedAlgorithmFactory

__all__ = ["FedAASWorker"]


class FedAASWorker(GraphWorker):
    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        # local-subgraph training: never exchange boundary embeddings
        self._share_feature = False
        # num_neighbor is resampled per ROUND here (not per batch) — keep it
        # out of the dataloader to avoid double sampling
        self._dataloader_num_neighbor = False
        self._num_neighbor = self.config.algorithm_kwargs.get(
            "num_neighbor",
            self.config.extra_hyper_parameters.get("num_neighbor"),
        )

    def _before_round(self) -> None:
        super()._before_round()
        if self._num_neighbor is None:
            return
        graph = self.training_dataset.inputs
        edge_index = graph["edge_index"]
        dst = edge_index[1]
        base = self._local_edge_mask.astype(bool)
        rng = np.random.default_rng(
            self.config.seed * 1013 + self.worker_id * 97 + self._round_num
        )
        keep = cap_fan_in(base, dst, int(self._num_neighbor), rng)
        graph["edge_mask"] = keep.astype(np.float32)
        get_logger().debug(
            "%s round %d: neighbor sampling kept %d/%d local edges",
            self.name,
            self._round_num,
            int(keep.sum()),
            int(base.sum()),
        )


CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_aas",
    client_cls=FedAASWorker,
    server_cls=GraphNodeServer,
)
