"""Method packages: importing this module registers every algorithm
(reference ``simulation_lib/method/__init__.py:1-9`` — registrations fire at
import time)."""

from .algorithm_factory import CentralizedAlgorithmFactory

from . import fed_avg  # noqa: F401
from . import fed_paq  # noqa: F401
from . import fed_dropout_avg  # noqa: F401
from . import fed_obd  # noqa: F401
from . import sign_sgd  # noqa: F401
from . import smafd  # noqa: F401
from . import shapley_value  # noqa: F401
from . import fed_gnn  # noqa: F401
from . import fed_aas  # noqa: F401

__all__ = ["CentralizedAlgorithmFactory"]
