"""FedOBD client role — spec-driven, block selection by composition.

Functional parity target: ``simulation_lib/method/fed_obd/worker.py:12-74``
(phase 1: block-dropout'd uploads through the quantized endpoint; phase 2:
per-epoch ``in_round`` aggregation with lr reuse for ``second_phase_epoch``
epochs, ``end_training`` on the last one).  All phase *meaning* comes from
the shared :class:`~.driver.PhaseSpec` records; this class only applies
whatever spec the server's annotation names — it holds no transition rules
of its own.
"""

from typing import Any

from ...message import DeltaParameterMessage, Message, ParameterMessage
from ...ml_type import ExecutorHookPoint
from ...topology.quantized_endpoint import QuantClientEndpoint
from ...utils.logging import get_logger
from ...worker.aggregation_worker import AggregationWorker
from .driver import BLOCK_DROPOUT_ROUNDS, EPOCH_TUNE, PHASE_TWO_KEY, PhaseSpec
from .obd_algorithm import OpportunisticBlockDropoutAlgorithm


class FedOBDWorker(AggregationWorker):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._block_selector = OpportunisticBlockDropoutAlgorithm(
            dropout_rate=self.config.algorithm_kwargs["dropout_rate"],
            worker_id=self.worker_id,
        )
        self._spec: PhaseSpec = BLOCK_DROPOUT_ROUNDS
        self._last_epoch_announced = False
        assert isinstance(self._endpoint, QuantClientEndpoint)
        self._endpoint.dequant_server_data = True
        self._apply_spec(self._spec)

    # ---- spec application (client-side meaning of a phase) ----
    def _apply_spec(self, spec: PhaseSpec) -> None:
        self._spec = spec
        self._send_parameter_diff = not spec.block_dropout
        self._reuse_learning_rate = spec.reuse_learning_rate
        if spec.epoch_cadence:
            self._aggregation_time = ExecutorHookPoint.AFTER_EPOCH

    def _enter_epoch_tune(self) -> None:
        get_logger().info("%s switches to %s", self.name, EPOCH_TUNE.name)
        self._apply_spec(EPOCH_TUNE)
        self.disable_choose_model_by_validation()
        self.trainer.hyper_parameter.epoch = self.config.algorithm_kwargs[
            "second_phase_epoch"
        ]
        # one more Worker.start() iteration runs the whole tuning phase
        self.config.round = self._round_num + 1
        self._register_aggregation()

    def _before_round(self) -> None:
        """Train the SPMD OBD session's exact rng stream (the 3-way split
        chain, one link per AGGREGATE — ``obd_aligned_round_stream``), so
        both executors follow the same trajectory.  With the shared phase
        driver, deterministic block selection, and the deterministic
        NNADQ codec, this is the last stream gap; the worker's
        ``_round_num`` counts aggregates on both phases when
        ``second_phase_epoch == 1`` (the per-epoch chain of a longer
        phase 2 is not reproducible from one ``set_round_stream`` call —
        those runs stay loosely compared)."""
        super()._before_round()
        if int(self.config.algorithm_kwargs.get("second_phase_epoch", 0)) == 1:
            from ...engine.executor import obd_aligned_round_stream
            from ...parallel.mesh import client_slots, make_mesh

            # pass the SPMD session's exact padded slot count: split
            # prefixes are slot-count-dependent under non-partitionable
            # threefry, so the replayed stream must split the same n
            self.trainer.set_round_stream(
                obd_aligned_round_stream(
                    self.config.seed,
                    self._round_num,
                    self.worker_id,
                    n_slots=client_slots(
                        self.config.worker_number, make_mesh()
                    ),
                )
            )

    # ---- message flow ----
    def _load_result_from_server(self, result: Message) -> None:
        if PHASE_TWO_KEY in result.other_data:
            assert isinstance(result, ParameterMessage)
            if getattr(result, "is_initial", False) and "round" in result.other_data:
                # resumed directly into phase 2: the round annotation must
                # land BEFORE _enter_epoch_tune derives config.round from
                # _round_num, or the worker would stop before training
                self._round_num = result.other_data["round"]
            self._enter_epoch_tune()
        super()._load_result_from_server(result=result)

    def _get_sent_data(self) -> Message:
        # global leaf positions for the codec's fold-by-position rule
        # (the SPMD program folds quant_rng by each leaf's index in the
        # FULL param dict, even when only kept blocks travel the wire)
        self._quant_fold_indices = {
            name: i for i, name in enumerate(self.trainer.params)
        }
        data = super()._get_sent_data()
        if self._spec.block_dropout:
            assert isinstance(data, ParameterMessage)
            kept = self._block_selector.get_block_parameter(
                parameter_dict=data.parameter, model_cache=self._model_cache
            )
            # ship the kept blocks as DIFFS vs the cached global (reference
            # ``worker.py:68`` model_cache.get_parameter_diff): the NNADQ
            # endpoint then quantizes deltas, whose span is one round's
            # movement — value quantization would snap that movement back
            # to the grid and stall training.  The server restores deltas
            # onto the old global, which also fills dropped blocks
            # (``message.py`` restore = complete semantics).
            cached = self._model_cache.parameter_dict
            return DeltaParameterMessage(
                delta_parameter={k: v - cached[k] for k, v in kept.items()},
                dataset_size=data.dataset_size,
                other_data=data.other_data,
                in_round=data.in_round,
                end_training=data.end_training,
            )
        data.in_round = True
        if self._spec.check_acc:
            data.other_data["check_acc"] = True
        return data

    def _aggregation(self, sent_data: Message, **kwargs: Any) -> None:
        if self._spec.epoch_cadence:
            executor = kwargs["executor"]
            if kwargs["epoch"] == executor.hyper_parameter.epoch:
                # last tuning epoch: announce the end of the run
                sent_data.end_training = True
                self._last_epoch_announced = True
        super()._aggregation(sent_data=sent_data, **kwargs)

    def _stopped(self) -> bool:
        return self._last_epoch_announced or super()._stopped()
