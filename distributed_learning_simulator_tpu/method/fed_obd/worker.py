"""FedOBD client (reference ``simulation_lib/method/fed_obd/worker.py:12-74``):
phase 1 uploads block-dropout'd partial parameters through a quantized
endpoint; on the server's ``phase_two`` signal switches to per-epoch
``in_round`` aggregation with lr reuse for ``second_phase_epoch`` epochs."""

from typing import Any

from ...message import DeltaParameterMessage, Message, ParameterMessage
from ...ml_type import ExecutorHookPoint
from ...topology.quantized_endpoint import QuantClientEndpoint
from ...utils.logging import get_logger
from ...worker.aggregation_worker import AggregationWorker
from .obd_algorithm import OpportunisticBlockDropoutAlgorithm
from .phase import Phase


class FedOBDWorker(AggregationWorker, OpportunisticBlockDropoutAlgorithm):
    def __init__(self, *args: Any, **kwargs: Any) -> None:
        AggregationWorker.__init__(self, *args, **kwargs)
        OpportunisticBlockDropoutAlgorithm.__init__(
            self,
            dropout_rate=self.config.algorithm_kwargs["dropout_rate"],
            worker_id=self.worker_id,
        )
        self.__phase = Phase.STAGE_ONE
        self.__end_training = False
        assert isinstance(self._endpoint, QuantClientEndpoint)
        self._endpoint.dequant_server_data = True
        self._send_parameter_diff = False

    def _load_result_from_server(self, result: Message) -> None:
        if "phase_two" in result.other_data:
            assert isinstance(result, ParameterMessage)
            self.__phase = Phase.STAGE_TWO
            get_logger().info("%s switches to phase 2", self.name)
            self._reuse_learning_rate = True
            self._send_parameter_diff = True
            self.disable_choose_model_by_validation()
            self.trainer.hyper_parameter.epoch = self.config.algorithm_kwargs[
                "second_phase_epoch"
            ]
            self.config.round = self._round_num + 1
            self._aggregation_time = ExecutorHookPoint.AFTER_EPOCH
            self._register_aggregation()
        super()._load_result_from_server(result=result)

    def _aggregation(self, sent_data: Message, **kwargs: Any) -> None:
        if self.__phase == Phase.STAGE_TWO:
            executor = kwargs["executor"]
            if kwargs["epoch"] == executor.hyper_parameter.epoch:
                sent_data.end_training = True
                self.__end_training = True
        super()._aggregation(sent_data=sent_data, **kwargs)

    def _stopped(self) -> bool:
        return self.__end_training or super()._stopped()

    def _get_sent_data(self) -> Message:
        data = super()._get_sent_data()
        if self.__phase == Phase.STAGE_ONE:
            assert isinstance(data, ParameterMessage)
            kept = self.get_block_parameter(
                parameter_dict=data.parameter, model_cache=self._model_cache
            )
            # ship the kept blocks as DIFFS vs the cached global (reference
            # ``worker.py:68`` model_cache.get_parameter_diff): the NNADQ
            # endpoint then quantizes deltas, whose span is one round's
            # movement — value quantization would snap that movement back
            # to the grid and stall training.  The server restores deltas
            # onto the old global, which also fills dropped blocks
            # (``message.py`` restore = complete semantics).
            cached = self._model_cache.parameter_dict
            return DeltaParameterMessage(
                delta_parameter={k: v - cached[k] for k, v in kept.items()},
                dataset_size=data.dataset_size,
                other_data=data.other_data,
                in_round=data.in_round,
                end_training=data.end_training,
            )
        data.in_round = True
        data.other_data["check_acc"] = True
        return data
