"""FedOBD: two-phase opportunistic block dropout over quantized transport
(reference ``simulation_lib/method/fed_obd/__init__.py:8-22``)."""

from ...topology.quantized_endpoint import (
    NNADQClientEndpoint,
    NNADQServerEndpoint,
    StochasticQuantClientEndpoint,
    StochasticQuantServerEndpoint,
)
from ..algorithm_factory import CentralizedAlgorithmFactory
from .server import FedOBDServer
from .worker import FedOBDWorker

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_obd",
    client_cls=FedOBDWorker,
    server_cls=FedOBDServer,
    client_endpoint_cls=NNADQClientEndpoint,
    server_endpoint_cls=NNADQServerEndpoint,
)

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_obd_sq",
    client_cls=FedOBDWorker,
    server_cls=FedOBDServer,
    client_endpoint_cls=StochasticQuantClientEndpoint,
    server_endpoint_cls=StochasticQuantServerEndpoint,
)
