"""Opportunistic Block Dropout.

TPU-native equivalent of
``simulation_lib/method/fed_obd/obd_algorithm.py:8-145``: decompose the model
into blocks, rank blocks by mean L2 delta against the cached global model,
and greedily keep blocks under the ``1 - dropout_rate`` parameter budget.

Blocks here are groups of flat parameter paths sharing a top-level module
prefix (flax module instances — e.g. one ``DenseLayer_k`` of densenet40, one
``EncoderLayer_k`` of the transformer), the structural analogue of the
reference's (Conv,BN) groups / TransformerEncoderLayer blocks.  The block
L2 deltas are computed in one fused jit program instead of per-block CPU
norms.
"""

import jax
import jax.numpy as jnp

from ...ops.pytree import Params
from ...utils.logging import get_logger


def get_module_blocks(parameter_names: list[str]) -> list[list[str]]:
    """Group flat "a/b/kernel" names by their leading module component."""
    blocks: dict[str, list[str]] = {}
    for name in sorted(parameter_names):
        prefix = name.split("/")[0] if "/" in name else name
        blocks.setdefault(prefix, []).append(name)
    return list(blocks.values())


@jax.jit
def _block_deltas(cur: Params, prev: Params) -> Params:
    return {
        k: jnp.sum(jnp.square(cur[k].astype(jnp.float32) - prev[k].astype(jnp.float32)))
        for k in cur
    }


class OpportunisticBlockDropoutAlgorithm:
    def __init__(self, dropout_rate: float, worker_id: int) -> None:
        self.__dropout_rate = dropout_rate
        self.__worker_id = worker_id
        self.__blocks: list[list[str]] | None = None
        self.__parameter_num = 0

    def __find_blocks(self, parameter_dict: Params) -> None:
        self.__blocks = get_module_blocks(list(parameter_dict.keys()))
        covered = {name for block in self.__blocks for name in block}
        assert covered == set(parameter_dict.keys())
        self.__parameter_num = sum(int(v.size) for v in parameter_dict.values())
        if self.__worker_id == 0:
            get_logger().info(
                "identified %d blocks over %d parameters",
                len(self.__blocks),
                self.__parameter_num,
            )

    def get_block_parameter(self, parameter_dict: Params, model_cache) -> Params:
        """Return the selected blocks' parameters (full values; the caller
        converts them to diffs vs the cached global for transport — the
        reference does the same at ``method/fed_obd/worker.py:59-69``, and
        diff transport is what keeps the NNADQ quantization step far below
        the parameters' own scale)."""
        if self.__blocks is None:
            self.__find_blocks(parameter_dict)
        assert self.__blocks is not None
        threshold = (1 - self.__dropout_rate) * self.__parameter_num

        per_name_sq = _block_deltas(parameter_dict, model_cache.parameter_dict)
        scored: list[tuple[float, int, list[str]]] = []
        for block in self.__blocks:
            sq = sum(float(per_name_sq[name]) for name in block)
            size = sum(int(parameter_dict[name].size) for name in block)
            scored.append((float(jnp.sqrt(sq)) / size, size, block))

        new_parameter_dict: Params = {}
        partial_parameter_num = 0
        for mean_delta, size, block in sorted(scored, key=lambda t: t[0], reverse=True):
            if partial_parameter_num > threshold:
                break
            if partial_parameter_num + size > threshold:
                continue
            partial_parameter_num += size
            for name in block:
                new_parameter_dict[name] = parameter_dict[name]
        get_logger().info(
            "partial_parameter_num %s threshold %s parameter_num %s",
            partial_parameter_num,
            threshold,
            self.__parameter_num,
        )
        return new_parameter_dict
