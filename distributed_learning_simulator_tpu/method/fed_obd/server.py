"""FedOBD server role — a thin adapter over the shared phase driver.

Functional parity target: ``simulation_lib/method/fed_obd/server.py:10-61``
(random selection + per-round stats in phase 1, all-worker per-epoch
aggregation with ``check_acc`` stats in phase 2, plateau handling).  The
round structure itself lives in :mod:`.driver`, shared with the SPMD
session — this class only translates driver decisions into the threaded
server's message flow.
"""

from typing import Any

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...message import ParameterMessageBase
from ...server.aggregation_server import AggregationServer
from ...topology.quantized_endpoint import QuantServerEndpoint
from ...utils.logging import get_logger
from .driver import ObdRoundDriver


class FedOBDServer(AggregationServer):
    #: the OBD phase driver owns the round progression — a buffer flush
    #: cannot reorder phase-1/phase-2 aggregates (aggregation_mode gate)
    _buffered_capable = False

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("algorithm", FedAVGAlgorithm())
        super().__init__(**kwargs)
        self._driver = ObdRoundDriver.from_config(self.config)
        self._last_phase_name = ""  # phase that produced the pending stat
        self._bcast_count = 0  # aggregates broadcast so far (codec chain)
        assert isinstance(self._endpoint, QuantServerEndpoint)
        # global-model broadcasts ride the same codec as uploads
        self._endpoint.quant_broadcast = True

    def _annotate_stat(self, round_stat: dict) -> None:
        if self._last_phase_name:
            round_stat["phase"] = self._last_phase_name

    def _try_resume(self):
        """Base resume restores params/round/stats; the phase driver must
        then be fast-forwarded by replaying its transition rules over the
        restored aggregates (same replay as ``SpmdFedOBDSession``) — a
        fresh driver would re-run the whole phase-1 budget."""
        resumed = super()._try_resume()
        if resumed is None:
            return None
        from .driver import replay_resume

        stats = self.performance_stat
        total = len([k for k in stats if k > 0])
        # replay the RECORDED phase sequence through the driver — one
        # definition of the transition rules (shared with the SPMD
        # session), no plateau re-guessing; a superseded tail is dropped
        kept_keys, phase1_kept = replay_resume(self._driver, stats)
        for stale in [k for k in stats if k > 0 and k not in kept_keys]:
            del stats[stale]
        # each kept aggregate was broadcast once (non-initial, so it drew a
        # codec rng): continue the aligned bcast chain from there — the SPMD
        # session advances its 3-way rng chain the same way on resume
        # (spmd_obd.py run: one chain step per replayed aggregate)
        self._bcast_count = len(kept_keys)
        # the base resume numbered the round after the LATEST checkpoint;
        # the replayed schedule may have dropped that tail — round and
        # params must follow the kept prefix (stat key == checkpoint key)
        self._round_number = phase1_kept + 1
        if kept_keys and len(kept_keys) < total:
            from ...util.resume import load_round_checkpoint

            kept_params = load_round_checkpoint(
                self.config.algorithm_kwargs["resume_dir"], kept_keys[-1]
            )
            if kept_params is not None:
                resumed = kept_params
        get_logger().info(
            "resume: fed_obd driver fast-forwarded to %s (round -> %d)",
            self._driver.phase.name if self._driver.phase else "finished",
            self._round_number,
        )
        return resumed

    def _select_workers(self) -> set[int]:
        phase = self._driver.phase
        if phase is not None and not phase.select_all:
            return super()._select_workers()
        return set(range(self.worker_number))

    def _get_stat_key(self) -> int:
        # epoch-cadence records land while the round counter is frozen
        # (``in_round`` uploads), so stat keys append past whatever exists
        if not self.performance_stat:
            return super()._get_stat_key()
        return max(self.performance_stat.keys()) + 1

    def _maybe_early_stop(self, result) -> None:
        """No-op: the phase driver owns plateau handling (phase-1 plateau
        switches phases, it must not end the run)."""

    def _aggregate_worker_data(self) -> ParameterMessageBase:
        result = super()._aggregate_worker_data()
        assert result is not None
        # capture the phase that PRODUCED this aggregate before the driver
        # possibly switches (the stat is recorded after the decision)
        self._last_phase_name = self._driver.phase.name if self._driver.phase else ""
        improved = True
        if self._driver.early_stop and self.performance_stat:
            improved = not self._convergent()
        decision = self._driver.after_aggregate(
            improved=improved,
            worker_ended=result.end_training,
            check_acc="check_acc" in result.other_data,
        )
        self._compute_stat = decision.record_metric
        if decision.annotations:
            get_logger().info(
                "phase switch -> %s", self._driver.phase and self._driver.phase.name
            )
            result.other_data.update(decision.annotations)
        if decision.end_training:
            get_logger().info("stop aggregation")
            result.end_training = True
            self._driver.stop_now()
        return result

    def _before_send_result(self, result) -> None:
        super()._before_send_result(result)
        from ...message import ParameterMessage

        if (
            isinstance(result, ParameterMessage)
            and not getattr(result, "is_initial", False)
            and hasattr(self._endpoint, "set_quant_key")
            and int(
                self.config.algorithm_kwargs.get("second_phase_epoch", 0)
            )
            == 1
        ):
            # fed_obd_sq: the quantized broadcast draws the SPMD chain's
            # bcast rng for this aggregate, folded by global leaf position
            # (parallel/spmd_obd.py round_program's bcast loop); NNADQ
            # endpoints have no set_quant_key and skip this
            from ...engine.executor import obd_aligned_bcast_rng

            self._bcast_count += 1
            self._endpoint.set_quant_key(
                obd_aligned_bcast_rng(self.config.seed, self._bcast_count),
                fold_indices={
                    name: i for i, name in enumerate(result.parameter)
                },
            )

    def _init_annotations(self) -> dict:
        # a resume that fast-forwarded into phase 2 must tell the freshly
        # started workers on the INIT message so they adopt the
        # epoch-cadence spec (the phase-switch annotation they never saw)
        from .driver import EPOCH_TUNE, PHASE_TWO_KEY

        if self._driver.phase is EPOCH_TUNE:
            return {PHASE_TWO_KEY: True}
        return {}

    def _stopped(self) -> bool:
        return self._driver.finished
