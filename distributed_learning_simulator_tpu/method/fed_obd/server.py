"""FedOBD server (reference ``simulation_lib/method/fed_obd/server.py:10-61``):
phase state machine over the FedAvg aggregator — phase 1 rounds with random
selection and quantized broadcast; switch to phase 2 when rounds are
exhausted (or converged under early-stop); end on phase-2 plateau or worker
``end_training``."""

from typing import Any

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...message import ParameterMessageBase
from ...server.aggregation_server import AggregationServer
from ...topology.quantized_endpoint import QuantServerEndpoint
from ...utils.logging import get_logger
from .phase import Phase


class FedOBDServer(AggregationServer):
    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("algorithm", FedAVGAlgorithm())
        super().__init__(**kwargs)
        self.__phase: Phase = Phase.STAGE_ONE
        assert isinstance(self._endpoint, QuantServerEndpoint)
        self._endpoint.quant_broadcast = True

    def _select_workers(self) -> set[int]:
        if self.__phase != Phase.STAGE_ONE:
            return set(range(self.worker_number))
        return super()._select_workers()

    def _get_stat_key(self) -> int:
        if not self.performance_stat:
            return super()._get_stat_key()
        return max(self.performance_stat.keys()) + 1

    def _aggregate_worker_data(self) -> ParameterMessageBase:
        result = super()._aggregate_worker_data()
        assert result is not None
        self._compute_stat = False
        if self.__phase == Phase.STAGE_ONE:
            self._compute_stat = True
        if "check_acc" in result.other_data:
            self._compute_stat = True
        if result.end_training:
            self.__phase = Phase.END
        match self.__phase:
            case Phase.STAGE_ONE:
                if self.round_number >= self.config.round or (
                    self.early_stop and not self.__has_improvement()
                ):
                    get_logger().info("switch to phase 2")
                    self.__phase = Phase.STAGE_TWO
                    result.other_data["phase_two"] = True
            case Phase.STAGE_TWO:
                if self.early_stop and not self.__has_improvement():
                    get_logger().info("stop aggregation")
                    result.end_training = True
            case Phase.END:
                pass
        return result

    def _stopped(self) -> bool:
        return self.__phase == Phase.END

    def __has_improvement(self) -> bool:
        # the reference short-circuits phase 2 to "always improving"
        # (method/fed_obd/server.py:57-60), making its documented phase-2
        # plateau stop dead code; here phase 2 also uses the plateau test
        return not self._convergent()
