"""FedOBD server role — a thin adapter over the shared phase driver.

Functional parity target: ``simulation_lib/method/fed_obd/server.py:10-61``
(random selection + per-round stats in phase 1, all-worker per-epoch
aggregation with ``check_acc`` stats in phase 2, plateau handling).  The
round structure itself lives in :mod:`.driver`, shared with the SPMD
session — this class only translates driver decisions into the threaded
server's message flow.
"""

from typing import Any

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...message import ParameterMessageBase
from ...server.aggregation_server import AggregationServer
from ...topology.quantized_endpoint import QuantServerEndpoint
from ...utils.logging import get_logger
from .driver import ObdRoundDriver


class FedOBDServer(AggregationServer):
    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("algorithm", FedAVGAlgorithm())
        super().__init__(**kwargs)
        self._driver = ObdRoundDriver.from_config(self.config)
        assert isinstance(self._endpoint, QuantServerEndpoint)
        # global-model broadcasts ride the same codec as uploads
        self._endpoint.quant_broadcast = True

    def _select_workers(self) -> set[int]:
        phase = self._driver.phase
        if phase is not None and not phase.select_all:
            return super()._select_workers()
        return set(range(self.worker_number))

    def _get_stat_key(self) -> int:
        # epoch-cadence records land while the round counter is frozen
        # (``in_round`` uploads), so stat keys append past whatever exists
        if not self.performance_stat:
            return super()._get_stat_key()
        return max(self.performance_stat.keys()) + 1

    def _maybe_early_stop(self, result) -> None:
        """No-op: the phase driver owns plateau handling (phase-1 plateau
        switches phases, it must not end the run)."""

    def _aggregate_worker_data(self) -> ParameterMessageBase:
        result = super()._aggregate_worker_data()
        assert result is not None
        improved = True
        if self._driver.early_stop and self.performance_stat:
            improved = not self._convergent()
        decision = self._driver.after_aggregate(
            improved=improved,
            worker_ended=result.end_training,
            check_acc="check_acc" in result.other_data,
        )
        self._compute_stat = decision.record_metric
        if decision.annotations:
            get_logger().info(
                "phase switch -> %s", self._driver.phase and self._driver.phase.name
            )
            result.other_data.update(decision.annotations)
        if decision.end_training:
            get_logger().info("stop aggregation")
            result.end_training = True
            self._driver.stop_now()
        return result

    def _stopped(self) -> bool:
        return self._driver.finished
