"""FedOBD phases (reference ``simulation_lib/method/fed_obd/phase.py:4-7``)."""

from enum import IntEnum, auto


class Phase(IntEnum):
    STAGE_ONE = auto()
    STAGE_TWO = auto()
    END = auto()
