"""Host-side FedOBD phase driver — one source of truth for both executors.

The reference implements FedOBD's two-phase protocol as a pair of mirrored
state machines buried in role callbacks
(``simulation_lib/method/fed_obd/worker.py:12-74`` /
``server.py:10-61``): each side flips a private enum and re-derives the
other's behavior from message annotations.  This framework hoists the
schedule out of the roles entirely:

* the two phases are **data** (:class:`PhaseSpec` records listing selection
  policy, aggregation cadence, upload transform, and client-side settings);
* one :class:`ObdRoundDriver` owns every transition rule (round budget,
  plateau early-stop, epoch budget, worker end signal);
* the threaded server consults the driver after each aggregation, the
  threaded worker applies the spec the server's annotation names, and the
  SPMD session (``parallel/spmd_obd.py``) iterates the very same driver's
  phase stream — so round structure cannot drift between executors.
"""

import dataclasses
from typing import Any


@dataclasses.dataclass(frozen=True)
class PhaseSpec:
    """Everything one FedOBD phase means, for both roles."""

    name: str
    #: server: broadcast to everyone instead of a random subset
    select_all: bool
    #: aggregate per local epoch (``in_round`` uploads) instead of per round
    epoch_cadence: bool
    #: client upload transform: opportunistic block dropout + delta vs the
    #: cached global (phase 1) or a plain parameter diff (phase 2)
    block_dropout: bool
    #: client keeps its lr-schedule position across the phase switch
    reuse_learning_rate: bool
    #: ``in_round`` uploads carry ``check_acc`` so the server still records
    #: a test metric for them
    check_acc: bool


BLOCK_DROPOUT_ROUNDS = PhaseSpec(
    name="block_dropout_rounds",
    select_all=False,
    epoch_cadence=False,
    block_dropout=True,
    reuse_learning_rate=False,
    check_acc=False,
)

EPOCH_TUNE = PhaseSpec(
    name="epoch_tune",
    select_all=True,
    epoch_cadence=True,
    block_dropout=False,
    reuse_learning_rate=True,
    check_acc=True,
)

#: the wire annotation announcing the switch into :data:`EPOCH_TUNE`
#: (reference ``other_data["phase_two"]``, ``fed_obd/server.py:38-44``)
PHASE_TWO_KEY = "phase_two"


def replay_resume(driver, entries: dict[int, dict]) -> tuple[list[int], int]:
    """Shared resume replay for BOTH executors: feed the recorded phase
    sequence (rows keyed > 0, in key order) through
    :meth:`ObdRoundDriver.fast_forward`.  Returns ``(kept keys, phase-1
    ticks)``; the caller drops rows beyond the kept prefix."""
    from ...utils.logging import get_logger

    keys = sorted(k for k in entries if k > 0)
    names = [entries[k].get("phase", "") for k in keys]
    kept, phase1_ticks = driver.fast_forward(names)
    if kept < len(keys):
        get_logger().info(
            "resume: dropping %d recorded aggregates from a superseded "
            "schedule (from key %d on)",
            len(keys) - kept,
            keys[kept],
        )
    return keys[:kept], phase1_ticks

SPEC_BY_NAME = {spec.name: spec for spec in (BLOCK_DROPOUT_ROUNDS, EPOCH_TUNE)}


@dataclasses.dataclass
class Decision:
    """What the server should do with the aggregate it just produced."""

    annotations: dict[str, Any]
    end_training: bool
    record_metric: bool


class ObdRoundDriver:
    """Owns FedOBD phase progression.

    Transition rules (reference behavior, re-centralized):

    * ``block_dropout_rounds`` → ``epoch_tune`` when the round budget is
      spent, or on an accuracy plateau under ``early_stop``;
    * ``epoch_tune`` → done when the epoch budget is spent (the threaded
      worker announces this with ``end_training`` on its last epoch), or on
      a plateau under ``early_stop``.
    """

    def __init__(
        self, total_rounds: int, second_phase_epoch: int, early_stop: bool
    ) -> None:
        self.total_rounds = max(1, int(total_rounds))
        self.second_phase_epoch = max(1, int(second_phase_epoch))
        self.early_stop = bool(early_stop)
        self._schedule: list[PhaseSpec] = [BLOCK_DROPOUT_ROUNDS, EPOCH_TUNE]
        self._tick = 0  # aggregations completed in the current phase

    @classmethod
    def from_config(cls, config) -> "ObdRoundDriver":
        kwargs = config.algorithm_kwargs
        return cls(
            total_rounds=config.round,
            second_phase_epoch=int(kwargs["second_phase_epoch"]),
            early_stop=bool(kwargs.get("early_stop", False)),
        )

    @property
    def phase(self) -> PhaseSpec | None:
        return self._schedule[0] if self._schedule else None

    @property
    def finished(self) -> bool:
        return not self._schedule

    def budget(self, spec: PhaseSpec | None = None) -> int:
        spec = spec or self.phase
        assert spec is not None
        return self.second_phase_epoch if spec.epoch_cadence else self.total_rounds

    @property
    def remaining(self) -> int:
        """Aggregations left in the current phase's budget — what a fused
        dispatch may clamp its horizon to so phase switches always land on
        horizon boundaries (plateau early-stop can still end a phase
        sooner, which is why fusion runs per-round under ``early_stop``)."""
        return 0 if self.finished else self.budget() - self._tick

    def stop_now(self) -> None:
        self._schedule.clear()

    def fast_forward(self, phase_names: list[str]) -> int:
        """Resume support: advance the driver to match a RECORDED sequence
        of per-aggregate phase names (one source of truth for both
        executors' resume paths).

        The record already reflects whatever plateau/budget decisions the
        original run made, so no ``improved`` guessing happens here: a
        recorded name equal to the current phase consumes one tick; a name
        equal to the NEXT scheduled phase mid-budget follows the recorded
        switch ONLY when ``early_stop`` could have produced it (a plateau
        switch) — otherwise a mid-budget switch can only come from a
        SUPERSEDED schedule (e.g. the round budget was raised since) and
        the replay stops there.  Returns ``(consumed, phase1_ticks)`` —
        how many entries were consumed (the caller drops the rest) and how
        many of those counted against the block-dropout phase (the round
        counter's resume value; attribution happens HERE because untagged
        rows belong to whatever phase the replay was in)."""
        kept = 0
        phase1_ticks = 0
        for name in phase_names:
            if self.finished:
                break
            # untagged rows (records predating phase tagging) count against
            # the current phase
            if name and name != self.phase.name:
                if (
                    self.early_stop
                    and len(self._schedule) > 1
                    and name == self._schedule[1].name
                ):
                    self._schedule.pop(0)
                    self._tick = 0
                else:
                    break
            if self.phase.block_dropout:
                phase1_ticks += 1
            self._tick += 1
            kept += 1
            if self._tick >= self.budget():
                self._schedule.pop(0)
                self._tick = 0
        return kept, phase1_ticks

    def after_aggregate(
        self,
        *,
        improved: bool = True,
        worker_ended: bool = False,
        check_acc: bool = False,
    ) -> Decision:
        """Advance one tick and decide the aggregate's disposition.

        ``improved`` is the caller's plateau test (False = converged under
        the 5-point window); ``worker_ended`` / ``check_acc`` mirror the
        upload annotations on the threaded path.
        """
        spec = self.phase
        if spec is None:
            return Decision({}, end_training=True, record_metric=False)
        self._tick += 1
        record = (not spec.epoch_cadence) or check_acc
        if worker_ended:
            # a worker announced its last epoch — record and wind down
            self.stop_now()
            return Decision({}, end_training=False, record_metric=record)
        annotations: dict[str, Any] = {}
        end_training = False
        plateau = self.early_stop and not improved
        if self._tick >= self.budget(spec) or plateau:
            self._schedule.pop(0)
            self._tick = 0
            if self.finished:
                end_training = True
            else:
                annotations[PHASE_TWO_KEY] = True
        return Decision(annotations, end_training, record_metric=record)
