"""single_model_afd client: random whole-tensor dropout of the parameter
delta with error feedback (truncated deltas accumulate in the residual and
are retried next round).  Logs ``send_num`` the way the reference's analysis
cost model expects (``analysis/analyze_log.py:191-209``).

With ``algorithm_kwargs.topk_ratio`` set, per-tensor magnitude top-k
(native ``nth_element`` threshold, ``native/fastops.cc``) replaces the
whole-tensor dropout — the classical error-feedback compressor."""

from typing import Any

import jax.numpy as jnp
import numpy as np

from ...algorithm.random_dropout_algorithm import RandomDropoutAlgorithm
from ...native import sparsify
from ...ops.pytree import Params
from ...utils.logging import get_logger
from ...worker.error_feedback_worker import ErrorFeedbackWorker


class SingleModelAFDWorker(ErrorFeedbackWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._topk_ratio = self.config.algorithm_kwargs.get("topk_ratio")
        if self._topk_ratio is None:
            self._dropout = RandomDropoutAlgorithm(
                dropout_rate=self.config.algorithm_kwargs["dropout_rate"],
                seed=self.config.seed * 31 + self.worker_id,
            )

    def _topk_sparsify(self, delta: Params) -> tuple[Params, int]:
        sent: Params = {}
        send_num = 0
        for name, value in delta.items():
            flat = np.asarray(value, np.float32).reshape(-1)
            k = max(1, int(flat.size * self._topk_ratio))
            indices, values = sparsify(flat, k)
            send_num += len(indices)
            dense = np.zeros_like(flat)
            dense[indices] = values
            sent[name] = jnp.asarray(dense.reshape(np.shape(value)))
        return sent, send_num

    def _aligned_dropout(self, delta: Params, rng) -> Params:
        """The SPMD session's whole-tensor dropout rule, replicated
        host-side from the aligned stream's reserved rng
        (``parallel/spmd_sparse.py`` ``sparsify``): permutation by
        ``jax.random.permutation`` over INSERTION order, greedy ``<=``
        budget keep — identical kept sets, tight cross-executor parity."""
        import jax
        import numpy as np

        names = list(delta)
        # float32 throughout, with the threshold computed by the IDENTICAL
        # np expression as the SPMD sparsify (spmd_sparse.py) — boundary
        # `<=` decisions must match bit-for-bit
        sizes = np.asarray([float(delta[k].size) for k in names], np.float32)
        threshold = np.float32(
            (1.0 - float(self.config.algorithm_kwargs["dropout_rate"]))
            * np.sum(sizes, dtype=np.float32)
        )
        order = np.asarray(jax.random.permutation(rng, len(names)))
        partial = np.float32(0.0)
        kept: Params = {}
        keep_mask = {}
        for position in order:
            if np.float32(partial + sizes[position]) <= threshold:
                partial = np.float32(partial + sizes[position])
                keep_mask[names[position]] = True
        for name in names:  # kept entries in insertion order
            if keep_mask.get(name):
                kept[name] = delta[name]
        return kept

    def _sparsify(self, delta: Params) -> Params:
        aligned = getattr(self.trainer, "reserved_quant_rng", None)
        if self._topk_ratio is not None:
            sent, send_num = self._topk_sparsify(delta)
        elif aligned is not None:
            sent = self._aligned_dropout(delta, aligned)
            send_num = sum(int(v.size) for v in sent.values())
        else:
            sent = self._dropout.drop_parameters(delta)
            send_num = sum(int(v.size) for v in sent.values())
        get_logger().info("send_num %s", send_num)
        return sent
