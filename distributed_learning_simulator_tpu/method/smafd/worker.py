"""single_model_afd client: random whole-tensor dropout of the parameter
delta with error feedback (truncated deltas accumulate in the residual and
are retried next round).  Logs ``send_num`` the way the reference's analysis
cost model expects (``analysis/analyze_log.py:191-209``)."""

from typing import Any

from ...algorithm.random_dropout_algorithm import RandomDropoutAlgorithm
from ...ops.pytree import Params
from ...utils.logging import get_logger
from ...worker.error_feedback_worker import ErrorFeedbackWorker


class SingleModelAFDWorker(ErrorFeedbackWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._dropout = RandomDropoutAlgorithm(
            dropout_rate=self.config.algorithm_kwargs["dropout_rate"],
            seed=self.config.seed * 31 + self.worker_id,
        )

    def _sparsify(self, delta: Params) -> Params:
        sent = self._dropout.drop_parameters(delta)
        send_num = sum(int(v.size) for v in sent.values())
        get_logger().info("send_num %s", send_num)
        return sent
