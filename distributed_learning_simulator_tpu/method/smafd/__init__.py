"""single_model_afd: error-feedback sparsified (whole-tensor dropout) delta
uploads.

The reference ships configs (``conf/smafd/*.yaml``) and the building blocks
(``ErrorFeedbackWorker``, ``RandomDropoutAlgorithm``) but the registration
was removed from the snapshot (SURVEY.md §2.9); this build supplies the
method first-class.
"""

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...server.aggregation_server import AggregationServer
from ..algorithm_factory import CentralizedAlgorithmFactory
from .worker import SingleModelAFDWorker

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="single_model_afd",
    client_cls=SingleModelAFDWorker,
    server_cls=AggregationServer,
    algorithm_cls=FedAVGAlgorithm,
)
