"""FedPAQ = FedAvg over quantized transport (QSGD stochastic codec)
(reference ``simulation_lib/method/fed_paq/__init__.py:7-14``)."""

from ...algorithm.fed_avg_algorithm import FedAVGAlgorithm
from ...server.aggregation_server import AggregationServer
from ...topology.quantized_endpoint import (
    StochasticQuantClientEndpoint,
    StochasticQuantServerEndpoint,
)
from ...worker.aggregation_worker import AggregationWorker
from ..algorithm_factory import CentralizedAlgorithmFactory

CentralizedAlgorithmFactory.register_algorithm(
    algorithm_name="fed_paq",
    client_cls=AggregationWorker,
    server_cls=AggregationServer,
    algorithm_cls=FedAVGAlgorithm,
    client_endpoint_cls=StochasticQuantClientEndpoint,
    server_endpoint_cls=StochasticQuantServerEndpoint,
)
