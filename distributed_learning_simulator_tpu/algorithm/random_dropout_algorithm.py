"""Random whole-tensor dropout of a parameter dict.

TPU-native equivalent of
``simulation_lib/algorithm/random_dropout_algorithm.py:7-31``: randomly keep
whole tensors under a ``1 - dropout_rate`` byte budget (building block of the
``single_model_afd`` method family).
"""

import random

from ..ops.pytree import Params
from ..utils.logging import get_logger


class RandomDropoutAlgorithm:
    def __init__(self, dropout_rate: float, seed: int | None = None) -> None:
        self.dropout_rate = dropout_rate
        self._rng = random.Random(seed)

    def drop_parameters(self, parameter_dict: Params) -> Params:
        names = list(parameter_dict.keys())
        sizes = {k: int(parameter_dict[k].size) for k in names}
        total = sum(sizes.values())
        budget = total * (1.0 - self.dropout_rate)
        self._rng.shuffle(names)
        kept: Params = {}
        used = 0
        for name in names:
            if used + sizes[name] > budget and kept:
                continue
            kept[name] = parameter_dict[name]
            used += sizes[name]
        get_logger().debug(
            "random dropout kept %d/%d tensors (%.2f%% of bytes)",
            len(kept),
            len(names),
            100.0 * used / max(total, 1),
        )
        return kept
