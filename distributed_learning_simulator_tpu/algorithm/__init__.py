from .aggregation_algorithm import AggregationAlgorithm
from .fed_avg_algorithm import FedAVGAlgorithm
from .random_dropout_algorithm import RandomDropoutAlgorithm

__all__ = ["AggregationAlgorithm", "FedAVGAlgorithm", "RandomDropoutAlgorithm"]
