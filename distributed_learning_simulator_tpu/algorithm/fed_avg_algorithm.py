"""FedAvg weighted averaging.

TPU-native equivalent of ``simulation_lib/algorithm/fed_avg_algorithm.py:11-110``:
dataset-size-weighted average with a **streaming** accumulation mode that
frees each worker's tensors as they arrive to bound memory, per-name weight
accumulators (subclasses may return per-element weight arrays — see
``fed_dropout_avg``), and a batch fallback path.

The streaming hot path runs on the **ParamVec** representation
(``ops/pytree.py``): each upload is flattened and accumulated into one
contiguous float32 vector by a single donated jitted ``acc += w · vec`` —
one dispatch per upload, in-place buffer reuse — and finalize is one divide
plus one split back through the static layout.  Subclasses that override
the per-name weighting hooks (fed_dropout_avg's per-element weights) fall
back to the per-tensor walk; ``algorithm_kwargs.flat_aggregation: false``
forces the fallback.  Both accumulate in float32 with fixed arrival order
instead of the reference's CPU float64 walk (SURVEY.md §7 hard-part 3);
setting ``algorithm_kwargs.float64_parity: true`` switches to the native
host float64 accumulator (``native/fastops.cc``) for bit-level
reference-parity runs.
"""

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..message import Message, ParameterMessage
from ..ops import pytree
from ..ops.pytree import ParamVecLayout, Params
from ..utils.logging import get_logger
from .aggregation_algorithm import AggregationAlgorithm, check_finite


@jax.jit
def _acc_add(acc, term):
    return {k: acc[k] + term[k] for k in acc}


class FedAVGAlgorithm(AggregationAlgorithm):
    def __init__(self, server=None) -> None:
        super().__init__(server=server)
        self.accumulate: bool = True
        self._dtypes: dict[str, Any] = {}
        self._total_weights: dict[str, Any] = {}
        self._parameter: Params = {}
        # ParamVec streaming state (the flat hot path)
        self._vec_acc: jax.Array | None = None
        self._vec_layout: ParamVecLayout | None = None
        self._vec_total_weight: float = 0.0
        self._end_training = False
        self._other_data: dict = {}

    # subclass hooks (reference ``_get_weight`` / ``_apply_total_weight``)
    def _get_weight(self, dataset_size: int, name: str, parameter: Any) -> Any:
        assert dataset_size != 0
        return float(dataset_size)

    def _apply_total_weight(self, name: str, parameter, total_weight):
        return parameter / total_weight

    @property
    def _float64_parity(self) -> bool:
        # parity mode implements plain scalar-weighted FedAvg only; subclass
        # weighting/finalize hooks (e.g. fed_dropout_avg's per-element
        # weights) are bypassed by the native accumulator, so never engage
        # it for them
        if type(self) is not FedAVGAlgorithm:
            return False
        server = getattr(self, "_server", None)
        if server is None:
            return False
        return bool(server.config.algorithm_kwargs.get("float64_parity"))

    @property
    def _flat_path(self) -> bool:
        """Whether streaming accumulation rides the ParamVec hot path.

        The flat vector carries ONE scalar weight per upload and one divide
        at finalize, so any subclass that re-derives per-name (or
        per-element) weights keeps the per-tensor walk; so does the f64
        reference-parity mode and ``algorithm_kwargs.flat_aggregation:
        false`` (the A/B escape hatch the bench contract records)."""
        if type(self)._get_weight is not FedAVGAlgorithm._get_weight:
            return False
        if type(self)._apply_total_weight is not FedAVGAlgorithm._apply_total_weight:
            return False
        if self._float64_parity:
            return False
        server = getattr(self, "_server", None)
        config = getattr(server, "config", None) or self._config
        if config is not None and not config.algorithm_kwargs.get(
            "flat_aggregation", True
        ):
            return False
        return True

    def _process_worker_data_f64(self, data: ParameterMessage) -> None:
        """Reference-parity path: host float64 streaming accumulation
        (``simulation_lib/algorithm/fed_avg_algorithm.py:44``) via the
        native runtime."""
        import numpy as np

        from ..native import Float64Accumulator

        if not hasattr(self, "_f64_acc"):
            self._f64_acc = {}
        for name, value in data.parameter.items():
            self._dtypes[name] = value.dtype
            weight = self._get_weight(
                dataset_size=data.dataset_size, name=name, parameter=value
            )
            arr = np.asarray(value, np.float32)
            if name not in self._f64_acc:
                self._f64_acc[name] = (Float64Accumulator(arr.size), arr.shape)
            self._f64_acc[name][0].add(arr, float(weight))

    def process_worker_data(self, worker_id, worker_data, **kwargs) -> None:
        super().process_worker_data(worker_id, worker_data, **kwargs)
        if not self.accumulate:
            return
        data = self._all_worker_data.get(worker_id)
        if not isinstance(data, ParameterMessage):
            return
        if self._float64_parity:
            self._process_worker_data_f64(data)
            self._end_training |= data.end_training
            self._merge_other_data(data.other_data)
            data.parameter = {}
            return
        if self._flat_path:
            # ParamVec streaming: ONE fused dispatch per upload (donated
            # in-place accumulate), vs the per-tensor O(tensors) walk below
            weight = float(
                self._get_weight(
                    dataset_size=data.dataset_size, name="", parameter=None
                )
            )
            if self._vec_acc is None:
                self._vec_layout = ParamVecLayout.of(data.parameter)
                self._vec_acc = pytree.flat_weighted_vec(data.parameter, weight)
            else:
                assert self._vec_layout is not None
                assert self._vec_layout.matches(
                    data.parameter
                ), "inconsistent upload keys"
                self._vec_acc = pytree.flat_acc_add(
                    self._vec_acc, data.parameter, weight
                )
            self._vec_total_weight += weight
            self._end_training |= data.end_training
            self._merge_other_data(data.other_data)
            # release worker tensors immediately (reference bounds memory
            # the same way, fed_avg_algorithm.py:53-54)
            data.parameter = {}
            return
        terms = {}
        for name, value in data.parameter.items():
            self._dtypes[name] = value.dtype
            weight = self._get_weight(
                dataset_size=data.dataset_size, name=name, parameter=value
            )
            term = value.astype(jnp.float32) * weight
            terms[name] = term
            if name in self._total_weights:
                self._total_weights[name] = self._total_weights[name] + weight
            else:
                self._total_weights[name] = weight
        if not self._parameter:
            self._parameter = terms
        else:
            assert set(terms) == set(self._parameter), "inconsistent upload keys"
            self._parameter = _acc_add(self._parameter, terms)
        self._end_training |= data.end_training
        self._merge_other_data(data.other_data)
        # release worker tensors immediately (reference bounds memory the same
        # way, fed_avg_algorithm.py:53-54)
        data.parameter = {}

    def _merge_other_data(self, other_data: dict) -> None:
        for key, value in other_data.items():
            if key in self._other_data:
                if self._other_data[key] != value:
                    raise RuntimeError(f"different values on key {key}")
            else:
                self._other_data[key] = value

    def aggregate_worker_data(self) -> Message:
        if not self.accumulate:
            return self._aggregate_worker_data(self._all_worker_data)
        if getattr(self, "_f64_acc", None):
            import jax.numpy as _jnp

            parameter = {
                name: _jnp.asarray(acc.finalize().reshape(shape)).astype(
                    self._dtypes[name]
                )
                for name, (acc, shape) in self._f64_acc.items()
            }
            self._f64_acc = {}
            check_finite(parameter)
            return ParameterMessage(
                parameter=parameter,
                end_training=self._end_training,
                other_data=dict(self._other_data),
            )
        if self._vec_acc is not None:
            # ParamVec finalize: one divide, one finite check (a single
            # reduction), one split back through the static layout
            assert self._vec_layout is not None
            vec = pytree.flat_scale(self._vec_acc, self._vec_total_weight)
            self._vec_acc = None
            self._vec_total_weight = 0.0
            pytree.check_finite_vec(vec, self._vec_layout)
            parameter = pytree.split_flat_params(vec, self._vec_layout)
            return ParameterMessage(
                parameter=parameter,
                end_training=self._end_training,
                other_data=dict(self._other_data),
            )
        assert self._parameter, "no worker parameters to aggregate"
        parameter = self._parameter
        self._parameter = {}
        for name, value in parameter.items():
            averaged = self._apply_total_weight(
                name=name, parameter=value, total_weight=self._total_weights[name]
            )
            parameter[name] = averaged.astype(self._dtypes[name])
        check_finite(parameter)
        self._total_weights = {}
        return ParameterMessage(
            parameter=parameter,
            end_training=self._end_training,
            other_data=dict(self._other_data),
        )

    @classmethod
    def _aggregate_worker_data(cls, all_worker_data: dict) -> ParameterMessage:
        """Batch path (reference ``accumulate=False`` fallback)."""
        messages = {
            w: d for w, d in all_worker_data.items() if isinstance(d, ParameterMessage)
        }
        assert messages
        weights = AggregationAlgorithm.get_ratios(
            {w: d.dataset_size for w, d in messages.items()}
        )
        parameter = AggregationAlgorithm.weighted_avg(messages, weights)
        check_finite(parameter)
        other: dict = {}
        for d in messages.values():
            for k, v in d.other_data.items():
                if k in other and other[k] != v:
                    raise RuntimeError(f"different values on key {k}")
                other[k] = v
        return ParameterMessage(
            parameter=parameter,
            end_training=any(d.end_training for d in messages.values()),
            other_data=other,
        )

    def clear_worker_data(self) -> None:
        super().clear_worker_data()
        self._f64_acc = {}
        self._parameter = {}
        self._total_weights = {}
        self._dtypes = {}
        self._vec_acc = None
        self._vec_layout = None  # rebuilt on first upload (key sets may change)
        self._vec_total_weight = 0.0
        self._end_training = False
        self._other_data = {}
