"""Graph-FL server algorithm: training-node-index union + boundary-embedding
routing.

TPU-native equivalent of ``simulation_lib/algorithm/graph_algorithm.py:7-89``
(``GraphNodeEmbeddingPassingAlgorithm``): in-round messages are either (a)
per-worker training-node index sets — unioned and rebroadcast — or (b)
boundary-embedding exchanges — each worker provides embeddings for its nodes
and declares the node ids it needs; the server indexes all provided rows and
returns each worker its requested rows.  Parameter rounds fall through to
FedAvg.
"""

import numpy as np

from ..message import Message
from .fed_avg_algorithm import FedAVGAlgorithm


class GraphNodeEmbeddingPassingAlgorithm(FedAVGAlgorithm):
    def __init__(self, server=None) -> None:
        super().__init__(server=server)
        self.training_node_indices: dict[int, np.ndarray] = {}

    def aggregate_worker_data(self) -> Message:
        sample = next(iter(self._all_worker_data.values()), None)
        if isinstance(sample, Message) and "training_node_indices" in sample.other_data:
            return self._exchange_training_node_indices()
        if isinstance(sample, Message) and "node_embedding" in sample.other_data:
            return self._route_node_embeddings()
        return super().aggregate_worker_data()

    def _exchange_training_node_indices(self) -> Message:
        for worker_id, data in self._all_worker_data.items():
            self.training_node_indices[worker_id] = np.asarray(
                data.other_data["training_node_indices"]
            )
        merged = {w: idx.tolist() for w, idx in self.training_node_indices.items()}
        worker_result = {
            w: Message(in_round=True, other_data={"training_node_indices": merged})
            for w in self._all_worker_data
        }
        return Message(in_round=True, other_data={"worker_result": worker_result})

    def _route_node_embeddings(self) -> Message:
        # index all provided embeddings by global node id
        provided_rows = []
        provided_ids = []
        for data in self._all_worker_data.values():
            embedding = np.asarray(data.other_data["node_embedding"])
            node_ids = np.asarray(data.other_data["node_indices"])
            provided_rows.append(embedding)
            provided_ids.append(node_ids)
        all_rows = np.concatenate(provided_rows, axis=0)
        all_ids = np.concatenate(provided_ids, axis=0)
        id_to_row = {int(node): i for i, node in enumerate(all_ids)}

        worker_result = {}
        for worker_id, data in self._all_worker_data.items():
            wanted = np.asarray(data.other_data["boundary"])
            available = [int(n) for n in wanted if int(n) in id_to_row]
            rows = (
                all_rows[[id_to_row[n] for n in available]]
                if available
                else np.zeros((0, all_rows.shape[1]), all_rows.dtype)
            )
            worker_result[worker_id] = Message(
                in_round=True,
                other_data={
                    "node_embedding": rows,
                    "node_indices": np.asarray(available, dtype=np.int32),
                },
            )
        return Message(in_round=True, other_data={"worker_result": worker_result})
