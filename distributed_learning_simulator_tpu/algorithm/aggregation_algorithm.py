"""Server-side aggregation algorithm base.

TPU-native equivalent of
``simulation_lib/algorithm/aggregation_algorithm.py:9-96``: normalizes
incoming worker messages (restore deltas onto the old global params,
``complete()`` partial uploads), tracks skipped workers, and provides the
weighted-average primitives.  The math runs as jitted device programs over
jax arrays instead of CPU float64 tensor walks.
"""

from typing import Any

import jax.numpy as jnp

from ..message import DeltaParameterMessage, Message, ParameterMessage
from ..ops.pytree import Params
from ..utils.logging import get_logger


class AggregationAlgorithm:
    def __init__(self, server=None) -> None:
        self._server = server
        self._all_worker_data: dict[int, Message] = {}
        self._skipped_workers: set[int] = set()
        self._rejected_workers: set[int] = set()
        self._old_parameter_dict: Params | None = None
        self._config = None
        self._fault_plan = None

    def set_server(self, server) -> None:
        self._server = server

    def set_config(self, config) -> None:
        self._config = config
        from ..util.faults import FaultPlan

        self._fault_plan = (
            FaultPlan.from_config(config) if config is not None else None
        )

    @property
    def all_worker_data(self) -> dict[int, Message]:
        return self._all_worker_data

    @property
    def skipped_workers(self) -> set[int]:
        return self._skipped_workers

    @property
    def rejected_workers(self) -> set[int]:
        """Workers whose uploads the update guard rejected this round."""
        return self._rejected_workers

    @staticmethod
    def get_ratios(
        data_dict: dict[int, float | int], scale: float = 1.0
    ) -> dict[int, float]:
        """Dataset-size weights (reference ``get_ratios``)."""
        total = sum(data_dict.values())
        assert total > 0
        return {k: float(v) * scale / total for k, v in data_dict.items()}

    @staticmethod
    def weighted_avg(
        all_worker_data: dict[int, ParameterMessage],
        weights: dict[int, float],
        key: str = "parameter",
    ) -> Params:
        """Fixed-worker-order float32 weighted sum on the ParamVec batch
        path: the K selected uploads stack into ONE ``[K, D]`` matrix and
        aggregate with one jitted matvec (full-precision on TPU, Pallas
        fused accumulate for tile-sized models), then one split restores
        the param dict — one dispatch instead of O(workers × tensors).
        Beyond the ``FLAT_BATCH_MAX_ELEMENTS`` memory ceiling the stack
        degrades to K streaming donated adds (no ``[K, D]`` temporary).

        The reference accumulates in CPU float64
        (``fed_avg_algorithm.py:44``); float64 is emulated/slow on TPU, so we
        use a fixed summation order (sorted worker ids) in float32 — see
        SURVEY.md §7 hard-part 3.
        """
        from ..ops import pytree

        worker_ids = sorted(all_worker_data)
        assert worker_ids
        first = getattr(all_worker_data[worker_ids[0]], key)
        layout = pytree.ParamVecLayout.of(first)
        uploads = [getattr(all_worker_data[w], key) for w in worker_ids]
        assert all(layout.matches(u) for u in uploads), "inconsistent upload keys"
        w_list = [float(weights[w]) for w in worker_ids]
        return pytree.flat_weighted_avg_params(uploads, w_list, layout)

    def process_worker_data(
        self,
        worker_id: int,
        worker_data: Message | None,
        old_parameter_dict: Params | None = None,
        save_dir: str = "",
        **kwargs: Any,
    ) -> None:
        """Normalize one worker's upload (reference
        ``aggregation_algorithm.py:52-71``)."""
        if worker_data is None:
            self._skipped_workers.add(worker_id)
            get_logger().debug("worker %s skipped this round", worker_id)
            return
        if old_parameter_dict is not None:
            self._old_parameter_dict = old_parameter_dict
        match worker_data:
            case DeltaParameterMessage():
                assert self._old_parameter_dict is not None
                worker_data = worker_data.restore(self._old_parameter_dict)
            case ParameterMessage():
                if self._old_parameter_dict is not None:
                    worker_data.complete(self._old_parameter_dict)
            case Message():
                pass
        if isinstance(
            worker_data, ParameterMessage
        ) and not self._update_passes_guard(worker_id, worker_data):
            # update hygiene (fault_tolerance.update_guard): a non-finite
            # or norm-exploded upload is counted and demoted to a skipped
            # worker BEFORE any accumulation can see it — the round
            # renormalizes over the survivors (same semantics as the SPMD
            # sessions' in-program guard)
            self._rejected_workers.add(worker_id)
            self._skipped_workers.add(worker_id)
            return
        self._all_worker_data[worker_id] = worker_data

    def _update_passes_guard(
        self, worker_id: int, message: ParameterMessage
    ) -> bool:
        return update_passes_guard(
            self._fault_plan,
            worker_id,
            message.parameter,
            self._old_parameter_dict,
        )

    def aggregate_worker_data(self) -> Message:
        raise NotImplementedError

    def clear_worker_data(self) -> None:
        self._all_worker_data.clear()
        self._skipped_workers.clear()
        self._rejected_workers.clear()

    def exit(self) -> None:
        pass


def update_passes_guard(
    plan, worker_id: int, parameter: Params, old_params: Params | None
) -> bool:
    """THE server-side update-hygiene check (module-level so the buffered
    aggregation path can guard each flush item against its own ORIGIN
    base — a stale delta's norm is measured from the global it trained
    on, not the newest one): reject a non-finite upload, or one whose
    delta norm vs ``old_params`` exceeds ``plan.max_update_norm``."""
    if plan is None or not plan.update_guard:
        return True
    import numpy as np

    norm_sq = 0.0
    for name, value in parameter.items():
        arr = np.asarray(value, np.float32)
        if not np.all(np.isfinite(arr)):
            get_logger().warning(
                "update guard: worker %s upload %r is non-finite — "
                "rejected",
                worker_id,
                name,
            )
            return False
        if plan.max_update_norm > 0 and old_params:
            old = old_params.get(name)
            if old is not None:
                norm_sq += float(
                    np.sum(np.square(arr - np.asarray(old, np.float32)))
                )
    if plan.max_update_norm > 0 and norm_sq > plan.max_update_norm**2:
        get_logger().warning(
            "update guard: worker %s delta norm %.3e exceeds "
            "max_update_norm=%.3e — rejected",
            worker_id,
            norm_sq**0.5,
            plan.max_update_norm,
        )
        return False
    return True


def check_finite(params: Params) -> None:
    """NaN guard (reference asserts after aggregation,
    ``aggregation_algorithm.py:49``)."""
    for name, value in params.items():
        if not bool(jnp.all(jnp.isfinite(value))):
            raise FloatingPointError(f"non-finite aggregated parameter {name}")
