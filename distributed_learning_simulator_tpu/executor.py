"""Executor base for workers and the server.

TPU-native equivalent of ``simulation_lib/executor.py:16-96``.  The reference
needed a gevent semaphore per process plus a cross-process device lock to
time-share CUDA devices between greenlets; under single-controller JAX there
is one process and XLA serializes device work, so the execution context is
reduced to thread naming for log attribution and the save-dir convention.
"""

import copy
import os
import threading

from .config import DistributedTrainingConfig


class ExecutorContext:
    """Names the current thread for log attribution (reference
    ``ExecutorContext``, ``executor.py:16-38``; the semaphore is gone by
    design)."""

    def __init__(self, name: str) -> None:
        self._name = name

    def __enter__(self) -> "ExecutorContext":
        threading.current_thread().name = self._name
        return self

    def __exit__(self, *exc) -> None:
        threading.current_thread().name = "dls-idle"


class Executor:
    def __init__(
        self,
        config: DistributedTrainingConfig,
        name: str,
        task_context,
    ) -> None:
        self.config: DistributedTrainingConfig = copy.copy(config)
        self._name = name
        self._task_context = task_context

    @property
    def name(self) -> str:
        return self._name

    @property
    def save_dir(self) -> str:
        save_dir = os.path.join(self.config.save_dir, self._name.replace(" ", "_"))
        os.makedirs(save_dir, exist_ok=True)
        return save_dir

    def _get_execution_context(self) -> ExecutorContext:
        return ExecutorContext(self._name)

    def _raise_if_aborted(self) -> None:
        """One definition of the abort check used by every blocking loop."""
        if self._task_context is not None and self._task_context.aborted():
            from .ml_type import TaskAbortedError

            raise TaskAbortedError(self._name)

    def start(self) -> None:
        raise NotImplementedError
