"""Compressed-upload worker with error feedback.

TPU-native equivalent of
``simulation_lib/worker/error_feedback_worker.py:9-19``: keeps a residual
``_error`` parameter dict, ships ``sparsify(delta + error)`` and folds the
truncation error back into the residual.  Basis of the ``single_model_afd``
method family.  The residual is persisted per round
(``worker_N/error_feedback.npz``) and restored from
``algorithm_kwargs.resume_dir`` so a resumed run continues the exact
error-feedback dynamics (the reference keeps it in-memory only and loses
it on restart).
"""

import os
from typing import Any

import numpy as np

from ..message import DeltaParameterMessage, ParameterMessageBase
from ..ops.pytree import Params
from ..utils.logging import get_logger
from .aggregation_worker import AggregationWorker


class ErrorFeedbackWorker(AggregationWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        assert self._send_parameter_diff, "error feedback needs diff uploads"
        self._error: Params | None = None

    def _sparsify(self, delta: Params) -> Params:
        """Subclass hook: return the (sparse) payload actually sent."""
        raise NotImplementedError

    def _before_training(self) -> None:
        resume_dir = self.config.algorithm_kwargs.get("resume_dir")
        if resume_dir:
            path = os.path.join(
                str(resume_dir),
                os.path.basename(self.save_dir),
                "error_feedback.npz",
            )
            restored = self._load_residual(path, str(resume_dir))
            if restored is not None:
                self._error = restored
                get_logger().info(
                    "%s: restored error-feedback residual", self.name
                )
            else:
                get_logger().warning(
                    "%s: resume without a usable error_feedback.npz — "
                    "residual restarts at zero", self.name
                )
        super()._before_training()

    def _load_residual(self, path: str, resume_dir: str) -> Params | None:
        """Load a round-tagged residual, or None when missing/corrupt/stale.

        The residual written during a round the server never checkpointed
        is ahead of the restored params — reusing it would apply a
        mismatched correction, so a ``__round__`` tag greater than the
        server's resumable round is rejected.  An OLDER tag is fine: with
        client selection an unselected worker keeps (and does not rewrite)
        the residual from its last participating round, which is exactly
        the state an uninterrupted run would carry forward.
        """
        if not os.path.isfile(path):
            return None
        from ..util.resume import resumable_round

        try:
            with np.load(path) as blob:
                data = {k: blob[k] for k in blob.files}
        except Exception as exc:  # corrupt/truncated file
            get_logger().warning(
                "%s: error_feedback.npz unreadable (%s)", self.name, exc
            )
            return None
        tag = data.pop("__round__", None)
        server_round = resumable_round(resume_dir)
        if tag is None or int(tag) > server_round:
            get_logger().warning(
                "%s: residual round tag %s is ahead of resumable "
                "round %d", self.name, tag, server_round
            )
            return None
        return data

    def _get_sent_data(self) -> ParameterMessageBase:
        message = super()._get_sent_data()
        assert isinstance(message, DeltaParameterMessage)
        delta = message.delta_parameter
        if self._error is not None:
            delta = {k: v + self._error.get(k, 0.0) for k, v in delta.items()}
        sent = self._sparsify(delta)
        self._error = {k: delta[k] - sent.get(k, 0.0) for k in delta}
        final = os.path.join(self.save_dir, "error_feedback.npz")
        # .npz suffix keeps np.savez from appending one to the tmp name
        tmp = os.path.join(self.save_dir, "error_feedback.tmp.npz")
        np.savez(
            tmp,
            __round__=np.asarray(self._round_num),
            **{k: np.asarray(v) for k, v in self._error.items()},
        )
        os.replace(tmp, final)
        message.delta_parameter = sent
        return message
