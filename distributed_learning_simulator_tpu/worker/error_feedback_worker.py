"""Compressed-upload worker with error feedback.

TPU-native equivalent of
``simulation_lib/worker/error_feedback_worker.py:9-19``: keeps a residual
``_error`` parameter dict, ships ``sparsify(delta + error)`` and folds the
truncation error back into the residual.  Basis of the ``single_model_afd``
method family.  The residual is persisted per round
(``worker_N/error_feedback.npz``) and restored from
``algorithm_kwargs.resume_dir`` so a resumed run continues the exact
error-feedback dynamics (the reference keeps it in-memory only and loses
it on restart).
"""

import os
from typing import Any

import numpy as np

from ..message import DeltaParameterMessage, ParameterMessageBase
from ..ops.pytree import Params
from ..utils.logging import get_logger
from .aggregation_worker import AggregationWorker


class ErrorFeedbackWorker(AggregationWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        assert self._send_parameter_diff, "error feedback needs diff uploads"
        self._error: Params | None = None

    def _sparsify(self, delta: Params) -> Params:
        """Subclass hook: return the (sparse) payload actually sent."""
        raise NotImplementedError

    def _before_training(self) -> None:
        resume_dir = self.config.algorithm_kwargs.get("resume_dir")
        if resume_dir:
            path = os.path.join(
                str(resume_dir),
                os.path.basename(self.save_dir),
                "error_feedback.npz",
            )
            if os.path.isfile(path):
                with np.load(path) as blob:
                    self._error = {k: blob[k] for k in blob.files}
                get_logger().info(
                    "%s: restored error-feedback residual", self.name
                )
            else:
                get_logger().warning(
                    "%s: resume without error_feedback.npz — residual "
                    "restarts at zero", self.name
                )
        super()._before_training()

    def _get_sent_data(self) -> ParameterMessageBase:
        message = super()._get_sent_data()
        assert isinstance(message, DeltaParameterMessage)
        delta = message.delta_parameter
        if self._error is not None:
            delta = {k: v + self._error.get(k, 0.0) for k, v in delta.items()}
        sent = self._sparsify(delta)
        self._error = {k: delta[k] - sent.get(k, 0.0) for k in delta}
        np.savez(
            os.path.join(self.save_dir, "error_feedback.npz"),
            **{k: np.asarray(v) for k, v in self._error.items()},
        )
        message.delta_parameter = sent
        return message
