"""Compressed-upload worker with error feedback.

TPU-native equivalent of
``simulation_lib/worker/error_feedback_worker.py:9-19``: keeps a residual
``_error`` parameter dict, ships ``sparsify(delta + error)`` and folds the
truncation error back into the residual.  Basis of the ``single_model_afd``
method family.
"""

from typing import Any

from ..message import DeltaParameterMessage, ParameterMessageBase
from ..ops.pytree import Params
from .aggregation_worker import AggregationWorker


class ErrorFeedbackWorker(AggregationWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        assert self._send_parameter_diff, "error feedback needs diff uploads"
        self._error: Params | None = None

    def _sparsify(self, delta: Params) -> Params:
        """Subclass hook: return the (sparse) payload actually sent."""
        raise NotImplementedError

    def _get_sent_data(self) -> ParameterMessageBase:
        message = super()._get_sent_data()
        assert isinstance(message, DeltaParameterMessage)
        delta = message.delta_parameter
        if self._error is not None:
            delta = {k: v + self._error.get(k, 0.0) for k, v in delta.items()}
        sent = self._sparsify(delta)
        self._error = {k: delta[k] - sent.get(k, 0.0) for k in delta}
        message.delta_parameter = sent
        return message
