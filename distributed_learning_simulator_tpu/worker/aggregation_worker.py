"""The FedAvg client.

TPU-native equivalent of
``simulation_lib/worker/aggregation_worker.py:16-144``: registers an
aggregation hook at a configurable hook point (AFTER_EXECUTE by default),
sends parameter deltas (or full params / best-validation params), blocks for
the aggregated result, handles unselected-round ``None``s and
``end_training``, and mirrors the global model in a :class:`ModelCache`.
"""

import os
from typing import Any

import jax

from ..engine.batching import make_epoch_batches
from ..engine.engine import summarize_metrics
from ..message import (
    DeltaParameterMessage,
    Message,
    ParameterMessage,
    ParameterMessageBase,
)
from ..ml_type import ExecutorHookPoint, MachineLearningPhase, StopExecutingException
from ..util.model import load_parameters
from ..util.model_cache import ModelCache
from ..utils.logging import get_logger
from .client import Client


class KeepModelHook:
    """Keep the best params by validation accuracy across the round's epochs
    (reference ``cyy_torch_toolbox.hook.keep_model.KeepModelHook``)."""

    def __init__(self, trainer) -> None:
        self._trainer = trainer
        self.keep_best_model = True
        self.best_model: dict[str, Any] | None = None

    def __call__(self, executor, hook_point, **kwargs) -> None:
        trainer = executor
        dc = trainer.dataset_collection
        if not dc.has_dataset(MachineLearningPhase.Validation):
            return
        batches = trainer._epoch_batches(MachineLearningPhase.Validation, None)
        metrics = summarize_metrics(trainer.engine.evaluate(trainer.params, batches))
        if self.best_model is None or metrics["accuracy"] >= self.best_model["accuracy"]:
            self.best_model = {
                "parameter": dict(trainer.params),
                "accuracy": metrics["accuracy"],
            }

    def clear(self) -> None:
        self.best_model = None


class AggregationWorker(Client):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._aggregation_time: ExecutorHookPoint = ExecutorHookPoint.AFTER_EXECUTE
        self._reuse_learning_rate: bool = False
        self._choose_model_by_validation: bool = False
        self._send_parameter_diff: bool = True
        self._model_cache: ModelCache = ModelCache()
        self._keep_model_hook: KeepModelHook | None = None
        # deterministic chaos (util/faults.py): the threaded executor's
        # injection point is the upload boundary — the same seeded draws
        # the SPMD sessions fold into their weight rows
        from ..util.faults import FaultPlan

        self._fault_plan = FaultPlan.from_config(self.config)

    def _before_training(self) -> None:
        super()._before_training()
        dc = self.trainer.dataset_collection
        dc.remove_dataset(phase=MachineLearningPhase.Test)
        if self.config.dataset_sampling == "iid":
            self.enable_choose_model_by_validation()
        if not self._choose_model_by_validation:
            dc.remove_dataset(phase=MachineLearningPhase.Validation)
        if self.config.distribute_init_parameters:
            try:
                self._get_result_from_server()
            except StopExecutingException:
                return  # init carried end_training (resumed-complete run)
            if self._stopped():
                return
        self._register_aggregation()

    def _before_round(self) -> None:
        """fed_avg trains the SPMD executor's exact rng stream
        (``aligned_round_stream``), pinning cross-executor trajectory
        parity (VERDICT r3 item 4).  Other methods keep the legacy
        per-worker stream: their extra rng consumers sit in different
        places on the two executors (endpoint codecs vs in-program QSGD,
        per-step exchanges, OBD phase logic), so stream alignment alone
        cannot make them bit-comparable — see PARITY.md."""
        super()._before_round()
        if self.config.distributed_algorithm in (
            "fed_avg",
            "fed_paq",
            "fed_dropout_avg",
            "single_model_afd",
        ):
            # fed_paq = fed_avg + the stochastic codec and fed_dropout_avg
            # = fed_avg + per-element dropout; the aligned stream ALSO
            # reserves the quant/drop rng, which _aggregation hands to the
            # endpoint (fed_paq) or the worker draws directly
            # (fed_dropout_avg) so the wire transform matches the SPMD
            # program's
            from ..engine.executor import aligned_round_stream

            self.trainer.set_round_stream(
                aligned_round_stream(
                    self.config.seed, self._round_num, self.worker_id
                )
            )

    def _register_aggregation(self) -> None:
        self.trainer.remove_named_hook(name="aggregation")

        def aggregation_impl(**kwargs) -> None:
            self._aggregation(sent_data=self._get_sent_data(), **kwargs)

        self.trainer.append_named_hook(
            self._aggregation_time, "aggregation", aggregation_impl
        )

    def _inject_upload_faults(self, sent_data: Message) -> Message | None:
        """Apply the round's FaultPlan at the upload boundary: straggle
        (sleep), drop (upload becomes the server's ``None`` skipped-worker
        path — the client trained, the upload was lost), or corrupt
        (NaN-poison the payload; the server-side update guard must reject
        it).  Returns the message to send, or None for a dropout."""
        plan = self._fault_plan
        if plan is None or not plan.injection_active:
            return sent_data
        n = self.config.worker_number
        round_number = self._round_num
        plan.straggler_sleep(round_number, n, worker_id=self.worker_id)
        if self.worker_id in plan.dropped_clients(round_number, n):
            get_logger().warning(
                "fault plan: worker %s drops round %s upload",
                self.worker_id,
                round_number,
            )
            return None
        if self.worker_id in plan.corrupt_clients(round_number, n):
            get_logger().warning(
                "fault plan: worker %s corrupts round %s upload",
                self.worker_id,
                round_number,
            )
            match sent_data:
                case DeltaParameterMessage():
                    plan.poison_params(sent_data.delta_parameter)
                case ParameterMessage():
                    plan.poison_params(sent_data.parameter)
        return sent_data

    def _aggregation(self, sent_data: Message, **kwargs: Any) -> None:
        sent_data = self._inject_upload_faults(sent_data)
        if sent_data is None:  # injected dropout: lost upload, stay in sync
            self.send_data_to_server(None)
            self._get_result_from_server()
            return
        quant_key = getattr(self.trainer, "reserved_quant_rng", None)
        if quant_key is not None and hasattr(self._endpoint, "set_quant_key"):
            # codec parity with the SPMD in-program path (fed_paq /
            # fed_obd_sq): the endpoint's next encode draws the reserved
            # per-round key; a worker that quantizes a SUBSET of leaves
            # also provides the global fold-index map
            self._endpoint.set_quant_key(
                quant_key,
                fold_indices=getattr(self, "_quant_fold_indices", None),
            )
        self.send_data_to_server(sent_data)
        self._offload_from_device()
        self._get_result_from_server()

    def enable_choose_model_by_validation(self) -> None:
        dc = self.trainer.dataset_collection
        if (
            not dc.has_dataset(MachineLearningPhase.Validation)
            or dc.dataset_size(MachineLearningPhase.Validation) == 0
        ):
            # small splits can leave a worker with no validation samples
            return
        self._choose_model_by_validation = True
        if self._keep_model_hook is None:
            self._keep_model_hook = KeepModelHook(self.trainer)
            self.trainer.append_named_hook(
                ExecutorHookPoint.AFTER_EPOCH, "keep_model_hook", self._keep_model_hook
            )

    def disable_choose_model_by_validation(self) -> None:
        self._choose_model_by_validation = False
        if self._keep_model_hook is not None:
            self.trainer.remove_named_hook("keep_model_hook")
            self._keep_model_hook = None

    @property
    def best_model_hook(self) -> KeepModelHook | None:
        return self._keep_model_hook

    def _get_sent_data(self) -> ParameterMessageBase:
        if self._choose_model_by_validation and (
            self._keep_model_hook is not None
            and self._keep_model_hook.best_model is not None
        ):
            parameter = self._keep_model_hook.best_model["parameter"]
        else:
            parameter = self.trainer.get_parameter_dict()
        if self._send_parameter_diff:
            return DeltaParameterMessage(
                dataset_size=self.trainer.dataset_size,
                delta_parameter=self._model_cache.get_parameter_diff(parameter),
            )
        return ParameterMessage(
            dataset_size=self.trainer.dataset_size, parameter=parameter
        )

    def _load_result_from_server(self, result: Message) -> None:
        if result.end_training:
            self._force_stop = True
            raise StopExecutingException()
        if getattr(result, "is_initial", False) and "round" in result.other_data:
            # server resumed a previous session: jump to its round
            self._round_num = result.other_data["round"]
        model_path = os.path.join(
            self.config.save_dir, "aggregated_model", f"round_{self._round_num}.npz"
        )
        match result:
            case ParameterMessage():
                self._model_cache.cache_parameter_dict(result.parameter, path=model_path)
            case DeltaParameterMessage():
                self._model_cache.add_parameter_diff(
                    result.delta_parameter, path=model_path
                )
            case _:
                raise NotImplementedError(type(result))
        load_parameters(
            trainer=self.trainer,
            parameter_dict=self._model_cache.parameter_dict,
            reuse_learning_rate=self._reuse_learning_rate,
        )

    def _offload_from_device(self) -> None:
        if self.config.limited_resource:
            self._model_cache.save()
        if self._keep_model_hook is not None:
            self._keep_model_hook.clear()
        super()._offload_from_device()

    def _get_result_from_server(self) -> None:
        """Blocking receive; a ``None`` means unselected this round — skip,
        advance the round, ack with ``None``, and wait again (reference
        ``aggregation_worker.py:128-144``)."""
        while True:
            result = self._get_data_from_server()
            if result is None:
                get_logger().debug("%s skips round %s", self.name, self._round_num)
                self._round_num += 1
                self.send_data_to_server(None)
                if self._stopped():
                    return
                continue
            self._load_result_from_server(result=result)
            break
