"""Federated GNN worker (subgraph-per-client).

TPU-native equivalent of ``simulation_lib/worker/graph_worker.py:18-406``:

* before training, exchanges training-node indices through the server
  (``__exchange_training_node_indices``, reference ``graph_worker.py:68-84``);
* prunes edges to in-client edges + cross-client *training* edges with
  optional ``edge_drop_rate`` (reference ``graph_worker.py:197-241``) —
  pruning here is an **edge mask**, not an edge-list rebuild, so the XLA
  program keeps static shapes;
* with ``share_feature``, every training step performs a synchronous
  boundary-embedding exchange through the server before EVERY
  message-passing layer after the first (reference installs
  forward-pre-hooks on each ``MessagePassing`` module with index > 0,
  ``graph_worker.py:344-373``; here the model's ``mp_stage`` API is called
  explicitly per layer and received rows enter as constants —
  ``stop_gradient`` — matching the reference's detached pipe tensors);
* tracks communicated/skipped bytes and edge/node counts, dumped to
  ``graph_worker_stat.json`` (reference ``graph_worker.py:391-406``).
"""

import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..message import Message
from ..ml_type import (
    ExecutorHookPoint,
    MachineLearningPhase,
    StopExecutingException,
)
from ..ops.pytree import param_nbytes, unflatten_nested
from ..utils.logging import get_logger
from .aggregation_worker import AggregationWorker


class GraphWorker(AggregationWorker):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._share_feature = self.config.algorithm_kwargs.get("share_feature", True)
        self._edge_drop_rate = self.config.algorithm_kwargs.get("edge_drop_rate", 0.0)
        self._send_parameter_diff = False
        self._other_training_node_indices: set[int] = set()
        self._own_nodes: np.ndarray | None = None
        self._boundary: np.ndarray = np.zeros(0, np.int32)
        self._provide_nodes: np.ndarray = np.zeros(0, np.int32)
        # edge masks (static global edge list)
        self._local_edge_mask: np.ndarray | None = None  # layer-0: in-client only
        self._cross_edge_mask: np.ndarray | None = None  # + cross training edges
        self.communicated_bytes = 0
        self.skipped_bytes = 0
        self.exchange_count = 0
        # fed_aas handles num_neighbor itself (per-round resampling); the
        # stock GraphWorker forwards it to the dataloader like the reference
        self._dataloader_num_neighbor = True

    # ------------------------------------------------------------- setup
    def _before_training(self) -> None:
        dc = self.trainer.dataset_collection
        dc.remove_dataset(phase=MachineLearningPhase.Test)
        dc.remove_dataset(phase=MachineLearningPhase.Validation)
        if self.config.distribute_init_parameters:
            try:
                self._get_result_from_server()
            except StopExecutingException:
                return  # init carried end_training (resumed-complete run)
            if self._stopped():
                return
        self._exchange_training_node_indices()
        self._prune_edges()
        # reference graph_worker.py:94-101: batch_number / num_neighbor are
        # dataloader kwargs — each epoch trains `batch_number` shuffled
        # training-node minibatches with optional fan-in sampling
        if "batch_number" in self.config.algorithm_kwargs:
            self.trainer.update_dataloader_kwargs(
                batch_number=int(self.config.algorithm_kwargs["batch_number"])
            )
        if (
            self._dataloader_num_neighbor
            and "num_neighbor" in self.config.algorithm_kwargs
        ):
            self.trainer.update_dataloader_kwargs(
                num_neighbor=int(self.config.algorithm_kwargs["num_neighbor"])
            )
        if self._share_feature:
            self.trainer.append_named_hook(
                ExecutorHookPoint.OPTIMIZER_STEP,
                "shared_feature_step",
                self._shared_feature_step,
            )
        self._register_aggregation()

    @property
    def training_dataset(self):
        return self.trainer.dataset_collection.get_dataset(MachineLearningPhase.Training)

    def _exchange_training_node_indices(self) -> None:
        graph = self.training_dataset.inputs
        own_training = np.nonzero(graph["mask"])[0].astype(np.int32)
        message = Message(
            in_round=True,
            other_data={"training_node_indices": own_training.tolist()},
        )
        self.send_data_to_server(message)
        result = self._get_data_from_server()
        merged = result.other_data["training_node_indices"]
        self._own_nodes = own_training
        others: set[int] = set()
        for worker_id, indices in merged.items():
            if int(worker_id) != self.worker_id:
                others.update(int(i) for i in indices)
        # disjointness assert (reference graph_worker.py:81-84)
        assert not others.intersection(own_training.tolist())
        self._other_training_node_indices = others

    def _prune_edges(self) -> None:
        graph = self.training_dataset.inputs
        edge_index = graph["edge_index"]
        src, dst = edge_index[0], edge_index[1]
        own = np.zeros(len(self.training_dataset.targets), bool)
        own[self._own_nodes] = True
        other_training = np.zeros_like(own)
        other_training[list(self._other_training_node_indices)] = True

        in_client = own[src] & own[dst]
        cross = (own[src] & other_training[dst]) | (other_training[src] & own[dst])
        if self._edge_drop_rate > 0:
            rng = np.random.default_rng(self.config.seed * 131 + self.worker_id)
            cross &= rng.random(cross.shape) >= self._edge_drop_rate
        self._local_edge_mask = in_client.astype(np.float32)
        self._cross_edge_mask = (in_client | cross).astype(np.float32)
        # boundary = other clients' training nodes I still have edges to
        cross_src = np.unique(
            np.concatenate(
                [src[cross & other_training[src]], dst[cross & other_training[dst]]]
            )
        )
        self._boundary = cross_src.astype(np.int32)
        # nodes whose embeddings I provide: my training nodes on kept cross edges
        provide = np.unique(
            np.concatenate([src[cross & own[src]], dst[cross & own[dst]]])
        )
        self._provide_nodes = provide.astype(np.int32)
        # default mask used by the trainer's standard (non-exchange) path
        graph["edge_mask"] = (
            self._cross_edge_mask if self._share_feature else self._local_edge_mask
        )
        get_logger().info(
            "%s: %d in-client edges, %d cross edges kept, boundary %d nodes",
            self.name,
            int(in_client.sum()),
            int(cross.sum() if isinstance(cross, np.ndarray) else 0),
            len(self._boundary),
        )

    # ----------------------------------------------------- per-step exchange
    def _exchange_boundary_rows(self, h) -> tuple[jnp.ndarray, jnp.ndarray]:
        """One synchronous through-server boundary-embedding exchange (the
        reference's ``_pass_node_feature`` barrier) for the current layer
        activations ``h``.  Returns ``(h_received, received_mask)``, both
        detached (the reference's pipe tensors carry no grad)."""
        payload = {
            "node_embedding": np.asarray(h[self._provide_nodes]),
            "node_indices": self._provide_nodes,
            "boundary": self._boundary,
        }
        message = Message(in_round=True, other_data=payload)
        self.exchange_count += 1
        self.communicated_bytes += param_nbytes(payload)
        self.send_data_to_server(message)
        result = self._get_data_from_server()
        received = np.asarray(result.other_data["node_embedding"])
        received_ids = np.asarray(result.other_data["node_indices"], dtype=np.int32)
        self.communicated_bytes += received.nbytes

        h_received = jnp.zeros(h.shape, h.dtype)
        received_mask = jnp.zeros((h.shape[0], 1), h.dtype)
        if len(received_ids):
            h_received = h_received.at[received_ids].set(jnp.asarray(received))
            received_mask = received_mask.at[received_ids].set(1.0)
        return (
            jax.lax.stop_gradient(h_received),
            jax.lax.stop_gradient(received_mask),
        )

    def _shared_feature_step(self, executor, batch, step_rng, **kwargs) -> None:
        """One optimizer step with a boundary exchange before EVERY
        message-passing layer after the first (reference installs a
        forward-pre-hook on each ``MessagePassing`` module with index > 0,
        ``graph_worker.py:344-373``) — ``num_mp_layers - 1`` synchronous
        barriers per step, not one."""
        trainer = executor
        params = trainer.params
        model = trainer.model_ctx.module
        num_layers = int(getattr(model, "num_mp_layers", 2))
        variables = {"params": unflatten_nested(params)}
        # per-minibatch edge mask (fan-in sampled when num_neighbor is set);
        # local ⊆ cross, so intersecting with the batch mask caps both
        batch_edge = batch["input"].get("edge_mask")
        local_mask = jnp.asarray(self._local_edge_mask)
        cross_mask = jnp.asarray(self._cross_edge_mask)
        if batch_edge is not None:
            batch_edge = jnp.asarray(batch_edge)
            local_mask = local_mask * batch_edge
            cross_mask = cross_mask * batch_edge
        inputs_local = dict(batch["input"])
        inputs_local["edge_mask"] = local_mask
        inputs_cross = dict(batch["input"])
        inputs_cross["edge_mask"] = cross_mask

        from ..models.graph import apply_mp_stage

        def stage(vs, i, h, inputs, train, rng=None):
            return apply_mp_stage(model, vs, i, h, inputs, train, rng)

        # payload forward (eval mode): exchange at each layer boundary,
        # collecting the received rows to replay inside the grad pass
        received_per_layer: list[tuple[jnp.ndarray, jnp.ndarray]] = []
        h = stage(variables, 0, None, inputs_local, False)
        for i in range(1, num_layers):
            h_received, received_mask = self._exchange_boundary_rows(h)
            received_per_layer.append((h_received, received_mask))
            if i < num_layers - 1:  # the final stage's output feeds no exchange
                h = h * (1.0 - received_mask) + h_received * received_mask
                h = stage(variables, i, h, inputs_cross, False)

        def loss_fn(p):
            vs = {"params": unflatten_nested(p)}
            h = stage(vs, 0, None, inputs_local, True, step_rng)
            for i in range(1, num_layers):
                h_received, received_mask = received_per_layer[i - 1]
                h = h * (1.0 - received_mask) + h_received * received_mask
                h = stage(vs, i, h, inputs_cross, True, step_rng)
            from ..models.registry import masked_ce_loss

            loss, aux = masked_ce_loss(h, batch["target"], batch["mask"])
            return loss, aux

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = trainer.engine.optimizer.update(
            grads, trainer.opt_state, params
        )
        import optax

        new_params = optax.apply_updates(params, updates)
        trainer._params = new_params
        trainer._opt_state = opt_state

    # ------------------------------------------------------------ artifacts
    def _after_training(self) -> None:
        super()._after_training()
        stat = {
            "communicated_bytes": int(self.communicated_bytes),
            "skipped_bytes": int(self.skipped_bytes),
            "exchange_count": int(self.exchange_count),
            "boundary_size": int(len(self._boundary)),
            "edge_count": int(
                self._cross_edge_mask.sum() if self._cross_edge_mask is not None else 0
            ),
            "node_count": int(len(self._own_nodes) if self._own_nodes is not None else 0),
        }
        with open(
            os.path.join(self.save_dir, "graph_worker_stat.json"), "wt", encoding="utf8"
        ) as f:
            json.dump(stat, f)

    def _get_sent_data(self):
        data = super()._get_sent_data()
        return data
