"""Per-optimizer-step distributed SGD worker.

TPU-native equivalent of ``simulation_lib/worker/gradient_worker.py:13-131``:
hooks OPTIMIZER_STEP, ships the raw (weight-decayed) gradient as one flat
vector through ``_process_gradient`` (identity here; ``sign`` in the sign-SGD
subclass), blocks for the aggregated gradient, then applies the
momentum/nesterov SGD update manually.  Requires the SGD optimizer.

On a real mesh the sign-SGD method family replaces this host round-trip with
an in-program ``psum`` (see ``parallel/``); this class is the
simulation-faithful path.
"""

import json
import os
from typing import Any

import jax
import jax.numpy as jnp

from ..engine.engine import summarize_metrics
from ..message import Message
from ..ml_type import ExecutorHookPoint
from ..ops.pytree import cat_params_to_vector, params_from_vector_like
from ..utils.logging import get_logger
from .client import Client


class GradientWorker(Client):
    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        assert self.config.optimizer_name.lower() == "sgd"
        self._momentum_buffer: jax.Array | None = None
        self._step_count = 0
        self._epoch_stat: dict[int, dict] = {}

    def _before_training(self) -> None:
        super()._before_training()
        from ..ml_type import MachineLearningPhase

        dc = self.trainer.dataset_collection
        dc.remove_dataset(phase=MachineLearningPhase.Test)
        dc.remove_dataset(phase=MachineLearningPhase.Validation)
        # per-step gradient exchange requires every replica to start from the
        # same parameters: use the task-level seed, not the per-worker seed
        self.trainer.load_parameter_dict(
            self.trainer.engine.init_params(self.config.seed), reuse_learning_rate=False
        )
        self.trainer.append_named_hook(
            ExecutorHookPoint.OPTIMIZER_STEP, "gradient_exchange", self.__step
        )
        self.trainer.append_named_hook(
            ExecutorHookPoint.AFTER_EPOCH, "record_epoch", self.__record
        )
        self.trainer.append_named_hook(
            ExecutorHookPoint.AFTER_EXECUTE, "end_training", self.__send_end
        )

    # subclass hook (sign() in sign-SGD)
    def _process_gradient(self, gradient: jax.Array) -> jax.Array:
        return gradient

    def __step(self, executor, batch, step_rng, **kwargs) -> None:
        trainer = executor
        params = trainer.params
        (loss, aux), grads = trainer.engine.loss_and_grad(params, batch, step_rng)
        if self.config.weight_decay:
            grads = {
                k: g + self.config.weight_decay * params[k] for k, g in grads.items()
            }
        vector = cat_params_to_vector(grads)
        vector = self._process_gradient(vector)
        self.send_data_to_server(
            Message(
                in_round=True,
                other_data={
                    "dataset_size": trainer.dataset_size,
                    "gradient": vector,
                },
            )
        )
        result = self._get_data_from_server()
        assert isinstance(result, Message)
        aggregated = result.other_data["gradient"]
        params_new, self._momentum_buffer = _sgd_update(
            params,
            aggregated,
            self._momentum_buffer,
            lr=float(self.trainer.engine.schedule(self._step_count)),
            momentum=self.config.momentum,
        )
        trainer.load_parameter_dict(params_new, reuse_learning_rate=True)
        self._step_count += 1

    def __record(self, executor, epoch, epoch_metrics, **kwargs) -> None:
        self._epoch_stat[epoch] = {
            "loss": epoch_metrics["loss"],
            "accuracy": epoch_metrics["accuracy"],
        }
        with open(
            os.path.join(self.save_dir, "epoch_stat.json"), "wt", encoding="utf8"
        ) as f:
            json.dump(self._epoch_stat, f)

    def __send_end(self, **kwargs) -> None:
        from ..message import ParameterMessage

        # final params ride along so the server can record the run's test
        # metric (replicas are identical under lockstep updates)
        self.send_data_to_server(
            ParameterMessage(
                end_training=True,
                parameter=self.trainer.get_parameter_dict(),
                dataset_size=self.trainer.dataset_size,
            )
        )
        get_logger().debug("%s sent end_training", self.name)


def _sgd_update(params, aggregated_vector, momentum_buffer, lr: float, momentum: float):
    if momentum_buffer is None:
        momentum_buffer = jnp.zeros_like(aggregated_vector)
    momentum_buffer = momentum * momentum_buffer + aggregated_vector
    delta = params_from_vector_like(momentum_buffer * lr, params)
    new_params = {k: params[k] - delta[k] for k in params}
    return new_params, momentum_buffer
