"""Client: a worker wired to the central topology.

TPU-native equivalent of ``simulation_lib/worker/client.py:9-22``.  The
reference polls ``endpoint.has_data()`` at 0.1 s under gevent while holding
back the device lock; here the endpoint is a thread-safe queue, so a blocking
``get`` with a stop-check timeout replaces the poll loop.
"""

from typing import Any

from .worker import Worker


class Client(Worker):
    def send_data_to_server(self, data: Any) -> None:
        self._endpoint.send(data)

    def _get_data_from_server(self) -> Any:
        import queue

        # while blocked on the server, hand the training slot to a peer
        # (reference: the device lock is released during the poll loop,
        # ``worker/client.py:13-22``) — with parallel_number < worker_number
        # the server's all-N barrier would otherwise deadlock
        owed_slot = self._holds_slot or self._slot_deferred
        if self._holds_slot:
            self._release_slot()
        while True:
            self._raise_if_aborted()
            try:
                result = self._endpoint.get(timeout=0.5)
                break
            except queue.Empty:
                continue
        if owed_slot:
            if result is None:
                # unselected this round: the None ack needs no compute —
                # stay slotless and re-acquire when real work arrives
                self._slot_deferred = True
            else:
                self._slot_deferred = False
                self._acquire_slot()
        return result
