"""Client: a worker wired to the central topology.

TPU-native equivalent of ``simulation_lib/worker/client.py:9-22``.  The
reference polls ``endpoint.has_data()`` at 0.1 s under gevent while holding
back the device lock; here the endpoint is a thread-safe queue, so a blocking
``get`` with a stop-check timeout replaces the poll loop.
"""

from typing import Any

from .worker import Worker


class Client(Worker):
    def send_data_to_server(self, data: Any) -> None:
        self._endpoint.send(data)

    def _get_data_from_server(self) -> Any:
        import queue

        while True:
            if self._task_context is not None and self._task_context.aborted():
                from ..ml_type import TaskAbortedError

                raise TaskAbortedError(self.name)
            try:
                return self._endpoint.get(timeout=0.5)
            except queue.Empty:
                continue
