from .worker import Worker
from .client import Client
from .aggregation_worker import AggregationWorker
from .error_feedback_worker import ErrorFeedbackWorker
from .gradient_worker import GradientWorker

__all__ = [
    "Worker",
    "Client",
    "AggregationWorker",
    "ErrorFeedbackWorker",
    "GradientWorker",
]
