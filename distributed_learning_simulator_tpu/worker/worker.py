"""Worker base: the per-client round loop.

TPU-native equivalent of ``simulation_lib/worker/worker.py:15-95``.  A worker
runs ``trainer.train()`` once per round until ``_round_num > config.round``
or a force-stop; subclass hooks fire through the trainer's hook points.
Device locks/gevent context of the reference are unnecessary here (one
process, XLA owns the device); workers run as host threads that block on
their endpoint.
"""

import json
import os
from functools import cached_property
from typing import Any

from ..engine.executor import Trainer
from ..executor import Executor
from ..ml_type import MachineLearningPhase
from ..practitioner import Practitioner
from ..utils.logging import get_logger


class Worker(Executor):
    def __init__(
        self,
        task_id: int | None,
        endpoint,
        practitioner: Practitioner,
        config=None,
        task_context=None,
        **kwargs: Any,
    ) -> None:
        worker_id = practitioner.worker_id
        name = f"worker {worker_id}"
        if task_id is not None:
            name = f"worker {worker_id} of {task_id}"
        super().__init__(config=config, name=name, task_context=task_context)
        self._practitioner = practitioner
        self._endpoint = endpoint
        self._round_num = 0
        self._force_stop = False
        self._holds_slot = False
        self._slot_deferred = False  # slot owed after an unselected round

    @property
    def worker_id(self) -> int:
        return self._practitioner.worker_id

    @cached_property
    def trainer(self) -> Trainer:
        dataset_collection = self._practitioner.create_dataset_collection(self.config)
        trainer = Trainer(
            self.config,
            dataset_collection,
            self._task_context.model_ctx,
            self._task_context.engine,
            seed=self.config.seed + self.worker_id + 1,
            name=self.name,
        )
        trainer.batch_loss_log_enabled = False  # reference disables batch_loss_logger
        return trainer

    def _offload_from_device(self) -> None:
        pass

    def _before_round(self) -> None:
        """Per-round hook (runs before each round's local training; no
        reference counterpart — subclasses use it for round-scoped state
        such as neighbor resampling in ``fed_aas``)."""

    def _before_training(self) -> None:
        pass

    def _after_training(self) -> None:
        # reference dumps hyper_parameter.pk via dill (worker.py:51-55);
        # we write a portable json
        import dataclasses

        hp = self.trainer.hyper_parameter
        with open(
            os.path.join(self.save_dir, "hyper_parameter.json"), "wt", encoding="utf8"
        ) as f:
            json.dump(dataclasses.asdict(hp), f)
        if self.config.save_performance_metric:
            # per-epoch metrics consumed by analysis/analyze_round.py
            # (reference: toolbox visualizer's performance_metric.json)
            with open(
                os.path.join(self.save_dir, "performance_metric.json"),
                "wt",
                encoding="utf8",
            ) as f:
                json.dump(self.trainer.performance_metric.epoch_metrics, f)

    def _stopped(self) -> bool:
        return self._round_num > self.config.round or self._force_stop

    # ---- train-slot bounding (reference ``parallel_number``) ----
    # The reference round-robins workers into ``parallel_number`` processes
    # and serializes within each (``algorithm_factory.py:38-58``); the
    # analogue here is a semaphore of ``parallel_number`` concurrent local
    # training loops, released while a worker blocks on the server (the
    # reference's Client releases its device lock the same way,
    # ``worker/client.py:13-22``).  0 = unbounded.
    def _train_slots(self):
        return getattr(self._task_context, "train_slots", None)

    def _acquire_slot(self) -> None:
        slots = self._train_slots()
        if slots is None or self._holds_slot:
            return
        while not slots.acquire(timeout=0.5):
            self._raise_if_aborted()
        self._holds_slot = True

    def _release_slot(self) -> None:
        slots = self._train_slots()
        if slots is not None and self._holds_slot:
            self._holds_slot = False
            slots.release()

    def start(self, **kwargs: Any) -> None:
        first_training = True
        self._round_num = 1
        self._force_stop = False
        with self._get_execution_context():
            try:
                while not self._stopped():
                    if first_training:
                        self._before_training()
                        first_training = False
                        if self._stopped():
                            break
                    self.trainer.set_visualizer_prefix(f"round: {self._round_num},")
                    self._before_round()
                    self._acquire_slot()
                    self.trainer.train(**kwargs)
                    self._round_num += 1
            finally:
                self._release_slot()
            get_logger().debug("finish %s", self.name)
            self._endpoint.close()
            self._after_training()
