"""Training orchestration.

TPU-native equivalent of ``simulation_lib/training.py:82-169`` +
``simulation_lib/algorithm_factory.py:12-61``.  The reference spawns one OS
process per worker group and a server process wired by multiprocessing
pipes; here the whole task is a **single-controller** program: one shared
:class:`ComputeEngine` (one set of compiled XLA executables for all
clients), the server and workers as host threads exchanging device-resident
payloads through in-memory endpoints.  Concurrent tasks keep the reference's
``task_id`` / ``get_training_result`` API.
"""

import copy
import dataclasses
import math
import os
import threading
import uuid
from typing import Any

from .config import DistributedTrainingConfig
from .data import DatasetCollection, create_dataset_collection
from .engine.engine import ComputeEngine
from .engine.hyper_parameter import HyperParameter
from .method.algorithm_factory import CentralizedAlgorithmFactory
from .ml_type import TaskAbortedError
from .models import ModelContext, create_model_context
from .practitioner import Practitioner
from .topology.central_topology import CentralTopology
from .utils.logging import add_file_handler, get_logger
from .utils.timer import TimeCounter


@dataclasses.dataclass
class TaskContext:
    """Shared, read-only task state: one engine/model/dataset for all
    executors (the reference rebuilt these per process)."""

    config: DistributedTrainingConfig
    dataset_collection: DatasetCollection
    model_ctx: ModelContext
    engine: ComputeEngine
    topology: CentralTopology
    task_id: Any
    abort_event: threading.Event = dataclasses.field(default_factory=threading.Event)
    threads: list = dataclasses.field(default_factory=list)
    errors: list = dataclasses.field(default_factory=list)
    # workers permanently demoted to dropouts (crashed threads, watchdog-
    # demoted stragglers) under fault_tolerance.client_faults_nonfatal —
    # the server's event loop synthesizes their per-round Nones
    dropped_workers: set = dataclasses.field(default_factory=set)
    server: Any = None
    workers: list = dataclasses.field(default_factory=list)
    practitioners: list = dataclasses.field(default_factory=list)
    timer: TimeCounter = dataclasses.field(default_factory=TimeCounter)
    spmd_result: Any = None  # set by the SPMD session thread (task mode)
    # reference parallel_number: at most this many concurrent local
    # training loops on the threaded executor (None = unbounded)
    train_slots: Any = None

    def aborted(self) -> bool:
        return self.abort_event.is_set()


tasks: dict[Any, TaskContext] = {}
_tasks_lock = threading.Lock()


def _build_task(
    config: DistributedTrainingConfig,
    practitioners=None,
    task_id=None,
) -> TaskContext:
    from .utils.compilation_cache import enable_persistent_cache

    enable_persistent_cache()
    config = copy.deepcopy(config)
    if not config.save_dir:
        config.load_config_and_process()
    if config.log_file:
        add_file_handler(config.log_file)
    algorithm = config.distributed_algorithm
    assert CentralizedAlgorithmFactory.has_algorithm(
        algorithm
    ), f"unknown distributed algorithm {algorithm}"

    if practitioners is None:
        practitioners = config.create_practitioners()
    else:
        for worker_id, practitioner in enumerate(
            sorted(practitioners, key=lambda p: p.practitioner_id)
        ):
            assert practitioner.has_dataset(config.dataset_name)
            practitioner.set_worker_id(worker_id)
    practitioners = sorted(practitioners, key=lambda p: p.worker_id)
    assert len(practitioners) == config.worker_number

    dataset_collection = create_dataset_collection(config)
    model_kwargs = dict(config.model_kwargs)
    # ``model_kwargs.sequence_parallel: N`` — shard the model's sequence
    # axis over an ("sp",) mesh of N devices (ring/Ulysses attention,
    # ``parallel/ring_attention.py``).  Meshes can't ride YAML, so the
    # config carries the axis SIZE and the mesh is built here; the model
    # factory receives it as ``sp_mesh`` (``models/long_context.py``).
    # ``model_kwargs.expert_parallel: N`` — shard an MoE model's expert
    # axis over an ("ep",) mesh.  The SPMD session owns the mesh and the
    # ep-mode twin (parallel/spmd_ep.py); the task's model_ctx stays
    # unsharded for central evaluation.
    model_kwargs.pop("expert_parallel", None)
    # ``model_kwargs.pipeline_stages: S`` — GPipe the model's encoder
    # trunk over a ("pp",) mesh of S devices (parallel/pipeline.py).
    # Under the SPMD executor the SESSION owns the mesh and builds a
    # pp-axis twin (parallel/spmd_pp.py) — the task's model_ctx stays
    # mesh-free (stacked sequential layout) for central evaluation.
    # Under the threaded executor the MODEL owns the mesh (like the
    # threaded sp_mesh mode): the mesh is built here.
    pipeline_stages = int(model_kwargs.get("pipeline_stages", 0))
    if int(model_kwargs.get("pipeline_microbatches", 0)) and not pipeline_stages:
        raise ValueError(
            "pipeline_microbatches without pipeline_stages is inert; set "
            "pipeline_stages (1 = stacked trunk, sequential) or drop it"
        )
    if pipeline_stages and int(model_kwargs.get("sequence_parallel", 0)):
        raise ValueError(
            "pipeline_stages and sequence_parallel are separate sharding "
            "layouts; set one"
        )
    if pipeline_stages and int(config.model_kwargs.get("expert_parallel", 0)):
        raise ValueError(
            "pipeline_stages and expert_parallel are separate sharding "
            "layouts; set one"
        )
    if pipeline_stages > 1 and resolve_executor(config) != "spmd":
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
        if pipeline_stages > len(devices):
            raise ValueError(
                f"pipeline_stages={pipeline_stages} exceeds the "
                f"{len(devices)}-device mesh"
            )
        import numpy as _np

        model_kwargs["pp_mesh"] = Mesh(
            _np.asarray(devices[:pipeline_stages]), axis_names=("pp",)
        )
    sequence_parallel = int(model_kwargs.pop("sequence_parallel", 0))
    if sequence_parallel and resolve_executor(config) == "spmd":
        # the SPMD SP session owns the mesh (parallel/spmd_sp.py builds an
        # sp-mode twin); the task's model_ctx stays mesh-free so central
        # evaluation runs the documented UNSHARDED fused/streaming path
        sequence_parallel = 0
    if sequence_parallel:
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
        if sequence_parallel > len(devices):
            raise ValueError(
                f"sequence_parallel={sequence_parallel} exceeds the "
                f"{len(devices)}-device mesh"
            )
        import numpy as _np

        model_kwargs["sp_mesh"] = Mesh(
            _np.asarray(devices[:sequence_parallel]), axis_names=("sp",)
        )
    model_ctx = create_model_context(
        config.model_name, dataset_collection, **model_kwargs
    )
    if pipeline_stages and (
        int(getattr(model_ctx.module, "pipeline_stages", 0)) != pipeline_stages
    ):
        # a factory whose **kwargs swallowed the knob would train
        # unpipelined with no signal — the same loud contract
        # spmd_ep.py applies to expert_parallel on a non-MoE model
        raise ValueError(
            f"pipeline_stages set but model {config.model_name!r} does not "
            "support a pipelined trunk (TransformerClassificationModel does)"
        )
    if config.use_amp:
        # reference use_amp (torch autocast) → bfloat16 compute on the MXU:
        # params/optimizer state stay float32, forward+backward run bf16
        import jax.numpy as jnp

        model_ctx.compute_dtype = jnp.bfloat16
    hyper_parameter = HyperParameter.from_config(config)
    from .ml_type import MachineLearningPhase as Phase

    train_size = dataset_collection.dataset_size(Phase.Training)
    steps_per_epoch = max(
        1, math.ceil(train_size / config.worker_number / config.batch_size)
    )
    engine = ComputeEngine(
        model_ctx, hyper_parameter, total_steps=steps_per_epoch * config.epoch
    )
    topology = CentralTopology(config.worker_number)
    # reference ``parallel_number`` (worker processes per group,
    # ``algorithm_factory.py:38-58``) → bounded concurrent training loops
    # on the threaded executor; 0 keeps today's unbounded default (XLA
    # already serializes device work — the bound caps host-side staging)
    train_slots = (
        threading.BoundedSemaphore(config.parallel_number)
        if config.parallel_number > 0
        else None
    )
    return TaskContext(
        config=config,
        dataset_collection=dataset_collection,
        model_ctx=model_ctx,
        engine=engine,
        topology=topology,
        task_id=task_id,
        practitioners=practitioners,
        train_slots=train_slots,
    )


def _spawn(ctx: TaskContext) -> None:
    config = ctx.config
    algorithm = config.distributed_algorithm
    common = {"config": config, "task_context": ctx, "task_id": ctx.task_id}
    ctx.server = CentralizedAlgorithmFactory.create_server(
        algorithm,
        ctx.topology,
        endpoint_kwargs=config.endpoint_kwargs.get("server", {}),
        kwargs=dict(common),
    )
    for practitioner in ctx.practitioners:
        worker = CentralizedAlgorithmFactory.create_client(
            algorithm,
            ctx.topology,
            worker_id=practitioner.worker_id,
            endpoint_kwargs=config.endpoint_kwargs.get("worker", {}),
            kwargs={**common, "practitioner": practitioner},
        )
        ctx.workers.append(worker)

    nonfatal_clients = bool(
        dict(config.fault_tolerance or {}).get("client_faults_nonfatal")
    )

    def run(executor) -> None:
        try:
            executor.start()
        except TaskAbortedError:
            get_logger().debug("%s aborted", executor.name)
        except Exception as exc:  # noqa: BLE001 — propagate to the caller
            worker_id = getattr(executor, "worker_id", None)
            if nonfatal_clients and worker_id is not None:
                # fault_tolerance.client_faults_nonfatal: a crashed WORKER
                # becomes a permanent dropout, not a whole-task abort —
                # the server synthesizes its per-round None and every
                # remaining round completes over the survivors (server
                # faults stay fatal: there is nobody to aggregate without
                # it)
                get_logger().warning(
                    "%s failed (%s: %s) — demoted to a dropout "
                    "(fault_tolerance.client_faults_nonfatal)",
                    executor.name,
                    type(exc).__name__,
                    exc,
                )
                ctx.dropped_workers.add(worker_id)
                ctx.topology.server_wakeup.set()
                return
            get_logger().exception("%s failed", executor.name)
            ctx.errors.append(exc)
            ctx.abort_event.set()

    for executor in [ctx.server, *ctx.workers]:
        thread = threading.Thread(
            target=run, args=(executor,), name=executor.name, daemon=True
        )
        ctx.threads.append(thread)
    for thread in ctx.threads:
        thread.start()
    if config.watchdog_seconds > 0:
        threading.Thread(  # not in ctx.threads: must not block harvest
            target=_watchdog_loop,
            args=(ctx, config.watchdog_seconds),
            name="watchdog",
            daemon=True,
        ).start()


def _watchdog_loop(ctx: TaskContext, stall_seconds: float, poll: float = 0.0) -> None:
    """Abort the task when the message fabric makes no progress for
    ``stall_seconds`` (SURVEY.md §5 TPU plan: "a 'deadline' watchdog on
    collective waits") — turns a silent deadlock (an executor waiting on a
    peer that will never send) into a raised error with a diagnosis.
    Deliberately a *message-progress* watchdog, not a per-wait deadline:
    long local training between messages is normal and must not trip it."""
    import time as _time

    poll = poll or min(10.0, max(0.5, stall_seconds / 10.0))
    last_activity = ctx.topology.activity
    stall_start = _time.monotonic()
    while not ctx.aborted() and any(t.is_alive() for t in ctx.threads):
        _time.sleep(poll)
        activity = ctx.topology.activity
        if activity != last_activity:
            last_activity = activity
            stall_start = _time.monotonic()
            continue
        stalled = _time.monotonic() - stall_start
        if stalled > stall_seconds:
            nonfatal = bool(
                dict(
                    getattr(ctx.config, "fault_tolerance", None) or {}
                ).get("client_faults_nonfatal")
            )
            pending_fn = getattr(ctx.server, "pending_workers", None)
            if nonfatal and pending_fn is not None:
                # a worker timeout becomes a dropout, not an abort: demote
                # the workers the server's round is still waiting on, wake
                # the event loop (it synthesizes their Nones), and keep
                # watching.  Only when the server itself is wedged — no
                # pending worker left to blame — does the stall abort.
                pending = set(pending_fn()) - set(ctx.dropped_workers)
                if pending:
                    get_logger().warning(
                        "watchdog: no message progress for %.0fs; demoting "
                        "stalled workers %s to dropouts "
                        "(fault_tolerance.client_faults_nonfatal)",
                        stalled,
                        sorted(pending),
                    )
                    ctx.dropped_workers.update(pending)
                    ctx.topology.server_wakeup.set()
                    stall_start = _time.monotonic()
                    continue
            waiting = [t.name for t in ctx.threads if t.is_alive()]
            get_logger().error(
                "watchdog: no message progress for %.0fs (threshold %.0fs); "
                "aborting task — executors still running: %s",
                stalled,
                stall_seconds,
                waiting,
            )
            ctx.errors.append(
                TimeoutError(
                    f"watchdog: message fabric stalled {stalled:.0f}s; "
                    f"live executors: {waiting}"
                )
            )
            ctx.abort_event.set()
            return


def _remap_sv(result: dict, practitioners) -> dict:
    """Remap per-round Shapley dicts from worker ids to practitioner ids
    (reference ``get_training_result``, ``training.py:156-167``)."""
    worker_to_practitioner = {
        p.worker_id: p.practitioner_id for p in practitioners
    }
    for key in ("sv", "sv_S"):
        if key not in result:
            continue
        result[key] = {
            round_number: {
                worker_to_practitioner[int(w)]: value
                for w, value in round_sv.items()
            }
            for round_number, round_sv in result[key].items()
        }
    return result


def _harvest(ctx: TaskContext) -> dict:
    for thread in ctx.threads:
        thread.join()
    if ctx.errors:
        raise ctx.errors[0]
    get_logger().info(
        "training took %.2f seconds", ctx.timer.elapsed_seconds()
    )
    if ctx.server is None:  # SPMD session task
        return ctx.spmd_result
    result: dict = {"performance": ctx.server.performance_stat}
    sv = getattr(getattr(ctx.server, "algorithm", None), "shapley_values", None)
    if sv:
        result["sv"] = sv
    return _remap_sv(result, ctx.practitioners)


def train(
    config: DistributedTrainingConfig,
    practitioners=None,
    return_task_id: bool = False,
    **kwargs: Any,
) -> dict | Any:
    """Run one federated training task (reference ``train``,
    ``training.py:82-137``).  With ``return_task_id`` the task runs in the
    background; fetch results with :func:`get_training_result`."""
    task_id = uuid.uuid4() if return_task_id else None
    ctx = _build_task(config, practitioners=practitioners, task_id=task_id)
    import contextlib

    profiler_cm: Any = contextlib.nullcontext()
    if ctx.config.profile and not return_task_id:
        # SURVEY.md §5 TPU plan: first-class profiler integration — one
        # xplane trace of the whole run, viewable with tensorboard/xprof
        import jax

        trace_dir = os.path.join(ctx.config.save_dir, "profile")
        os.makedirs(trace_dir, exist_ok=True)
        profiler_cm = jax.profiler.trace(trace_dir)
    with profiler_cm:
        return _run_task(ctx, return_task_id=return_task_id, task_id=task_id)


def train_with_recovery(
    config: DistributedTrainingConfig,
    practitioners=None,
    max_restarts: int | None = None,
    backoff_seconds: float | None = None,
    sleep_fn=None,
    **kwargs: Any,
) -> dict:
    """Self-healing :func:`train`: a bounded-retry supervisor that catches
    a crashed run (preemption, injected FaultPlan kill, infra fault — NOT
    Ctrl-C), backs off exponentially, and relaunches from the newest
    **loadable** checkpoint automatically instead of waiting for an
    operator (the active half of the SURVEY §5 recovery story; the passive
    half is ``algorithm_kwargs.resume_dir`` + ``util/resume.py``).

    Supervisor contract:

    * attempt ``k`` runs in ``<save_dir>_retry<k>`` and resumes from the
      newest attempt directory with a loadable ``round_N.npz`` + record
      row pair (``util/resume.resumable_round`` validates loadability —
      a torn newest checkpoint falls back to the previous round);
    * retries and backoff default from ``config.fault_tolerance``
      (``max_restarts``, ``restart_backoff_seconds``); after
      ``max_restarts`` relaunches the last error propagates unchanged;
    * the returned result is the final attempt's — its restored + fresh
      record rows cover every completed round exactly once — plus a
      ``recovery`` summary (restart count, attempt dirs, final save_dir);
    * methods without round checkpoints (sign_SGD) restart from round 1
      each attempt: the supervisor still bounds the retries.

    ``sleep_fn`` is a test seam for the backoff.
    """
    import time as _time

    config = copy.deepcopy(config)
    if not config.save_dir:
        config.load_config_and_process()
    fault_conf = dict(config.fault_tolerance or {})
    if max_restarts is None:
        max_restarts = int(fault_conf.get("max_restarts", 2))
    if backoff_seconds is None:
        backoff_seconds = float(fault_conf.get("restart_backoff_seconds", 1.0))
    sleep = sleep_fn if sleep_fn is not None else _time.sleep
    assert not kwargs.get("return_task_id"), (
        "train_with_recovery supervises a foreground run; background task "
        "mode has no crash to catch on this thread"
    )
    base_dir = config.save_dir
    attempt_dirs = [base_dir]
    current = config
    restarts = 0
    while True:
        try:
            result = train(current, practitioners=practitioners, **kwargs)
            result["recovery"] = {
                "restarts": restarts,
                "attempt_dirs": list(attempt_dirs),
                "save_dir": current.save_dir,
            }
            return result
        except (KeyboardInterrupt, SystemExit):
            raise  # an operator stop is not a fault to heal
        except Exception as exc:  # noqa: BLE001 — supervise any crash
            restarts += 1
            if restarts > max_restarts:
                get_logger().error(
                    "train_with_recovery: giving up after %d restart(s); "
                    "last error: %s",
                    max_restarts,
                    exc,
                )
                raise
            delay = backoff_seconds * (2 ** (restarts - 1))
            get_logger().warning(
                "train_with_recovery: attempt %d crashed (%s: %s); "
                "relaunching in %.1fs (%d/%d restarts)",
                restarts,
                type(exc).__name__,
                exc,
                delay,
                restarts,
                max_restarts,
            )
            if delay > 0:
                sleep(delay)
            from .util.resume import resumable_round

            # newest attempt with a LOADABLE checkpoint+record pair wins;
            # a run that crashed before its first checkpoint falls back to
            # the attempt before it (or a caller-provided resume_dir).
            # resumable_round fully loads the candidate checkpoint to
            # validate it, so compute it once per candidate and stop at
            # the first hit — no re-validation for the log line.
            candidates = list(reversed(attempt_dirs))
            caller_resume = dict(config.algorithm_kwargs or {}).get(
                "resume_dir"
            )
            if caller_resume:
                candidates.append(caller_resume)
            resume_dir, resume_round = None, 0
            for candidate in candidates:
                if not candidate:
                    continue
                resume_round = resumable_round(candidate)
                if resume_round > 0:
                    resume_dir = candidate
                    break
            current = current.replace(
                save_dir=f"{base_dir}_retry{restarts}"
            )
            current.algorithm_kwargs = dict(current.algorithm_kwargs)
            if resume_dir is not None:
                get_logger().info(
                    "train_with_recovery: resuming attempt %d from %s "
                    "(round %d)",
                    restarts + 1,
                    resume_dir,
                    resume_round,
                )
                current.algorithm_kwargs["resume_dir"] = resume_dir
            else:
                get_logger().warning(
                    "train_with_recovery: nothing resumable yet — attempt "
                    "%d restarts from scratch",
                    restarts + 1,
                )
                current.algorithm_kwargs.pop("resume_dir", None)
            attempt_dirs.append(current.save_dir)


def _session_fed_avg(ctx, args, kwargs):
    from .parallel.spmd import SpmdFedAvgSession

    return SpmdFedAvgSession(*args, **kwargs)


def _session_fed_paq(ctx, args, kwargs):
    from .parallel.spmd import SpmdFedAvgSession

    level = int(
        ctx.config.endpoint_kwargs.get("worker", {}).get("quantization_level", 255)
    )
    return SpmdFedAvgSession(*args, quantization_level=level, **kwargs)


def _session_sign_sgd(ctx, args, kwargs):
    from .parallel.spmd import SpmdSignSGDSession

    return SpmdSignSGDSession(*args, **kwargs)


def _session_fed_obd(ctx, args, kwargs):
    from .parallel.spmd_obd import SpmdFedOBDSession

    codec = "qsgd" if ctx.config.distributed_algorithm == "fed_obd_sq" else "nnadq"
    return SpmdFedOBDSession(*args, codec=codec, **kwargs)


def _session_fed_gnn(ctx, args, kwargs):
    from .parallel.spmd_gnn import SpmdFedGNNSession

    share = True if ctx.config.distributed_algorithm == "fed_gcn" else None
    return SpmdFedGNNSession(*args, share_feature=share, **kwargs)


def _session_fed_aas(ctx, args, kwargs):
    from .parallel.spmd_gnn import SpmdFedAASSession

    return SpmdFedAASSession(*args, **kwargs)


def _session_fed_dropout_avg(ctx, args, kwargs):
    from .parallel.spmd_sparse import SpmdFedDropoutAvgSession

    return SpmdFedDropoutAvgSession(*args, **kwargs)


def _session_smafd(ctx, args, kwargs):
    from .parallel.spmd_sparse import SpmdSMAFDSession

    return SpmdSMAFDSession(*args, **kwargs)


def _session_shapley(ctx, args, kwargs):
    from .parallel.spmd_shapley import SpmdShapleySession

    return SpmdShapleySession(*args, **kwargs)


#: algorithm name -> SPMD session builder.  ONE source of truth: ``executor:
#: auto`` resolves to the fast path exactly for these names, and the same
#: table dispatches session construction (a method added here gets both).
SPMD_SESSION_BUILDERS = {
    "fed_avg": _session_fed_avg,
    "fed_paq": _session_fed_paq,
    "sign_SGD": _session_sign_sgd,
    "fed_obd": _session_fed_obd,
    "fed_obd_sq": _session_fed_obd,
    "fed_gnn": _session_fed_gnn,
    "fed_gcn": _session_fed_gnn,
    "fed_aas": _session_fed_aas,
    "fed_dropout_avg": _session_fed_dropout_avg,
    "single_model_afd": _session_smafd,
    "GTG_shapley_value": _session_shapley,
    "multiround_shapley_value": _session_shapley,
    "Hierarchical_shapley_value": _session_shapley,
}

SPMD_METHODS = frozenset(SPMD_SESSION_BUILDERS)

#: algorithm name -> (module, class) the builders above construct —
#: resolution-only twin of SPMD_SESSION_BUILDERS for introspection
#: (tools/shardcheck's conf↔capability validator) that must never
#: import datasets/models/devices.  Kept key-identical to the builder
#: table (asserted below) so a method added to one cannot be missed by
#: the other.
_SPMD_SESSION_CLASS_PATHS = {
    "fed_avg": ("parallel.spmd", "SpmdFedAvgSession"),
    "fed_paq": ("parallel.spmd", "SpmdFedAvgSession"),
    "sign_SGD": ("parallel.spmd", "SpmdSignSGDSession"),
    "fed_obd": ("parallel.spmd_obd", "SpmdFedOBDSession"),
    "fed_obd_sq": ("parallel.spmd_obd", "SpmdFedOBDSession"),
    "fed_gnn": ("parallel.spmd_gnn", "SpmdFedGNNSession"),
    "fed_gcn": ("parallel.spmd_gnn", "SpmdFedGNNSession"),
    "fed_aas": ("parallel.spmd_gnn", "SpmdFedAASSession"),
    "fed_dropout_avg": ("parallel.spmd_sparse", "SpmdFedDropoutAvgSession"),
    "single_model_afd": ("parallel.spmd_sparse", "SpmdSMAFDSession"),
    "GTG_shapley_value": ("parallel.spmd_shapley", "SpmdShapleySession"),
    "multiround_shapley_value": ("parallel.spmd_shapley", "SpmdShapleySession"),
    "Hierarchical_shapley_value": (
        "parallel.spmd_shapley",
        "SpmdShapleySession",
    ),
}
assert set(_SPMD_SESSION_CLASS_PATHS) == SPMD_METHODS, (
    "SPMD session class table out of sync with the builder table"
)


def resolve_spmd_session_class(config):
    """The session CLASS ``_make_spmd_session`` would construct for this
    config, or None when :func:`resolve_executor` picks the threaded
    path — resolution only: no datasets, models, or devices are touched,
    so ``tools/shardcheck`` can cross-validate every ``conf/**/*.yaml``
    knob against the class's ``capability_gates`` at lint time.  Raises
    the same ``ValueError``/``NotImplementedError`` the runtime wiring
    would (invalid layout×method combinations fail here with the honest
    reason)."""
    import importlib

    if resolve_executor(config) != "spmd":
        return None
    model_kwargs = dict(config.model_kwargs)
    algorithm = config.distributed_algorithm

    def load(module, name):
        mod = importlib.import_module(f".{module}", package=__package__)
        return getattr(mod, name)

    if int(model_kwargs.get("pipeline_stages", 0)) > 1:
        return load("parallel.spmd_pp", "SpmdPipelineSession")
    if int(model_kwargs.get("expert_parallel", 0)):
        if int(model_kwargs.get("sequence_parallel", 0)):
            raise ValueError(
                "expert_parallel and sequence_parallel are separate "
                "session layouts; set one (composing them is a mesh "
                "design choice the YAML surface does not expose)"
            )
        if algorithm in ("fed_obd", "fed_obd_sq"):
            return load(
                "parallel.spmd_obd_ep", "SpmdFedOBDExpertParallelSession"
            )
        return load("parallel.spmd_ep", "SpmdExpertParallelSession")
    if int(model_kwargs.get("sequence_parallel", 0)):
        if algorithm == "fed_avg":
            return load("parallel.spmd_sp", "SpmdSequenceParallelSession")
        if algorithm in ("fed_obd", "fed_obd_sq"):
            return load(
                "parallel.spmd_obd_sp", "SpmdFedOBDSequenceParallelSession"
            )
        raise ValueError(
            "sequence_parallel under executor=spmd is implemented for "
            "fed_avg (parallel/spmd_sp.py) and fed_obd/fed_obd_sq "
            "(parallel/spmd_obd_sp.py); other methods run it on the "
            "threaded executor, where each client's jitted step owns "
            "the model's sp shard_map (executor auto does this)"
        )
    path = _SPMD_SESSION_CLASS_PATHS.get(algorithm)
    if path is None:
        raise NotImplementedError(
            f"no SPMD round program for {algorithm!r} (every built-in "
            "method has one; for custom registrations drop executor=spmd "
            "and use the threaded executor)"
        )
    return load(*path)


_EXECUTORS = ("auto", "spmd", "sequential")


def resolve_executor(config) -> str:
    """``auto`` → the SPMD fast path for every built-in method, threaded
    only for custom factory registrations (VERDICT r1 item 8: TPU-first
    means the compiled path is the default, the simulation-faithful
    threaded executor the explicit fallback via ``executor: sequential``)."""
    executor = str(config.executor or "auto")
    if executor not in _EXECUTORS:
        raise ValueError(
            f"unknown executor {executor!r}; expected one of {_EXECUTORS}"
        )
    if int(dict(config.model_kwargs).get("expert_parallel", 0)):
        if config.distributed_algorithm not in (
            "fed_avg",
            "fed_obd",
            "fed_obd_sq",
        ):
            raise ValueError(
                "expert_parallel is implemented for fed_avg "
                "(parallel/spmd_ep.py) and fed_obd/fed_obd_sq "
                "(parallel/spmd_obd_ep.py: the SPMD session gives the ep "
                "mesh to each client's MoE model); drop the key for "
                f"{config.distributed_algorithm!r}"
            )
        if executor == "sequential":
            raise ValueError(
                "expert_parallel requires the SPMD executor (GSPMD shards "
                "the expert kernels); drop executor=sequential"
            )
        return "spmd"
    if int(dict(config.model_kwargs).get("pipeline_stages", 0)) > 1:
        if config.distributed_algorithm == "fed_avg":
            if executor == "sequential":
                # explicit opt-in to the threaded layout (model owns the
                # pp mesh via its own shard_map, models/text.py)
                return "sequential"
            # TPU-first default: the SPMD session owns the ("pp",) mesh
            # and clients scan through the GPipe trunk in one program
            return "spmd"
        if executor == "spmd":
            raise ValueError(
                "pipeline_stages under executor=spmd is implemented for "
                "fed_avg (parallel/spmd_pp.py); other methods run it on "
                "the threaded executor (the model owns the pp mesh)"
            )
        return "sequential"
    if executor != "auto":
        return executor
    if int(dict(config.model_kwargs).get("sequence_parallel", 0)):
        if config.distributed_algorithm in ("fed_avg", "fed_obd", "fed_obd_sq"):
            # dedicated SPMD sessions: the ("sp",) mesh shards each client's
            # sequence axis, clients scan inside one round program
            # (parallel/spmd_sp.py; parallel/spmd_obd_sp.py for FedOBD)
            return "spmd"
        # other methods: the threaded executor, where each client's jitted
        # step owns the model's sp shard_map
        get_logger().info(
            "executor auto: sequence_parallel set, using the threaded "
            "executor for %r (sp mesh owns the devices)",
            config.distributed_algorithm,
        )
        return "sequential"
    if config.distributed_algorithm in SPMD_METHODS:
        return "spmd"
    get_logger().info(
        "executor auto: %r has no SPMD round program, using the threaded "
        "executor",
        config.distributed_algorithm,
    )
    return "sequential"


def _make_spmd_session(ctx: TaskContext):
    model_kwargs = dict(ctx.config.model_kwargs)
    if int(model_kwargs.get("pipeline_stages", 0)) > 1:
        # _build_task already rejected pipeline × sp/ep combinations and
        # resolve_executor pinned non-fed_avg to the threaded executor
        from .parallel.spmd_pp import build_pipeline_session

        session_args = (
            ctx.config,
            ctx.dataset_collection,
            ctx.model_ctx,
            ctx.engine,
            ctx.practitioners,
        )
        return build_pipeline_session(ctx, session_args, {})
    if int(model_kwargs.get("expert_parallel", 0)):
        if int(model_kwargs.get("sequence_parallel", 0)):
            raise ValueError(
                "expert_parallel and sequence_parallel are separate session "
                "layouts; set one (composing them is a mesh design choice "
                "the YAML surface does not expose)"
            )
        session_args = (
            ctx.config,
            ctx.dataset_collection,
            ctx.model_ctx,
            ctx.engine,
            ctx.practitioners,
        )
        if ctx.config.distributed_algorithm in ("fed_obd", "fed_obd_sq"):
            from .parallel.spmd_obd_ep import (
                build_obd_expert_parallel_session,
            )

            codec = (
                "qsgd"
                if ctx.config.distributed_algorithm == "fed_obd_sq"
                else "nnadq"
            )
            return build_obd_expert_parallel_session(
                ctx, session_args, codec
            )
        from .parallel.spmd_ep import build_expert_parallel_session

        return build_expert_parallel_session(ctx, session_args, {})
    if int(dict(ctx.config.model_kwargs).get("sequence_parallel", 0)):
        if ctx.config.distributed_algorithm not in (
            "fed_avg",
            "fed_obd",
            "fed_obd_sq",
        ):
            raise ValueError(
                "sequence_parallel under executor=spmd is implemented for "
                "fed_avg (parallel/spmd_sp.py) and fed_obd/fed_obd_sq "
                "(parallel/spmd_obd_sp.py); other methods run it on the "
                "threaded executor, where each client's jitted step owns "
                "the model's sp shard_map (executor auto does this)"
            )
        session_args = (
            ctx.config,
            ctx.dataset_collection,
            ctx.model_ctx,
            ctx.engine,
            ctx.practitioners,
        )
        if ctx.config.distributed_algorithm in ("fed_obd", "fed_obd_sq"):
            from .parallel.spmd_obd_sp import (
                build_obd_sequence_parallel_session,
            )

            codec = (
                "qsgd"
                if ctx.config.distributed_algorithm == "fed_obd_sq"
                else "nnadq"
            )
            return build_obd_sequence_parallel_session(
                ctx, session_args, codec
            )
        from .parallel.spmd_sp import build_sequence_parallel_session

        return build_sequence_parallel_session(ctx, session_args, {})
    builder = SPMD_SESSION_BUILDERS.get(ctx.config.distributed_algorithm)
    if builder is None:
        raise NotImplementedError(
            f"no SPMD round program for {ctx.config.distributed_algorithm!r} "
            "(every built-in method has one; for custom registrations drop "
            "executor=spmd and use the threaded executor)"
        )
    session_args = (
        ctx.config,
        ctx.dataset_collection,
        ctx.model_ctx,
        ctx.engine,
        ctx.practitioners,
    )
    # ``algorithm_kwargs.model_parallel: M`` shapes the mesh as
    # (clients=devices/M, model=M) — on fed_avg this turns on FSDP param
    # sharding over the model axis (parallel/spmd.py)
    model_parallel = int(ctx.config.algorithm_kwargs.get("model_parallel", 1))
    # ``algorithm_kwargs.hybrid_mesh_hosts`` opts into the (hosts × chips)
    # hybrid layout: the ``clients`` axis spans hosts so streamed cohort
    # rows land on their owning host's chips without crossing DCN.  A
    # positive int carves virtual per-host blocks (the forced-host-device
    # CI harness); ``auto`` groups by real process_index on a pod.
    hybrid_hosts = ctx.config.algorithm_kwargs.get("hybrid_mesh_hosts")
    session_kwargs = {}
    if hybrid_hosts is not None:
        from .parallel.mesh import create_hybrid_device_mesh

        session_kwargs["mesh"] = create_hybrid_device_mesh(
            model_parallel=model_parallel,
            virtual_hosts=(
                None
                if str(hybrid_hosts).strip().lower() == "auto"
                else int(hybrid_hosts)
            ),
        )
    elif model_parallel > 1:
        from .parallel.mesh import make_mesh

        session_kwargs["mesh"] = make_mesh(model_parallel=model_parallel)
    return builder(ctx, session_args, session_kwargs)


def _run_task(ctx: TaskContext, return_task_id: bool, task_id: Any) -> dict | Any:
    if resolve_executor(ctx.config) == "spmd":
        session = _make_spmd_session(ctx)
        if return_task_id:
            # task mode: the whole session runs on one background thread —
            # the single-controller analogue of the reference's background
            # process pool (its concurrent-task API, ``training.py:96-133``)
            def run_session() -> None:
                try:
                    ctx.spmd_result = _remap_sv(session.run(), ctx.practitioners)
                except Exception as exc:  # noqa: BLE001 — surfaced at harvest
                    get_logger().exception("spmd task failed")
                    ctx.errors.append(exc)

            thread = threading.Thread(
                target=run_session,
                name=f"spmd:{ctx.config.distributed_algorithm}",
                daemon=True,
            )
            ctx.threads.append(thread)
            thread.start()
            with _tasks_lock:
                tasks[task_id] = ctx
            return task_id
        result = _remap_sv(session.run(), ctx.practitioners)
        get_logger().info("training took %.2f seconds", ctx.timer.elapsed_seconds())
        return result
    _spawn(ctx)
    if return_task_id:
        with _tasks_lock:
            tasks[task_id] = ctx
        return task_id
    return _harvest(ctx)


def get_training_result(task_id: Any, timeout: float | None = None) -> dict:
    """Wait for a background task and return its results (reference
    ``get_training_result``, ``training.py:140-169``).  On timeout the task
    stays registered so the caller can retry."""
    with _tasks_lock:
        ctx = tasks[task_id]
    if timeout is not None:
        deadline = ctx.timer.elapsed_seconds() + timeout
        for thread in ctx.threads:
            remaining = deadline - ctx.timer.elapsed_seconds()
            thread.join(timeout=max(0.0, remaining))
        if any(thread.is_alive() for thread in ctx.threads):
            raise TimeoutError(f"task {task_id} still running")
    with _tasks_lock:
        tasks.pop(task_id, None)
    return _harvest(ctx)
