"""Federated participants.

TPU-native equivalent of ``simulation_lib/practitioner.py:5-35``: a
``Practitioner`` is a stable participant identity (``practitioner_id``) bound
per task to a ``worker_id`` slot, holding its partition of each dataset via a
shared sampler.
"""

from .config import DistributedTrainingConfig
from .data import DatasetCollection, create_dataset_collection
from .sampler import DatasetCollectionSampler, get_dataset_collection_sampler


class Practitioner:
    def __init__(self, practitioner_id: int) -> None:
        self.practitioner_id = practitioner_id
        self._worker_id: int | None = None
        self._samplers: dict[str, DatasetCollectionSampler] = {}

    @property
    def worker_id(self) -> int:
        assert self._worker_id is not None
        return self._worker_id

    def set_worker_id(self, worker_id: int) -> None:
        self._worker_id = worker_id

    def set_sampler(self, dataset_name: str, sampler: DatasetCollectionSampler) -> None:
        self._samplers[dataset_name] = sampler

    def has_dataset(self, dataset_name: str) -> bool:
        return dataset_name in self._samplers

    def get_sampler(self, dataset_name: str) -> DatasetCollectionSampler:
        return self._samplers[dataset_name]

    def create_dataset_collection(
        self, config: DistributedTrainingConfig
    ) -> DatasetCollection:
        """This practitioner's local view of the dataset (reference
        ``Practitioner.create_trainer`` subsets the toolbox trainer's dataset,
        ``practitioner.py:29-35``)."""
        sampler = self._samplers[config.dataset_name]
        return sampler.sample_dataset(self.practitioner_id)


def create_practitioners(config: DistributedTrainingConfig) -> set[Practitioner]:
    """Build ``worker_number`` practitioners sharing one sampler
    (reference ``config.create_practitioners``, ``config.py:55-72``)."""
    dc = create_dataset_collection(config)
    sampler = get_dataset_collection_sampler(
        config.dataset_sampling,
        dc,
        config.worker_number,
        seed=config.seed,
        **dict(config.dataset_sampling_kwargs),
    )
    practitioners = set()
    for practitioner_id in range(config.worker_number):
        practitioner = Practitioner(practitioner_id)
        practitioner.set_sampler(config.dataset_name, sampler)
        practitioner.set_worker_id(practitioner_id)
        practitioners.add(practitioner)
    return practitioners
