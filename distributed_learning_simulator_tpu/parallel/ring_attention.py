"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference has **no** sequence parallelism (SURVEY.md §5 "Long-context /
sequence parallelism: absent") — its models are small classifiers.  For the
TPU-native framework long context is first-class: a single client model can
shard its *sequence* axis over the mesh and still compute exact attention.

Two interchangeable strategies, both exact (not approximations):

* ``ring_attention`` — blockwise attention with an online (streaming)
  softmax.  Each device holds one sequence block of K/V; blocks rotate
  around the ring via ``lax.ppermute`` while every device accumulates
  attention for its local queries.  N-1 hops on ICI, O(T/N) memory per
  device, numerically stable (running max / normalizer, the flash-attention
  recurrence).
* ``ulysses_attention`` — all-to-all sequence↔head re-sharding: each device
  gathers the *full* sequence for ``H/N`` of the heads, runs dense local
  attention, and scatters back.  Two ``all_to_all``s, preferable when the
  head count is divisible by the mesh axis and sequence blocks are small.

Both are pure functions over **local** shards designed to run inside
``shard_map`` (see ``make_sequence_parallel_attention`` for the jitted
full-array wrapper).  Causal masking uses global positions reconstructed
from ``lax.axis_index``, so the sharded result matches dense attention up
to float accumulation order.  Key-padding masks (``kv_mask``) are
supported everywhere — they ride the ring alongside the K/V blocks.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def _block_scores(q, k, scale):
    # q: [B, Tq, H, D], k: [B, Tk, H, D] -> [B, H, Tq, Tk]
    return jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale


def _combined_mask(q_pos, k_pos, kv_mask, causal, batch):
    """[B, Tq, Tk] boolean mask (True = may attend), or None if unmasked."""
    mask = None
    if causal:
        mask = jnp.broadcast_to(
            (q_pos[:, None] >= k_pos[None, :])[None],
            (batch, q_pos.shape[0], k_pos.shape[0]),
        )
    if kv_mask is not None:
        pad = jnp.broadcast_to(
            kv_mask[:, None, :].astype(bool), (batch, q_pos.shape[0], k_pos.shape[0])
        )
        mask = pad if mask is None else (mask & pad)
    return mask


def ring_attention(q, k, v, axis_name: str, causal: bool = False, kv_mask=None):
    """Exact attention over a ring-sharded sequence.

    Arguments are the **local** sequence blocks inside ``shard_map``:
    ``q/k/v: [B, T_local, H, D]`` (global sequence laid out in axis-index
    order), ``kv_mask: [B, T_local]`` key-padding mask or None.  Returns the
    local attention output ``[B, T_local, H, D]``.

    When the per-device block is eligible for the fused Pallas kernel
    (``ops/fused_attention.kernel_tier``) each hop's block attention runs
    as one kernel call and hops merge differentiable ``(out, lse)`` pairs —
    the composition that makes multi-chip long context ride the same kernel
    as single-chip (the lse cotangent folds into the kernel's backward).
    Causal rides the kernel too: with equal-size blocks in axis-index
    order, hop 0 is the diagonal block (the kernel's own causal mask,
    global row/col offsets are equal) and every later hop is either fully
    visible or fully masked — never diagonal — so visibility is a per-hop
    lse select, not a kernel concern.
    """
    from ..ops.fused_attention import kernel_tier

    if kernel_tier(q.shape[1], q.shape[3], q.dtype.itemsize):
        return _ring_attention_fused(q, k, v, axis_name, kv_mask, causal)
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, t_local, heads, dim = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(dim, jnp.float32))
    q_pos = my_index * t_local + jnp.arange(t_local)
    has_mask = kv_mask is not None

    def accumulate(acc, k_blk, v_blk, mask_blk, kv_index):
        """Online-softmax update with one K/V block (the flash-attention
        recurrence)."""
        o, m, l = acc
        s = _block_scores(q, k_blk, scale)
        k_pos = kv_index * t_local + jnp.arange(t_local)
        mask = _combined_mask(q_pos, k_pos, mask_blk, causal, batch)
        if mask is not None:
            s = jnp.where(mask[:, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if mask is not None:
            p = p * mask[:, None]
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return o, m_new, l

    acc = (
        jnp.zeros((batch, heads, t_local, dim), jnp.float32),
        jnp.full((batch, heads, t_local), _NEG_INF, jnp.float32),
        jnp.zeros((batch, heads, t_local), jnp.float32),
    )
    mask0 = kv_mask.astype(bool) if has_mask else None
    # hop 0: the local block, no communication
    acc = accumulate(acc, k, v, mask0, my_index)

    if axis_size > 1:
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

        def step(carry, hop):
            # permute first, then accumulate: exactly N-1 hops on ICI
            if has_mask:
                o, m, l, k_blk, v_blk, mask_blk = carry
                mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
            else:
                o, m, l, k_blk, v_blk = carry
                mask_blk = None
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            kv_index = (my_index - hop) % axis_size
            o, m, l = accumulate((o, m, l), k_blk, v_blk, mask_blk, kv_index)
            if has_mask:
                return (o, m, l, k_blk, v_blk, mask_blk), None
            return (o, m, l, k_blk, v_blk), None

        carry = (*acc, k, v, mask0) if has_mask else (*acc, k, v)
        carry, _ = jax.lax.scan(step, carry, jnp.arange(1, axis_size))
        acc = carry[:3]

    o, m, l = acc
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_attention_fused(q, k, v, axis_name: str, kv_mask, causal=False):
    """Ring hops over Pallas-fused block attention.  Each hop computes its
    K/V block's partial ``(out, lse)`` with ``fused_attention_lse`` and the
    carry merges the pairs with the standard log-sum-exp combination —
    numerically identical to the online-softmax recurrence, and
    differentiable end-to-end (scan over custom_vjp calls + ppermute).

    Causal: hop 0 (the local block) is the only diagonal — the kernel's
    causal mask applies as-is.  At hop ``h``, the arriving K/V block is
    ``kv_index = my_index - h (mod N)``: fully visible when
    ``my_index >= h`` (all its key positions precede the local queries),
    fully masked otherwise — encoded by forcing that hop's ``lse`` to
    -inf, which zeroes its merge weight.  Devices early in the ring
    compute hops they discard (the uniform-program bubble every
    non-striped ring layout pays; the jnp path pays it as a full masked
    score block instead)."""
    from ..ops.fused_attention import fused_attention_lse

    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, t_local, _, _ = q.shape
    mask0 = (
        jnp.ones((batch, t_local), jnp.float32)
        if kv_mask is None
        else kv_mask.astype(jnp.float32)
    )
    o, lse = fused_attention_lse(q, k, v, kv_mask=mask0 != 0, causal=causal)
    o = o.astype(jnp.float32)

    if axis_size > 1:
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

        def step(carry, hop):
            o, lse, k_blk, v_blk, m_blk = carry
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            m_blk = jax.lax.ppermute(m_blk, axis_name, perm)
            o_b, lse_b = fused_attention_lse(q, k_blk, v_blk, kv_mask=m_blk != 0)
            if causal:
                visible = (my_index >= hop)[None, None, None]
                lse_b = jnp.where(visible, lse_b, _NEG_INF)
            m = jnp.maximum(lse, lse_b)  # [B, H, T]
            w = jnp.exp(lse - m)
            w_b = jnp.exp(lse_b - m)
            denom = jnp.maximum(w + w_b, 1e-30)
            align = lambda x: jnp.transpose(x, (0, 2, 1))[..., None]
            o = o * align(w / denom) + o_b.astype(jnp.float32) * align(
                w_b / denom
            )
            lse = m + jnp.log(denom)
            return (o, lse, k_blk, v_blk, m_blk), None

        (o, lse, _k, _v, _m), _ = jax.lax.scan(
            step, (o, lse, k, v, mask0), jnp.arange(1, axis_size)
        )
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False, kv_mask=None):
    """Exact attention via all-to-all sequence↔head re-sharding.

    Local blocks ``[B, T_local, H, D]``; requires ``H % axis_size == 0``.
    After the first ``all_to_all`` every device holds the full sequence for
    ``H / axis_size`` heads; dense attention runs locally; the second
    ``all_to_all`` restores sequence sharding.
    """
    axis_size = jax.lax.psum(1, axis_name)
    t_local = q.shape[1]
    assert q.shape[2] % axis_size == 0, (
        f"ulysses needs head count {q.shape[2]} divisible by mesh axis "
        f"{axis_name!r} size {axis_size}"
    )

    def seq_to_head(x):
        # [B, T_local, H, D] -> [B, T_global, H/N, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def head_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    full_mask = (
        jax.lax.all_gather(kv_mask.astype(bool), axis_name, axis=1, tiled=True)
        if kv_mask is not None
        else None
    )
    # after the all-to-all each device holds the FULL sequence for its
    # heads — exactly the fused kernel's shape (causal is fine here:
    # positions are global)
    from ..ops.fused_attention import fused_attention, kernel_tier

    if kernel_tier(qg.shape[1], qg.shape[3], qg.dtype.itemsize):
        out = fused_attention(qg, kg, vg, kv_mask=full_mask, causal=causal)
    else:
        out = dense_attention(qg, kg, vg, causal=causal, kv_mask=full_mask)
    return head_to_seq(out)


def dense_attention(q, k, v, causal: bool = False, kv_mask=None):
    """Single-device reference implementation (tests and the no-mesh
    fallback path of ``LongContextTransformer``)."""
    batch = q.shape[0]
    dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(dim, jnp.float32))
    s = _block_scores(q, k, scale)
    mask = _combined_mask(
        jnp.arange(q.shape[1]), jnp.arange(k.shape[1]), kv_mask, causal, batch
    )
    if mask is not None:
        s = jnp.where(mask[:, None], s, _NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    if mask is not None:
        p = p * mask[:, None]
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def make_sequence_parallel_attention(
    mesh: Mesh,
    axis_name: str = "sp",
    impl: str = "ring",
    causal: bool = False,
    with_kv_mask: bool = False,
):
    """Jitted full-array entry point: takes global ``[B, T, H, D]`` arrays
    sharded ``P(None, axis_name)`` over the mesh and returns the globally
    correct attention output with the same sharding.  With
    ``with_kv_mask=True`` the returned function takes a fourth argument,
    the ``[B, T]`` key-padding mask (sharded the same way)."""
    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    spec = P(None, axis_name)
    sharding = NamedSharding(mesh, spec)

    if with_kv_mask:

        def local_fn(q, k, v, kv_mask):
            return inner(
                q, k, v, axis_name=axis_name, causal=causal, kv_mask=kv_mask
            )

        mapped = _shard_map(local_fn, mesh, (spec,) * 4, spec)
        return jax.jit(
            mapped, in_shardings=(sharding,) * 4, out_shardings=sharding
        )

    def local_fn(q, k, v):
        return inner(q, k, v, axis_name=axis_name, causal=causal)

    mapped = _shard_map(local_fn, mesh, (spec,) * 3, spec)
    return jax.jit(
        mapped, in_shardings=(sharding,) * 3, out_shardings=sharding
    )


def sharded_attention(q, k, v, mesh, axis_name="sp", impl="ring", causal=False, kv_mask=None):
    """Global-array attention usable *inside* an outer jitted program (e.g.
    a flax module's forward): nests ``shard_map`` over ``mesh`` so the
    sequence axis stays device-resident and K/V blocks move over ICI."""
    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[impl]
    spec = P(None, axis_name)

    if kv_mask is None:

        def local_fn(q, k, v):
            return inner(q, k, v, axis_name=axis_name, causal=causal)

        return _shard_map(local_fn, mesh, (spec,) * 3, spec)(q, k, v)

    def local_fn(q, k, v, kv_mask):
        return inner(q, k, v, axis_name=axis_name, causal=causal, kv_mask=kv_mask)

    return _shard_map(local_fn, mesh, (spec, spec, spec, spec), spec)(q, k, v, kv_mask)
