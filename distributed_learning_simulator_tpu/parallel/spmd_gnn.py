"""Federated GNN (fed_gnn / fed_gcn) as one SPMD program per round.

The reference's graph FL performs a synchronous boundary-embedding exchange
through the server **inside every forward pass** — N workers post to pipes
and block until the server routes embeddings back
(``graph_worker.py:344-373``, SURVEY.md §3.4: "a synchronous barrier across
all workers per message-passing layer per batch").  On the mesh this whole
barrier is ONE collective: every client slot computes its first-layer
embeddings, the provided rows (each training node has exactly one owner, so
owner masks are disjoint) are summed across slots and ``psum``-ed over the
``clients`` axis into a global embedding table, and each slot's second layer
reads its boundary rows from that table — ``stop_gradient``-ed, matching the
reference's detached pipe tensors.  Epochs × exchanges × the weighted FedAvg
reduction compile into a single XLA program; the host keeps rounds, records,
and artifacts.

Partitioning parity with the threaded ``worker/graph_worker.py``: per-client
in-client edge masks for layer 0, in-client + surviving cross-training edges
(after ``edge_drop_rate``) for later layers, boundary/provide node sets, and
per-round byte accounting from the same mask counts.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..engine.batching import make_graph_batch
from ..engine.engine import maybe_slow_metrics, summarize_metrics
from ..ml_type import MachineLearningPhase as Phase
from ..models.registry import masked_ce_loss
from ..ops.pytree import unflatten_nested
from ..utils.logging import get_logger
from .mesh import client_slots, make_mesh, put_sharded
from .spmd import shard_map_compat


class SpmdFedGNNSession:
    # fed_aas resamples num_neighbor per ROUND host-side; the stock session
    # applies it per minibatch inside the round program
    _dataloader_num_neighbor = True

    def __init__(
        self,
        config,
        dataset_collection,
        model_ctx,
        engine,
        practitioners,
        mesh=None,
        share_feature: bool | None = None,
    ) -> None:
        self.config = config
        self.dc = dataset_collection
        self.model_ctx = model_ctx
        self.engine = engine
        self.mesh = mesh if mesh is not None else make_mesh()
        from .watchdog import DeadlineWatchdog

        self._watchdog = DeadlineWatchdog.from_config(config, self.mesh)
        self.n_slots = client_slots(config.worker_number, self.mesh)
        self._share_feature = (
            config.algorithm_kwargs.get("share_feature", True)
            if share_feature is None
            else share_feature
        )
        self._stat: dict[int, dict] = {}
        self._max_acc = 0.0
        from ..util.checkpoint import AsyncCheckpointWriter

        self._ckpt = AsyncCheckpointWriter()
        self._prepare_data(practitioners)
        self._round_fn = self._build_round_fn()

    # ------------------------------------------------------------------
    def _prepare_data(self, practitioners) -> None:
        config = self.config
        train = self.dc.get_dataset(Phase.Training)
        graph = train.inputs
        num_nodes = len(train.targets)
        edge_index = np.asarray(graph["edge_index"])
        src, dst = edge_index[0], edge_index[1]
        drop_rate = float(config.algorithm_kwargs.get("edge_drop_rate", 0.0))

        own_lists: list[np.ndarray] = []
        for practitioner in sorted(practitioners, key=lambda p: p.worker_id):
            sampler = practitioner.get_sampler(config.dataset_name)
            idx = sampler.sample(practitioner.practitioner_id)[Phase.Training]
            own_lists.append(np.asarray(idx, np.int64))

        S = self.n_slots
        own_mask = np.zeros((S, num_nodes), np.float32)
        local_edges = np.zeros((S, src.shape[0]), np.float32)
        cross_edges = np.zeros_like(local_edges)
        provide_mask = np.zeros_like(own_mask)
        boundary_mask = np.zeros_like(own_mask)
        train_mask = np.zeros_like(own_mask)
        sizes = np.zeros(S, np.float32)

        all_training = np.zeros(num_nodes, bool)
        for idx in own_lists:
            all_training[idx] = True
        for c, idx in enumerate(own_lists):
            own = np.zeros(num_nodes, bool)
            own[idx] = True
            other_training = all_training & ~own
            in_client = own[src] & own[dst]
            cross = (own[src] & other_training[dst]) | (
                other_training[src] & own[dst]
            )
            if drop_rate > 0:
                # same per-worker stream as the threaded GraphWorker
                rng = np.random.default_rng(config.seed * 131 + c)
                cross &= rng.random(cross.shape) >= drop_rate
            own_mask[c, own] = 1.0
            local_edges[c] = in_client
            cross_edges[c] = in_client | cross
            prov = np.unique(
                np.concatenate([src[cross & own[src]], dst[cross & own[dst]]])
            )
            bnd = np.unique(
                np.concatenate(
                    [
                        src[cross & other_training[src]],
                        dst[cross & other_training[dst]],
                    ]
                )
            )
            provide_mask[c, prov.astype(np.int64)] = 1.0
            boundary_mask[c, bnd.astype(np.int64)] = 1.0
            train_mask[c, own] = 1.0
            sizes[c] = len(idx)

        # a slot only receives rows someone actually provides
        provided_any = provide_mask.max(axis=0)
        recv_mask = boundary_mask * provided_any[None, :]

        self._dataset_sizes = sizes
        hidden = int(getattr(self.model_ctx.module, "hidden", 64))
        boundaries = int(getattr(self.model_ctx.module, "num_mp_layers", 2)) - 1
        # one exchange set per minibatch per epoch (full-batch: 1/epoch)
        steps = config.epoch * int(config.algorithm_kwargs.get("batch_number") or 1)
        self._round_payload_bytes = int(
            steps * boundaries * 4 * hidden * (provide_mask.sum() + recv_mask.sum())
        )
        if not self._share_feature:
            cross_edges = local_edges.copy()
            recv_mask = np.zeros_like(recv_mask)
            self._round_payload_bytes = 0

        client_sharding = NamedSharding(self.mesh, P("clients"))
        replicated = NamedSharding(self.mesh, P())
        self._client_sharding = client_sharding
        self._replicated = replicated

        self._data = {
            "local_edges": put_sharded(local_edges, client_sharding),
            "cross_edges": put_sharded(cross_edges, client_sharding),
            "provide": put_sharded(provide_mask, client_sharding),
            "recv": put_sharded(recv_mask, client_sharding),
            "train_mask": put_sharded(train_mask, client_sharding),
            "x": put_sharded(np.asarray(graph["x"], np.float32), replicated),
            "edge_index": put_sharded(edge_index, replicated),
            "targets": put_sharded(
                np.asarray(train.targets, np.int32), replicated
            ),
        }

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        engine = self.engine
        model = self.model_ctx.module
        epochs = self.config.epoch
        share_feature = self._share_feature
        num_layers = int(getattr(model, "num_mp_layers", 2))
        batch_number = int(self.config.algorithm_kwargs.get("batch_number") or 1)
        num_neighbor = (
            self.config.algorithm_kwargs.get("num_neighbor")
            if self._dataloader_num_neighbor
            else None
        )
        minibatched = batch_number > 1 or num_neighbor is not None

        from ..models.graph import apply_mp_stage
        from ..ops.graph_sampling import cap_fan_in_jax, minibatch_assignment

        def apply_stage(params, i, h, inputs, train, rng=None):
            variables = {"params": unflatten_nested(params)}
            return apply_mp_stage(model, variables, i, h, inputs, train, rng)

        def round_program(global_params, weights, rngs, data):
            def shard_body(global_params, data, weights, rngs):
                S = weights.shape[0]
                x = data["x"]
                edge_index = data["edge_index"]
                targets = data["targets"]

                params0 = jax.tree.map(
                    lambda p: jnp.broadcast_to(p, (S, *p.shape)), global_params
                )
                opt0 = jax.vmap(engine.optimizer.init)(params0)

                def inputs_for(edge_mask):
                    return {
                        "x": x,
                        "edge_index": edge_index,
                        "edge_mask": edge_mask,
                    }

                def train_one_batch(
                    params_s, opt_s, local_m, cross_m, train_m, step_rngs
                ):
                    """One synchronized step across all slots: boundary
                    exchange (psum per MP-layer boundary) + a local SGD step
                    on ``train_m``-masked nodes."""
                    if share_feature:
                        # the reference's through-server barrier before each
                        # MessagePassing layer after the first, one psum per
                        # layer boundary: disjoint owner masks sum into a
                        # global embedding table per boundary
                        tables = []
                        h_pay = jax.vmap(
                            lambda p, lm: apply_stage(
                                p, 0, None, inputs_for(lm), False
                            )
                        )(params_s, local_m)
                        for i in range(1, num_layers):
                            provide_sum = jnp.einsum(
                                "sn,snh->nh", data["provide"], h_pay
                            )
                            table = jax.lax.stop_gradient(
                                jax.lax.psum(provide_sum, axis_name="clients")
                            )
                            tables.append(table)
                            if i < num_layers - 1:
                                h_mixed = (
                                    h_pay * (1.0 - data["recv"])[..., None]
                                    + table[None] * data["recv"][..., None]
                                )
                                h_pay = jax.vmap(
                                    lambda p, h, cm, i=i: apply_stage(
                                        p, i, h, inputs_for(cm), False
                                    )
                                )(params_s, h_mixed, cross_m)
                    else:
                        tables = None

                    def slot_step(p, o, lm, cm, rm, tm, rng):
                        def loss_fn(p):
                            h = apply_stage(p, 0, None, inputs_for(lm), True, rng)
                            for i in range(1, num_layers):
                                if tables is not None:
                                    h = (
                                        h * (1.0 - rm[:, None])
                                        + tables[i - 1] * rm[:, None]
                                    )
                                h = apply_stage(p, i, h, inputs_for(cm), True, rng)
                            return masked_ce_loss(h, targets, tm)

                        (loss, aux), grads = jax.value_and_grad(
                            loss_fn, has_aux=True
                        )(p)
                        updates, o = engine.optimizer.update(grads, o, p)
                        p = optax.apply_updates(p, updates)
                        metrics = {
                            "loss_sum": loss * aux["count"],
                            "correct": aux["correct"],
                            "count": aux["count"],
                        }
                        return p, o, metrics

                    return jax.vmap(slot_step)(
                        params_s,
                        opt_s,
                        local_m,
                        cross_m,
                        data["recv"],
                        train_m,
                        step_rngs,
                    )

                def epoch_body(carry, epoch_rngs):
                    params_s, opt_s = carry
                    if not minibatched:
                        params_s, opt_s, metrics = train_one_batch(
                            params_s,
                            opt_s,
                            data["local_edges"],
                            data["cross_edges"],
                            data["train_mask"],
                            epoch_rngs,
                        )
                        return (params_s, opt_s), metrics

                    # reference graph dataloader semantics
                    # (graph_worker.py:94-101): per-epoch shuffled node
                    # minibatches, optional per-batch fan-in sampling; the
                    # boundary exchange fires per BATCH per layer boundary
                    assign = jax.vmap(
                        lambda k, tm: minibatch_assignment(
                            tm, batch_number, jax.random.fold_in(k, 7)
                        )
                    )(epoch_rngs, data["train_mask"])  # [S, n]
                    dst = edge_index[1]

                    def batch_body(carry, b):
                        params_s, opt_s = carry
                        train_b = data["train_mask"] * (assign == b)
                        local_m, cross_m = (
                            data["local_edges"],
                            data["cross_edges"],
                        )
                        if num_neighbor is not None:
                            keys = jax.vmap(
                                lambda k: jax.random.fold_in(
                                    jax.random.fold_in(k, 11), b
                                )
                            )(epoch_rngs)
                            keep = jax.vmap(
                                lambda m, k: cap_fan_in_jax(
                                    m, dst, int(num_neighbor), k
                                )
                            )(cross_m, keys)
                            local_m = local_m * keep
                            cross_m = keep
                        # disjoint fold-in domain from the assignment key
                        # (7) and the neighbor-cap keys (11)
                        step_rngs = jax.vmap(
                            lambda k: jax.random.fold_in(
                                jax.random.fold_in(k, 13), b
                            )
                        )(epoch_rngs)
                        params_s, opt_s, metrics = train_one_batch(
                            params_s, opt_s, local_m, cross_m, train_b, step_rngs
                        )
                        return (params_s, opt_s), metrics

                    (params_s, opt_s), metrics = jax.lax.scan(
                        batch_body,
                        (params_s, opt_s),
                        jnp.arange(batch_number, dtype=jnp.int32),
                    )
                    metrics = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)
                    return (params_s, opt_s), metrics

                epoch_rngs = jax.vmap(
                    lambda r: jax.random.split(r, epochs)
                )(rngs).swapaxes(0, 1)  # [E, S, 2]
                (params_s, _), metrics = jax.lax.scan(
                    epoch_body, (params0, opt0), epoch_rngs
                )

                contrib = jax.tree.map(
                    lambda ps: jnp.einsum(
                        "s,s...->...", weights, ps.astype(jnp.float32)
                    ),
                    params_s,
                )
                global_sum = jax.tree.map(
                    lambda c: jax.lax.psum(c, axis_name="clients"), contrib
                )
                total_weight = jax.lax.psum(jnp.sum(weights), axis_name="clients")
                new_global = jax.tree.map(
                    lambda s, g: (s / jnp.maximum(total_weight, 1e-12)).astype(
                        g.dtype
                    ),
                    global_sum,
                    global_params,
                )
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                    metrics,
                )
                return new_global, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(
                    P(),
                    {
                        "local_edges": P("clients"),
                        "cross_edges": P("clients"),
                        "provide": P("clients"),
                        "recv": P("clients"),
                        "train_mask": P("clients"),
                        "x": P(),
                        "edge_index": P(),
                        "targets": P(),
                    },
                    P("clients"),
                    P("clients"),
                ),
                out_specs=(P(), P()),
            )(global_params, data, weights, rngs)

        jitted = jax.jit(round_program, donate_argnums=(0,))

        def fn(global_params, weights, rngs):
            return jitted(global_params, weights, rngs, self._data)

        return fn

    # ------------------------------------------------------------------
    def _init_global_params(self):
        """Fresh init, or resume from a previous session's latest
        ``aggregated_model/round_N.npz`` + ``round_record.json`` (same
        semantics as ``SpmdFedAvgSession._init_global_params``)."""
        config = self.config
        resume_dir = config.algorithm_kwargs.get("resume_dir")
        if not resume_dir:
            return self.engine.init_params(config.seed), 1
        from ..util.resume import load_resume_state

        params, stats, last = load_resume_state(resume_dir)
        if params is None:
            get_logger().warning(
                "nothing resumable under %s; starting fresh", resume_dir
            )
            return self.engine.init_params(config.seed), 1
        self._stat = stats
        self._max_acc = max(
            (s.get("test_accuracy", 0.0) for s in self._stat.values()),
            default=0.0,
        )
        get_logger().info("resumed graph session from %s round %d", resume_dir, last)
        return params, last + 1

    def run(self) -> dict:
        config = self.config
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        init_params, start_round = self._init_global_params()
        # jnp.copy after placement: device_put of aligned host numpy (the
        # npz resume path) ALIASES the python-owned buffer, and the round
        # program donates these params — XLA must own the memory it reuses
        # (see SpmdFedAvgSession._place_params)
        global_params = jax.tree.map(
            jnp.copy, put_sharded(init_params, self._replicated)
        )
        weights = put_sharded(
            self._dataset_sizes, self._client_sharding
        )
        rng = jax.random.PRNGKey(config.seed)
        for _ in range(start_round - 1):  # keep the rng stream aligned
            rng, _unused = jax.random.split(rng)
        test_batch = make_graph_batch(self.dc.get_dataset(Phase.Test))
        model_dir = os.path.join(config.save_dir, "aggregated_model")
        os.makedirs(model_dir, exist_ok=True)
        with self._ckpt:  # flush async round checkpoints at exit
            for round_number in range(start_round, config.round + 1):
                self._before_round(round_number)
                rng, round_rng = jax.random.split(rng)
                client_rngs = put_sharded(
                    jax.random.split(round_rng, self.n_slots), self._client_sharding
                )
                # old global_params are donated into the round program —
                # any pending background fetch of them must finish first
                self._ckpt.barrier()
                global_params, train_metrics = self._watchdog.call(
                    lambda gp=global_params, w=weights, r=client_rngs: self._round_fn(
                        gp, w, r
                    ),
                    phase="round",
                    round_number=round_number,
                )
                # queued now so the fetch/write overlaps the evaluation
                self._ckpt.save_npz(
                    os.path.join(model_dir, f"round_{round_number}.npz"),
                    global_params,
                )
                metric = self._watchdog.call(
                    lambda gp=global_params: summarize_metrics(
                        self.engine.evaluate_single(gp, test_batch)
                    ),
                    phase="eval",
                    round_number=round_number,
                )
                metric.update(
                    maybe_slow_metrics(
                        self.config,
                        self.engine,
                        global_params,
                        jax.tree.map(lambda x: x[None], test_batch),
                    )
                )
                mb = self._round_payload_bytes / 1e6
                self._stat[round_number] = {
                    **{f"test_{k}": v for k, v in metric.items()},
                    "received_mb": mb,
                    "sent_mb": mb,
                }
                get_logger().info(
                    "round: %d, test accuracy %.4f loss %.4f "
                    "(spmd gnn, %.3f MB exchanged)",
                    round_number,
                    metric["accuracy"],
                    metric["loss"],
                    mb,
                )
                from ..util.checkpoint import atomic_json_dump

                # atomic: a crash mid-write must not leave a torn record
                # for load_resume_state to trip on
                atomic_json_dump(
                    os.path.join(save_dir, "round_record.json"), self._stat
                )
                if metric["accuracy"] > self._max_acc:
                    self._max_acc = metric["accuracy"]
                    # file copy of the queued round checkpoint, no 2nd fetch
                    self._ckpt.copy_last_to(
                        os.path.join(save_dir, "best_global_model.npz")
                    )
        return {"performance": self._stat}

    def _before_round(self, round_number: int) -> None:
        """Hook for per-round data changes (same compiled program — edge
        masks are program ARGUMENTS, so new masks don't recompile)."""

    @property
    def performance_stat(self) -> dict:
        return self._stat


class SpmdFedAASSession(SpmdFedGNNSession):
    """fed_aas: local-subgraph training (no exchange) with a per-round
    GraphSAGE-style fan-in cap resampled each round (threaded counterpart:
    ``method/fed_aas/FedAASWorker._before_round``)."""

    _dataloader_num_neighbor = False

    def __init__(self, *args, **kwargs) -> None:
        kwargs.setdefault("share_feature", False)
        super().__init__(*args, **kwargs)
        config = self.config
        self._num_neighbor = config.algorithm_kwargs.get(
            "num_neighbor", config.extra_hyper_parameters.get("num_neighbor")
        )
        self._base_local = np.asarray(self._data["local_edges"]).astype(bool)
        # real copy: edge_index is a device array (put_sharded), and a
        # zero-copy row view kept on self would alias the device buffer
        self._dst = np.asarray(self._data["edge_index"])[1].copy()

    def _before_round(self, round_number: int) -> None:
        if self._num_neighbor is None:
            return
        from ..ops.graph_sampling import cap_fan_in

        limit = int(self._num_neighbor)
        resampled = np.zeros_like(self._base_local, np.float32)
        for c in range(self._base_local.shape[0]):
            # same stream as the threaded FedAASWorker (slot == worker_id)
            rng = np.random.default_rng(
                self.config.seed * 1013 + c * 97 + round_number
            )
            resampled[c] = cap_fan_in(self._base_local[c], self._dst, limit, rng)
        masks = put_sharded(resampled, self._client_sharding)
        self._data["local_edges"] = masks
        self._data["cross_edges"] = masks
