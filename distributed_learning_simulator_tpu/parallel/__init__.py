from .mesh import make_mesh
from .spmd import SpmdFedAvgSession

__all__ = ["make_mesh", "SpmdFedAvgSession"]
