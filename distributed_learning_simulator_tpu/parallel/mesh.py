"""Device mesh construction.

The client axis of federated learning maps onto the hardware mesh
(SURVEY.md §5: "clients = leading pytree axis sharded over ICI").  On a
multi-host pod, ``jax.distributed`` has already made every chip visible;
here we only shape the axes: ``clients`` (data/client parallelism, rides
ICI) and an optional inner ``model`` axis for TP/FSDP of large client
models.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def use_mesh(mesh: Mesh):
    """Ambient-mesh context manager, version-compat: sessions whose models
    carry bare-``PartitionSpec`` sharding constraints (the MoE expert
    layout) need an ambient mesh at trace time.  Newer jax spells this
    ``jax.sharding.set_mesh``; on the jax 0.4 line that name does not
    exist and the ``Mesh`` object itself is the context manager — calling
    ``jax.sharding.set_mesh`` there raises ``AttributeError`` at the first
    FedOBD/fed_avg expert-parallel round (the pre-existing ``set_mesh``
    failure ROADMAP catalogued)."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh


def make_mesh(model_parallel: int = 1, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % model_parallel == 0, (n, model_parallel)
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, axis_names=("clients", "model"))


def create_hybrid_device_mesh(
    model_parallel: int = 1, devices=None, virtual_hosts: int | None = None
) -> Mesh:
    """A (hosts × chips) hybrid mesh: the ``clients`` axis spans hosts
    (outer blocks ride DCN), the ``model`` axis stays within one host's
    chips (ICI) — the t5x/maxtext hybrid layout (SNIPPETS [1]).  Client
    slots land contiguously per host, which is exactly the
    sharded-per-host layout ``PopulationStore`` persists, so a streamed
    cohort's host→device path never crosses DCN.

    On a real pod the blocks come from ``device.process_index``; jax's
    own ``mesh_utils.create_hybrid_device_mesh`` is tried first and the
    manual grouping is the fallback for backends whose device attributes
    confuse it.  ``virtual_hosts`` carves a SINGLE process's device list
    into contiguous per-"host" blocks instead — the
    ``--xla_force_host_platform_device_count`` CI harness that exercises
    this path end-to-end on CPU (tests/test_multihost.py).  Virtual
    blocks preserve device order, so the grid equals ``make_mesh``'s and
    results stay bit-identical to the flat layout."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % model_parallel == 0, (n, model_parallel)
    if virtual_hosts is not None:
        hosts = int(virtual_hosts)
        assert hosts >= 1 and n % hosts == 0, (n, hosts)
        per_host = n // hosts
        assert per_host % model_parallel == 0, (per_host, model_parallel)
        blocks = [
            np.asarray(devices[h * per_host : (h + 1) * per_host]).reshape(
                per_host // model_parallel, model_parallel
            )
            for h in range(hosts)
        ]
        grid = np.concatenate(blocks, axis=0)
        return Mesh(grid, axis_names=("clients", "model"))
    process_ids = sorted({d.process_index for d in devices})
    hosts = len(process_ids)
    if hosts <= 1:
        return make_mesh(model_parallel=model_parallel, devices=devices)
    per_host = n // hosts
    assert per_host % model_parallel == 0, (per_host, model_parallel)
    try:
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_host // model_parallel, model_parallel),
            dcn_mesh_shape=(hosts, 1),
            devices=devices,
        )
        return Mesh(grid, axis_names=("clients", "model"))
    except Exception as exc:  # noqa: BLE001 — backend-specific attrs
        from ..utils.logging import get_logger

        get_logger().warning(
            "mesh_utils.create_hybrid_device_mesh unavailable on this "
            "backend (%s); grouping devices by process_index manually",
            exc,
        )
    blocks = []
    for pid in process_ids:
        host_devices = [d for d in devices if d.process_index == pid]
        assert len(host_devices) == per_host, (pid, len(host_devices))
        blocks.append(
            np.asarray(host_devices).reshape(
                per_host // model_parallel, model_parallel
            )
        )
    grid = np.concatenate(blocks, axis=0)
    return Mesh(grid, axis_names=("clients", "model"))


def broadcast_selection_rows(rows: np.ndarray) -> np.ndarray:
    """Make host-built selection rows (cohort ids, weight rows) agree
    across a pod: broadcast process 0's rows to everyone and ASSERT the
    local rows matched — selection is seeded-deterministic, so a
    mismatch means a diverged rng stream, which must fail loudly rather
    than silently train different cohorts per host.  No-op (and no
    collective) with a single process."""
    rows = np.array(rows)
    if jax.process_count() == 1:
        return rows
    from jax.experimental import multihost_utils

    agreed = np.array(multihost_utils.broadcast_one_to_all(rows))
    if not np.array_equal(agreed, rows):
        raise RuntimeError(
            "host-built selection rows diverged across processes "
            f"(process {jax.process_index()} disagrees with process 0) — "
            "per-host rng streams are out of sync"
        )
    return agreed


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    retries: int | None = None,
    backoff_seconds: float = 1.0,
    config=None,
) -> None:
    """Join a multi-host pod (DCN between hosts, ICI within).

    Thin wrapper over ``jax.distributed.initialize`` — on TPU pods the three
    arguments auto-detect from the metadata server, so a bare call is enough
    on each host; afterwards ``jax.devices()`` is the GLOBAL device list and
    ``make_mesh`` spans the pod.  This is the framework's analogue of the
    reference's NCCL/MPI bring-up, except the reference never had one (its
    backend is single-host pipes — SURVEY.md §5): collectives ride ICI/DCN
    via the mesh, not a side channel.  Idempotent.

    ``retries`` (``config.multihost_init_retries``) re-attempts a failed
    join with exponential backoff (``backoff_seconds`` × 2^attempt) before
    giving up: pod bring-up is racy by nature — the coordinator host often
    starts seconds after its workers, and preempted hosts rejoin a
    coordinator that is itself still restarting.  The terminal error names
    the unreachable coordinator instead of surfacing a bare connect error
    with no address to debug."""
    if retries is None:
        retries = (
            int(getattr(config, "multihost_init_retries", 0) or 0)
            if config is not None
            else 0
        )
    # NOT jax.process_count(): that would touch the backend, and
    # jax.distributed.initialize() must run before backend init.
    # ``is_initialized`` does not exist on every jax version — fall back to
    # probing the distributed global state's client handle.
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return  # already joined
    else:
        state = getattr(jax.distributed, "global_state", None)
        if state is not None and getattr(state, "client", None) is not None:
            return  # already joined
    explicit_cluster = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    last_error: Exception | None = None
    for attempt in range(max(0, int(retries)) + 1):
        if attempt:
            import time

            delay = backoff_seconds * (2 ** (attempt - 1))
            from ..utils.logging import get_logger

            get_logger().warning(
                "initialize_multihost: join attempt %d/%d failed (%s); "
                "retrying in %.1fs",
                attempt,
                retries + 1,
                last_error,
                delay,
            )
            time.sleep(delay)
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            return
        except (ValueError, RuntimeError) as exc:
            last_error = exc
            if not explicit_cluster:
                # bare call with no coordinator configured: single-process
                # run — no cluster to retry against
                return
    raise RuntimeError(
        "initialize_multihost: coordinator "
        f"{coordinator_address or '<auto-detected>'} unreachable after "
        f"{retries + 1} attempt(s) "
        f"(num_processes={num_processes}, process_id={process_id}); "
        "check that the coordinator host is up and the address/port is "
        "routable from this host, or raise config.multihost_init_retries "
        f"for racier bring-ups. Last error: {last_error}"
    ) from last_error


def put_sharded(host_data, sharding):
    """Place host arrays onto the mesh, multi-host aware: with one process
    this is ``device_put``; on a pod every process holds the FULL global
    array and ``make_array_from_process_local_data`` slices out the
    per-process portion (``global_shape == local_data.shape`` tells JAX the
    local data is the actual target array, so each host keeps only its
    addressable shards)."""
    if jax.process_count() == 1:
        return jax.device_put(host_data, sharding)

    def _place(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape=x.shape
        )

    return jax.tree.map(_place, host_data)


def client_slots(
    worker_number: int, mesh: Mesh, axes: tuple[str, ...] = ("clients",)
) -> int:
    """Pad the client count to a multiple of the slot axes' total size so
    every device carries the same number of client slots (zero-weight
    padding mirrors the reference's time-multiplexing of workers onto
    devices, ``algorithm_factory.py:38-58``).  FSDP sessions partition
    slots over ``("clients", "model")``."""
    n = 1
    for axis in axes:
        n *= mesh.shape[axis]
    return ((worker_number + n - 1) // n) * n
