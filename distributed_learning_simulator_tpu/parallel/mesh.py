"""Device mesh construction.

The client axis of federated learning maps onto the hardware mesh
(SURVEY.md §5: "clients = leading pytree axis sharded over ICI").  On a
multi-host pod, ``jax.distributed`` has already made every chip visible;
here we only shape the axes: ``clients`` (data/client parallelism, rides
ICI) and an optional inner ``model`` axis for TP/FSDP of large client
models.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(model_parallel: int = 1, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % model_parallel == 0, (n, model_parallel)
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, axis_names=("clients", "model"))


def client_slots(worker_number: int, mesh: Mesh) -> int:
    """Pad the client count to a multiple of the mesh's client axis so every
    device carries the same number of client slots (zero-weight padding
    mirrors the reference's time-multiplexing of workers onto devices,
    ``algorithm_factory.py:38-58``)."""
    n = mesh.shape["clients"]
    return ((worker_number + n - 1) // n) * n
