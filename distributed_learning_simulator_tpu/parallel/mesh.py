"""Device mesh construction.

The client axis of federated learning maps onto the hardware mesh
(SURVEY.md §5: "clients = leading pytree axis sharded over ICI").  On a
multi-host pod, ``jax.distributed`` has already made every chip visible;
here we only shape the axes: ``clients`` (data/client parallelism, rides
ICI) and an optional inner ``model`` axis for TP/FSDP of large client
models.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def use_mesh(mesh: Mesh):
    """Ambient-mesh context manager, version-compat: sessions whose models
    carry bare-``PartitionSpec`` sharding constraints (the MoE expert
    layout) need an ambient mesh at trace time.  Newer jax spells this
    ``jax.sharding.set_mesh``; on the jax 0.4 line that name does not
    exist and the ``Mesh`` object itself is the context manager — calling
    ``jax.sharding.set_mesh`` there raises ``AttributeError`` at the first
    FedOBD/fed_avg expert-parallel round (the pre-existing ``set_mesh``
    failure ROADMAP catalogued)."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh


def make_mesh(model_parallel: int = 1, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % model_parallel == 0, (n, model_parallel)
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, axis_names=("clients", "model"))


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    retries: int | None = None,
    backoff_seconds: float = 1.0,
    config=None,
) -> None:
    """Join a multi-host pod (DCN between hosts, ICI within).

    Thin wrapper over ``jax.distributed.initialize`` — on TPU pods the three
    arguments auto-detect from the metadata server, so a bare call is enough
    on each host; afterwards ``jax.devices()`` is the GLOBAL device list and
    ``make_mesh`` spans the pod.  This is the framework's analogue of the
    reference's NCCL/MPI bring-up, except the reference never had one (its
    backend is single-host pipes — SURVEY.md §5): collectives ride ICI/DCN
    via the mesh, not a side channel.  Idempotent.

    ``retries`` (``config.multihost_init_retries``) re-attempts a failed
    join with exponential backoff (``backoff_seconds`` × 2^attempt) before
    giving up: pod bring-up is racy by nature — the coordinator host often
    starts seconds after its workers, and preempted hosts rejoin a
    coordinator that is itself still restarting.  The terminal error names
    the unreachable coordinator instead of surfacing a bare connect error
    with no address to debug."""
    if retries is None:
        retries = (
            int(getattr(config, "multihost_init_retries", 0) or 0)
            if config is not None
            else 0
        )
    # NOT jax.process_count(): that would touch the backend, and
    # jax.distributed.initialize() must run before backend init.
    # ``is_initialized`` does not exist on every jax version — fall back to
    # probing the distributed global state's client handle.
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return  # already joined
    else:
        state = getattr(jax.distributed, "global_state", None)
        if state is not None and getattr(state, "client", None) is not None:
            return  # already joined
    explicit_cluster = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    last_error: Exception | None = None
    for attempt in range(max(0, int(retries)) + 1):
        if attempt:
            import time

            delay = backoff_seconds * (2 ** (attempt - 1))
            from ..utils.logging import get_logger

            get_logger().warning(
                "initialize_multihost: join attempt %d/%d failed (%s); "
                "retrying in %.1fs",
                attempt,
                retries + 1,
                last_error,
                delay,
            )
            time.sleep(delay)
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            return
        except (ValueError, RuntimeError) as exc:
            last_error = exc
            if not explicit_cluster:
                # bare call with no coordinator configured: single-process
                # run — no cluster to retry against
                return
    raise RuntimeError(
        "initialize_multihost: coordinator "
        f"{coordinator_address or '<auto-detected>'} unreachable after "
        f"{retries + 1} attempt(s) "
        f"(num_processes={num_processes}, process_id={process_id}); "
        "check that the coordinator host is up and the address/port is "
        "routable from this host, or raise config.multihost_init_retries "
        f"for racier bring-ups. Last error: {last_error}"
    ) from last_error


def put_sharded(host_data, sharding):
    """Place host arrays onto the mesh, multi-host aware: with one process
    this is ``device_put``; on a pod every process holds the FULL global
    array and ``make_array_from_process_local_data`` slices out the
    per-process portion (``global_shape == local_data.shape`` tells JAX the
    local data is the actual target array, so each host keeps only its
    addressable shards)."""
    if jax.process_count() == 1:
        return jax.device_put(host_data, sharding)

    def _place(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape=x.shape
        )

    return jax.tree.map(_place, host_data)


def client_slots(
    worker_number: int, mesh: Mesh, axes: tuple[str, ...] = ("clients",)
) -> int:
    """Pad the client count to a multiple of the slot axes' total size so
    every device carries the same number of client slots (zero-weight
    padding mirrors the reference's time-multiplexing of workers onto
    devices, ``algorithm_factory.py:38-58``).  FSDP sessions partition
    slots over ``("clients", "model")``."""
    n = 1
    for axis in axes:
        n *= mesh.shape[axis]
    return ((worker_number + n - 1) // n) * n
