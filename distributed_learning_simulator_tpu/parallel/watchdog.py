"""Deadline watchdog for the SPMD executor (VERDICT r2 item 4).

The threaded executor already has a *message-progress* watchdog
(``training._watchdog_loop``); the SPMD sessions had none — a wedged
collective (multi-host especially) blocked ``run()`` forever with no
diagnostic.  ``config.watchdog_seconds`` now also guards the default
executor: every blocking device call in a session's round loop (the round
program and the evaluation fetch) runs under a deadline; exceeding it
raises ``TimeoutError`` with round number, phase, and mesh shape instead of
hanging (SURVEY.md §5 TPU plan: "a 'deadline' watchdog on collective
waits").

The guarded call runs on a daemon thread — a blocked XLA execution cannot
be interrupted from Python, so on timeout the call is *abandoned* (the
process is aborting anyway) and the controller raises.

The FIRST guarded call per phase gets ``compile_grace`` × the deadline:
round-program compilation legitimately takes minutes on first invocation
and must not trip a deadline sized for steady-state rounds.
"""

import threading

from ..utils.logging import get_logger


class DeadlineWatchdog:
    def __init__(self, seconds: float, mesh=None, compile_grace: float = 10.0):
        self.seconds = float(seconds or 0.0)
        self.mesh = mesh
        self.compile_grace = compile_grace
        self._seen_phases: set[str] = set()

    @classmethod
    def from_config(cls, config, mesh=None) -> "DeadlineWatchdog":
        return cls(getattr(config, "watchdog_seconds", 0.0) or 0.0, mesh=mesh)

    def call(self, fn, *, phase: str, round_number: int):
        """Run ``fn()`` under the deadline; raise TimeoutError on stall.

        The guarded call is forced synchronous (``jax.block_until_ready`` on
        its result) — jitted calls dispatch asynchronously, so without the
        block a wedged round would "return" instantly and hang later at an
        unguarded fetch.  ``phase`` keys the compile grace: distinct
        programs (e.g. FedOBD phase 1 vs phase 2) must use distinct phase
        labels so each first compile gets the grace."""
        if self.seconds <= 0:
            return fn()
        deadline = self.seconds
        if phase not in self._seen_phases:
            self._seen_phases.add(phase)
            deadline *= self.compile_grace  # first call compiles
        result: dict = {}

        def target() -> None:
            try:
                import jax

                result["value"] = jax.block_until_ready(fn())
            except BaseException as exc:  # surfaced on the caller thread
                result["error"] = exc

        thread = threading.Thread(
            target=target, daemon=True, name=f"spmd-{phase}-r{round_number}"
        )
        thread.start()
        thread.join(deadline)
        if thread.is_alive():
            mesh_shape = dict(self.mesh.shape) if self.mesh is not None else "?"
            diag = (
                f"watchdog: SPMD {phase!r} stalled > {deadline:.0f}s "
                f"at round {round_number} (mesh {mesh_shape}); aborting"
            )
            get_logger().error(diag)
            raise TimeoutError(diag)
        if "error" in result:
            raise result["error"]
        return result["value"]


__all__ = ["DeadlineWatchdog"]
