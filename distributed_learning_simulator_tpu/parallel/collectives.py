"""Axis-name collectives with non-default gradient rules.

``psum_symmetric`` is the boundary piece of the SPMD sequence-parallel
gradient story (``parallel/spmd_sp.py``): the sp-mode model pools its
sequence-sharded activations with a psum, which makes every parameter
DOWNSTREAM of the pool see replicated values (its per-device gradient is
already the full gradient) while every parameter UPSTREAM contributes
only its shard's partial gradient.  No single uniform reduction of the
gradient tree fixes both — unless the pooling boundary rescales the
upstream cotangent by the axis size.  Forward ``psum``, backward
``psum`` (the cotangent is replicated, so the backward psum is exactly
a multiply by the axis size) makes upstream per-device grads equal
``sp * partial``; a ``pmean`` over the whole gradient tree then yields
the correct total gradient for BOTH sides:

* upstream leaf: ``pmean_d(sp * partial_d) = sum_d partial_d``  (total)
* downstream leaf: ``pmean_d(full) = full``

The reference has no analogue (its data parallelism all-reduces
homogeneous grads over NCCL); this rule exists because sequence
parallelism mixes sharded and replicated compute in one backward.
"""

import functools

import jax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_symmetric(x, axis_name):
    """``lax.psum`` whose transpose is also a ``psum`` (equivalently: the
    backward multiplies the replicated cotangent by the axis size)."""
    return jax.lax.psum(x, axis_name)


def _psum_symmetric_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _psum_symmetric_bwd(axis_name, _, ct):
    return (jax.lax.psum(ct, axis_name),)


psum_symmetric.defvjp(_psum_symmetric_fwd, _psum_symmetric_bwd)
