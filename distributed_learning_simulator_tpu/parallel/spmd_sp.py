"""FedAvg rounds with sequence-parallel clients as one SPMD program.

The client-axis sessions (``spmd.py``) shard CLIENTS over the mesh; this
session gives the whole mesh to each client's MODEL instead: an
``("sp",)`` mesh shards the sequence axis, clients train one after
another inside the round program (``lax.scan``), and the weighted
aggregation accumulates on device.  This is the SPMD home of
``model_kwargs.sequence_parallel`` — the threaded executor supports the
same knob by letting the model own an ``sp_mesh``; here the SESSION owns
the one ``shard_map`` and the model runs in its ``sp_axis`` mode (local
blocks, ring/Ulysses by axis name, psum pooling —
``models/long_context.py``).

Design notes:

* The run loop, selection, eval, round records, checkpoints, watchdog,
  and resume are ALL inherited from ``SpmdFedAvgSession`` — this class
  only changes how a round's device program is laid out.  The rng stream
  is therefore identical to the client-axis session's, which is what the
  equivalence test pins (sp=1 matches ``SpmdFedAvgSession`` to float
  accumulation order).
* Unselected clients still flow through the scan (masked to weight 0) —
  SPMD needs a uniform program; with the few-but-huge clients this
  session targets, the waste is bounded by the selection ratio.
* Central evaluation uses the UNSHARDED engine (single-device semantics,
  Pallas fused/streaming attention at long sequence) — the sp-mode model
  shares its parameter structure exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.engine import ComputeEngine
from .mesh import put_sharded
from .spmd import (
    SpmdFedAvgSession,
    scan_weighted_clients,
    shard_map_compat,
    whole_mesh_session_shapes,
)


class SingleDeviceEvalMixin:
    """Central evaluation on ONE device for whole-mesh-per-client
    sessions (sp/pp): the base class evaluates on mesh-replicated arrays,
    which partitions the eval jit over the session mesh — wasted for a
    replicated program and incompatible with the Pallas interpreter
    (``DLS_TPU_FUSED_ATTN=interpret``: an ``io_callback`` cannot live
    inside a partitioned program)."""

    #: the single-device eval batches live on their OWN attribute — the
    #: fused-horizon path builds mesh-replicated ``_eval_batches`` for its
    #: in-program eval (``_ensure_eval_batches``), and a fused run that
    #: drops to a per-round tail must not hand those mesh-placed arrays
    #: to this single-device jit
    _host_eval_batches = None

    def _evaluate(self, global_params) -> dict:
        if jax.process_count() > 1:
            # a multi-host pod cannot device_put to one global device
            # (non-addressable from the other processes) — keep the base
            # class's put_sharded replicated path there
            return super()._evaluate(global_params)
        from ..engine.engine import maybe_slow_metrics, summarize_metrics
        from ..ml_type import MachineLearningPhase as Phase

        device = self.mesh.devices.flat[0]
        if self._host_eval_batches is None:
            from ..engine.batching import make_epoch_batches

            test = self.dc.get_dataset(Phase.Test)
            self._host_eval_batches = jax.device_put(
                make_epoch_batches(test, self.config.batch_size), device
            )
        params = jax.device_put(global_params, device)
        summed = self.engine.evaluate(params, self._host_eval_batches)
        metric = summarize_metrics(summed)
        metric.update(
            maybe_slow_metrics(
                self.config, self.engine, params, self._host_eval_batches
            )
        )
        return metric


class SpmdSequenceParallelSession(SingleDeviceEvalMixin, SpmdFedAvgSession):
    #: whole-mesh layout routed through the shared fused-round machinery:
    #: selection gather, round-horizon fusion and the update guard all
    #: apply (spmd.py::_wrap_round_programs)
    _whole_mesh_fused = True

    def __init__(
        self,
        config,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        sequence_parallel: int,
        sp_impl: str = "ring",
    ) -> None:
        devices = jax.devices()
        if sequence_parallel > len(devices):
            raise ValueError(
                f"sequence_parallel={sequence_parallel} exceeds the "
                f"{len(devices)}-device mesh"
            )
        sp_mesh = Mesh(
            np.asarray(devices[:sequence_parallel]), axis_names=("sp",)
        )
        # the sp-mode twin: same factory, same parameter structure, forward
        # written for local blocks inside THIS session's shard_map
        from ..models import create_model_context

        kwargs = dict(getattr(config, "model_kwargs", {}) or {})
        kwargs.pop("sequence_parallel", None)
        kwargs.pop("sp_mesh", None)
        kwargs["sp_axis"] = "sp"
        kwargs.setdefault("sp_impl", sp_impl)
        sp_model_ctx = create_model_context(
            config.model_name, dataset_collection, **kwargs
        )
        sp_model_ctx.compute_dtype = model_ctx.compute_dtype
        # grad_sync_axis: each device's backward yields a PARTIAL gradient
        # (its sequence shard); the engine pmeans over "sp" before the
        # optimizer update, with the model's psum_symmetric pooling making
        # that reduction exact for the post-pool params too
        # (parallel/collectives.py) — without it the shards silently
        # applied divergent updates (round-3 VERDICT item 1)
        self._sp_engine = ComputeEngine(
            sp_model_ctx,
            engine.hyper_parameter,
            total_steps=engine.total_steps,
            grad_sync_axis="sp",
        )
        super().__init__(
            config, dataset_collection, model_ctx, engine, practitioners,
            mesh=sp_mesh,
        )
        # the base placed the stacked client data replicated (no clients
        # axis in this mesh); re-place the sequence-bearing leaves sharded
        # over "sp" so each device holds only its blocks
        self._data = {
            k: jax.device_put(
                v,
                NamedSharding(
                    self.mesh,
                    P(None, None, None, "sp") if v.ndim >= 4 else P(),
                ),
            )
            for k, v in self._data.items()
        }

    def _leaf_spec(self, shape, name: str = "") -> P:
        return P()  # params replicated; the sequence axis is the sharded one

    def _build_round_fn(self):
        engine = self._sp_engine
        epochs = self.config.epoch
        mesh = self.mesh
        guard_active = self._update_guard
        max_update_norm = self._max_update_norm
        _, metrics_shape = whole_mesh_session_shapes(self)

        def round_program(global_params, weights, rngs, data, val):
            def shard_body(global_params, data, val, weights, rngs):
                # data leaves here are LOCAL sequence blocks ([C, nb, B, L/sp]
                # for the token input); params/weights/rngs are replicated
                return scan_weighted_clients(
                    engine, epochs, global_params, data, weights, rngs,
                    metrics_shape, val_data=val if val else None,
                    guard_active=guard_active,
                    max_update_norm=max_update_norm,
                    compute_dtype=self._resident_dtype,
                )

            def seq_specs(tree):
                return jax.tree.map(
                    lambda x: P(None, None, None, "sp")
                    if x.ndim >= 4
                    else P(),
                    tree,
                )

            return shard_map_compat(
                shard_body,
                mesh,
                in_specs=(P(), seq_specs(data), seq_specs(val), P(), P()),
                out_specs=(P(), P()),
            )(global_params, data, val, weights, rngs)

        # gather twin + horizon fusion + dispatch come from the shared
        # machinery; the gather's per-leaf sharding-preserving take keeps
        # the sequence axis sharded through the slot gather
        return self._wrap_round_programs(round_program)


def build_sequence_parallel_session(ctx, session_args, session_kwargs):
    config = ctx.config
    model_kwargs = dict(config.model_kwargs)
    return SpmdSequenceParallelSession(
        *session_args,
        sequence_parallel=int(model_kwargs.get("sequence_parallel", 0)),
        sp_impl=str(model_kwargs.get("sp_impl", "ring")),
    )
