"""FedOBD with expert-parallel MoE clients — the north-star method on a
model-sharding axis (VERDICT r4 item 3).

Round 4 left ``expert_parallel`` fed_avg-only; this session composes it
with the flagship FedOBD method (reference workload
``fed_obd_train.sh`` / BASELINE.json "fed_obd + fed_obd_sq").  The key
observation making the composition cheap: every FedOBD-specific op —
per-block L2 scoring, greedy keep under the budget, NNADQ/QSGD
distortion, ``complete()``'s where-fallback, the weighted sum — is a
per-leaf elementwise/reduction op, so it commutes with GSPMD's expert
sharding.  The layout is therefore ``spmd_ep.py``'s: an ``("ep",)``
mesh, expert-stacked kernels stored ``P("ep", None, None)``, clients
scanned one after another in a plain ``jit`` whose sharding constraints
(``models/moe.py``) let XLA place the dispatch/combine all-to-alls.

The per-client math (``local_train``: block dropout, codec, optimizer
continuation) is inherited VERBATIM from ``SpmdFedOBDSession`` — only
``_wrap_phase_program`` (how clients map onto the mesh) changes, so the
equivalence test pins ep=N against the client-axis FedOBD trajectory
with the identical rng stream (``jax.random.split``'s per-index streams
do not depend on the slot count).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..engine.engine import ComputeEngine
from ..ops.pytree import tree_cast
from .mesh import use_mesh
from .spmd import guarded_average
from .spmd_obd import SpmdFedOBDSession, _masked_slot_merge


def obd_scan_round_program(
    local_train, qdq, phase_two: bool, guard_active: bool = False,
    compute_dtype=None,
):
    """The whole-mesh-per-client FedOBD round: clients as a ``lax.scan``
    with on-device weighted accumulation and the quantized broadcast —
    shared by the expert-parallel (GSPMD jit) and sequence-parallel
    (session shard_map) layouts.

    Parity with the client-axis shard_body (``spmd_obd.py``):

    * under an ACTIVE selection the phase-1 carry (``opt_state_s`` not
      None) is participation-MERGED after the scan — a slot's phase-2
      seed is the state from its last participation, matching the
      client-axis (and threaded) semantics on both dense and gather
      paths;
    * ``guard_active``: ``local_train`` already zeroed each rejected
      client's contribution (the shared guard); here the total weight
      becomes the sum of the guard's per-slot EFFECTIVE weights
      (``_eff_weight`` accumulated through the metric sum) and a
      zero-survivor round keeps the old global
      (:func:`spmd.guarded_average`).

    ``compute_dtype`` (AMP residency): cast the broadcast ONCE before the
    client scan and hand every client the same compute-dtype view; the
    f32 anchors (deltas, dropped-block fallback, exact average) stay on
    ``global_params``."""

    def round_program(
        global_params, opt_state_s, weights, rngs, bcast_rng, data
    ):
        compute_global = (
            tree_cast(global_params, compute_dtype)
            if compute_dtype is not None
            else global_params
        )
        zero_params = jax.tree.map(
            lambda g: jnp.zeros(g.shape, jnp.float32), global_params
        )
        first = jax.tree.map(lambda x: x[0], data)
        _, _, met_shapes = jax.eval_shape(
            local_train, global_params, first, weights[0], rngs[0], None,
            compute_global=compute_global,
        )
        zero_metrics = jax.tree.map(
            lambda s: jnp.zeros((), s.dtype), met_shapes
        )

        def client_body(acc, xs):
            if phase_two:
                cdata, w, r, opt = xs
            else:
                cdata, w, r = xs
                opt = None
            contrib, opt_out, met = local_train(
                global_params, cdata, w, r, opt,
                compute_global=compute_global,
            )
            acc_sum, acc_met = acc
            acc_sum = jax.tree.map(lambda a, c: a + c, acc_sum, contrib)
            # NOTE: metrics sum unconditionally, matching the client-axis
            # shard_body (unselected slots still train, masked only in
            # the weighted param sum)
            acc_met = jax.tree.map(lambda a, m: a + m, acc_met, met)
            return (acc_sum, acc_met), opt_out

        xs = (
            (data, weights, rngs, opt_state_s)
            if phase_two
            else (data, weights, rngs)
        )
        (local_sum, metrics), opt_out = jax.lax.scan(
            client_body, (zero_params, zero_metrics), xs
        )
        if not phase_two and opt_state_s is not None:
            # selection-aware phase 1: the carried buffer keeps the
            # unselected slots' states (their last participation); only
            # selected slots take this round's trained states
            opt_out = _masked_slot_merge(weights > 0, opt_out, opt_state_s)
        if guard_active:
            # survivor renormalization: the summed _eff_weight IS the
            # total of the guard's effective weights (rejected slots at
            # exactly zero); zero survivors keep the old global
            metrics = dict(metrics)
            total_weight = metrics.pop("_eff_weight")
            new_global = guarded_average(
                local_sum, total_weight, global_params
            )
        else:
            total_weight = jnp.maximum(jnp.sum(weights), 1e-12)
            new_global = jax.tree.map(
                lambda s, g: (s / total_weight).astype(g.dtype),
                local_sum,
                global_params,
            )
        bcast = {}
        bcast_bits = jnp.float32(0.0)
        for i, (k, v) in enumerate(new_global.items()):
            vq, bits = qdq(
                v.astype(jnp.float32), jax.random.fold_in(bcast_rng, i)
            )
            bcast[k] = vq.astype(v.dtype)
            bcast_bits += bits * v.size
        metrics = dict(metrics, bcast_bits=bcast_bits)
        return new_global, bcast, opt_out, metrics

    return round_program


class SpmdFedOBDExpertParallelSession(SpmdFedOBDSession):
    #: whole-mesh scan layout routed through the shared fused machinery
    #: (spmd_obd.py::_finish_obd_phase_fn): selection gather,
    #: round-horizon fusion and the update guard all apply
    _whole_mesh_fused = True

    def __init__(
        self,
        config,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        expert_parallel: int,
        codec: str = "nnadq",
    ) -> None:
        devices = jax.devices()
        if expert_parallel > len(devices):
            raise ValueError(
                f"expert_parallel={expert_parallel} exceeds the "
                f"{len(devices)}-device mesh"
            )
        kwargs = dict(getattr(config, "model_kwargs", {}) or {})
        kwargs.pop("expert_parallel", None)
        self._n_experts = int(kwargs.get("n_experts", 4))
        if self._n_experts % expert_parallel:
            raise ValueError(
                f"expert_parallel={expert_parallel} must divide "
                f"n_experts={self._n_experts}"
            )
        ep_mesh = Mesh(
            np.asarray(devices[:expert_parallel]), axis_names=("ep",)
        )
        from ..models import create_model_context

        kwargs["ep_axis"] = "ep"
        ep_model_ctx = create_model_context(
            config.model_name, dataset_collection, **kwargs
        )
        ep_model_ctx.compute_dtype = model_ctx.compute_dtype
        self._ep_engine = ComputeEngine(
            ep_model_ctx, engine.hyper_parameter, total_steps=engine.total_steps
        )
        super().__init__(
            config, dataset_collection, model_ctx, engine, practitioners,
            mesh=ep_mesh, codec=codec,
        )
        # the ("ep",) mesh has no clients axis, so n_slots is bare
        # worker_number — but the per-round client-key contract splits to
        # the DEFAULT client-axis slot count (split prefixes depend on
        # the count on non-partitionable threefry; see
        # SpmdFedOBDSession._stream_slots)
        from .mesh import client_slots, make_mesh

        self._stream_slots = client_slots(config.worker_number, make_mesh())
        if not any(spec != P() for spec in self._param_specs.values()):
            raise ValueError(
                f"expert_parallel set but model {config.model_name!r} has no "
                "expert-stacked kernels to shard (expected an MoE model, "
                "e.g. MoETransformerClassificationModel)"
            )

    def _train_engine(self):
        return self._ep_engine

    def _leaf_spec(self, shape, name: str = "") -> P:
        # same declaration-driven rule as SpmdExpertParallelSession
        from ..models.moe import is_expert_param

        leaf = jax.ShapeDtypeStruct(shape, jnp.float32)
        if is_expert_param(name, leaf, self._n_experts):
            return P("ep", None, None)
        return P()

    def _round_mesh_context(self):
        # bare-PartitionSpec constraints inside the MoE model resolve
        # against the ambient mesh (version-compat helper: jax 0.4 has
        # no jax.sharding.set_mesh)
        return use_mesh(self.mesh)

    def _wrap_phase_program(self, local_train, qdq, phase_two: bool):
        round_program = obd_scan_round_program(
            local_train, qdq, phase_two, guard_active=self._update_guard,
            compute_dtype=self._resident_dtype,
        )
        # pin the aggregate AND broadcast to the stored expert layout so
        # donated round-over-round buffers never reshard; jit, gather
        # twin, horizon registration and dispatch (all under use_mesh via
        # _round_mesh_context) come from the shared machinery
        return self._finish_obd_phase_fn(
            round_program,
            phase_two,
            out_shardings=(
                self._param_shardings,
                self._param_shardings,
                # the donated opt carry enters replicated — pin its output
                # replicated too or GSPMD's expert-sharded choice trips a
                # donation aliasing size mismatch at runtime
                self._opt_carry_out_sharding(),
                None,
            ),
        )


def build_obd_expert_parallel_session(ctx, session_args, codec: str):
    model_kwargs = dict(ctx.config.model_kwargs)
    return SpmdFedOBDExpertParallelSession(
        *session_args,
        expert_parallel=int(model_kwargs.get("expert_parallel", 0)),
        codec=codec,
    )
