"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

The reference never shards a single model's computation (SURVEY.md §5
"long-context / sequence parallelism: absent") — its only axis is the
client axis.  This module adds the missing model-sharding mode natively:
a homogeneous stack of S identical stages (transformer encoder trunk,
DenseNet block sequence, ...) laid out one-stage-per-device over a ``pp``
mesh axis, fed with M microbatches in the classic GPipe bubble schedule.

The whole schedule is ONE ``lax.scan`` of ``M + S - 1`` ticks inside
``shard_map``; the stage-to-stage handoff is a ``lax.ppermute`` shift over
ICI.  Because every collective and select is differentiable, ``jax.grad``
through :func:`pipeline_apply` yields the reverse (backward) pipeline
schedule automatically — no hand-written backward pass.

Design rules that keep XLA happy:

* stages must be *homogeneous*: one ``stage_fn`` with stacked parameters
  ``[S, ...]`` sharded ``P("pp", ...)`` — the SPMD program is identical on
  every device, stage identity comes from ``axis_index``;
* the scanned carry (a pytree of ``[mb, ...]`` arrays) must have the same
  shape at stage input and output (true for encoder trunks);
* microbatch selection and the last-stage output write are masked
  ``where``/``dynamic_update_slice`` ops — static shapes, no host control
  flow.
"""

from collections.abc import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_body(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    axis_name: str,
    n_stages: int,
    params_local: bool = False,
    symmetric_out: bool = False,
):
    """The shard_map body: run ``microbatches`` (pytree of ``[M, mb, ...]``)
    through the S-stage pipeline.  ``stage_params`` is this device's slice
    ``[1, ...]`` of the stacked stage parameters (already the bare local
    slice when ``params_local`` — the session-owned shard_map mode, where
    the caller's in_specs did the slicing); ``stage_fn(params, tree)``
    maps a carry pytree to a carry pytree of identical structure/shape.

    Returns the last stage's outputs ``[M, mb, ...]``, already ``psum``-ed
    over the pipeline axis so the result is replicated (only the last stage
    contributes non-zeros).  ``symmetric_out`` routes that psum through
    ``psum_symmetric`` — required when ``jax.grad`` runs INSIDE the
    enclosing shard_map (``parallel/spmd_pp.py``: the ×S upstream
    cotangent makes one per-leaf sync rule correct for the whole tree);
    the plain psum is correct when grad runs OUTSIDE (the threaded mode,
    where shard_map's own transpose machinery owns the accounting).
    """
    s_idx = jax.lax.axis_index(axis_name)
    params_here = (
        stage_params
        if params_local
        else jax.tree.map(lambda p: p[0], stage_params)
    )
    n_micro = jax.tree.leaves(microbatches)[0].shape[0]
    n_ticks = n_micro + n_stages - 1

    zero_carry = jax.tree.map(lambda x: jnp.zeros_like(x[0]), microbatches)
    outputs0 = jax.tree.map(jnp.zeros_like, microbatches)

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests microbatch t (zeros once the feed is exhausted);
        # later stages ingest what the previous stage permuted to them
        feed = jax.tree.map(
            lambda mb: jnp.where(
                t < n_micro, jax.lax.dynamic_index_in_dim(
                    mb, jnp.minimum(t, n_micro - 1), keepdims=False
                ), jnp.zeros_like(mb[0])
            ),
            microbatches,
        )
        x_in = jax.tree.map(
            lambda f, b: jnp.where(s_idx == 0, f, b), feed, buf
        )
        y = stage_fn(params_here, x_in)
        # microbatch (t - S + 1) leaves the pipe at the last stage this tick
        out_idx = t - (n_stages - 1)
        write = (s_idx == n_stages - 1) & (out_idx >= 0)
        safe_idx = jnp.clip(out_idx, 0, n_micro - 1)
        outputs = jax.tree.map(
            lambda o, v: jax.lax.dynamic_update_index_in_dim(
                o,
                jnp.where(
                    write, v, jax.lax.dynamic_index_in_dim(o, safe_idx, keepdims=False)
                ),
                safe_idx,
                0,
            ),
            outputs,
            y,
        )
        # shift every stage's output one stage forward; stage 0 receives
        # zeros (no (S-1, 0) edge in the permutation)
        buf = jax.tree.map(
            lambda v: jax.lax.ppermute(
                v, axis_name, [(i, i + 1) for i in range(n_stages - 1)]
            ),
            y,
        )
        return (buf, outputs), None

    (_, outputs), _ = jax.lax.scan(
        tick, (zero_carry, outputs0), jnp.arange(n_ticks)
    )
    # only the last stage wrote real values; replicate them
    from .collectives import psum_symmetric

    def reduce(v):
        # integer leaves (pad masks, rng keys) carry no gradient — keep
        # them on the plain psum even in symmetric mode
        if symmetric_out and jnp.issubdtype(v.dtype, jnp.inexact):
            return psum_symmetric(v, axis_name)
        return jax.lax.psum(v, axis_name)

    return jax.tree.map(
        lambda o: reduce(
            jnp.where(s_idx == n_stages - 1, o, jnp.zeros_like(o))
        ),
        outputs,
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches,
    mesh: Mesh,
    *,
    axis_name: str = "pp",
):
    """Run a homogeneous pipeline over ``mesh``'s ``axis_name`` axis.

    ``stage_params``: pytree stacked on a leading ``[S]`` axis (sharded or
    not — in_specs shard it here).  ``microbatches``: pytree of
    ``[M, mb, ...]`` arrays, replicated.  Returns the pipeline output
    ``[M, mb, ...]``, replicated.  Differentiable; ``jax.grad`` yields the
    backward pipeline schedule (reverse ppermute shifts) for free.
    """
    from .spmd import shard_map_compat

    n_stages = mesh.shape[axis_name]

    def body(stage_params, microbatches):
        return pipeline_body(
            stage_fn,
            stage_params,
            microbatches,
            axis_name=axis_name,
            n_stages=n_stages,
        )

    return shard_map_compat(
        body,
        mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
    )(stage_params, microbatches)


def stack_stage_params(init_one: Callable[[jax.Array], dict], rng, n_stages: int):
    """Initialize S independent stages and stack their parameter pytrees on
    a leading axis (the layout :func:`pipeline_apply` expects)."""
    rngs = jax.random.split(rng, n_stages)
    params = [init_one(r) for r in rngs]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params)


def split_microbatches(tree, n_micro: int):
    """Reshape a pytree of ``[B, ...]`` arrays to ``[M, B//M, ...]``."""
    def split(x):
        batch = x.shape[0]
        assert batch % n_micro == 0, (batch, n_micro)
        return x.reshape(n_micro, batch // n_micro, *x.shape[1:])

    return jax.tree.map(split, tree)
