"""FedOBD with sequence-parallel long-context clients (VERDICT r4 item 3).

The second model-sharding axis for the north-star method: an ``("sp",)``
mesh shards each client's sequence axis (ring/Ulysses attention —
``parallel/ring_attention.py``), clients scan through the round program
one after another, and the FedOBD machinery — block dropout, codec,
optimizer continuation — runs per-leaf on REPLICATED parameters exactly
as in the client-axis session (block L2 scores, keep masks, and the
NNADQ/QSGD distortion see the same replicated values on every device,
so the math commutes with the sequence sharding).

Layout = ``spmd_sp.py``'s (session-owned shard_map, sp-mode model twin
with ``grad_sync_axis="sp"``); per-client math = ``SpmdFedOBDSession``'s
``local_train`` verbatim; the scan round body is shared with the
expert-parallel composition (``spmd_obd_ep.obd_scan_round_program``).
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.engine import ComputeEngine
from .spmd import shard_map_compat
from .spmd_obd import SpmdFedOBDSession
from .spmd_obd_ep import obd_scan_round_program
from .spmd_sp import SingleDeviceEvalMixin


class SpmdFedOBDSequenceParallelSession(
    SingleDeviceEvalMixin, SpmdFedOBDSession
):
    #: whole-mesh scan layout routed through the shared fused machinery
    #: (spmd_obd.py::_finish_obd_phase_fn): selection gather,
    #: round-horizon fusion and the update guard all apply
    _whole_mesh_fused = True

    def __init__(
        self,
        config,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        sequence_parallel: int,
        sp_impl: str = "ring",
        codec: str = "nnadq",
    ) -> None:
        devices = jax.devices()
        if sequence_parallel > len(devices):
            raise ValueError(
                f"sequence_parallel={sequence_parallel} exceeds the "
                f"{len(devices)}-device mesh"
            )
        sp_mesh = Mesh(
            np.asarray(devices[:sequence_parallel]), axis_names=("sp",)
        )
        from ..models import create_model_context

        kwargs = dict(getattr(config, "model_kwargs", {}) or {})
        kwargs.pop("sequence_parallel", None)
        kwargs.pop("sp_mesh", None)
        kwargs["sp_axis"] = "sp"
        kwargs.setdefault("sp_impl", sp_impl)
        sp_model_ctx = create_model_context(
            config.model_name, dataset_collection, **kwargs
        )
        sp_model_ctx.compute_dtype = model_ctx.compute_dtype
        self._sp_engine = ComputeEngine(
            sp_model_ctx,
            engine.hyper_parameter,
            total_steps=engine.total_steps,
            grad_sync_axis="sp",
        )
        super().__init__(
            config, dataset_collection, model_ctx, engine, practitioners,
            mesh=sp_mesh, codec=codec,
        )
        # same client-key contract as the expert-parallel layout: split to
        # the default client-axis slot count, take the worker rows (see
        # SpmdFedOBDSession._stream_slots)
        from .mesh import client_slots, make_mesh

        self._stream_slots = client_slots(config.worker_number, make_mesh())
        # re-place the sequence-bearing leaves sharded over "sp" (the base
        # placed the stacked client data replicated — no clients axis)
        self._data = {
            k: jax.device_put(
                v,
                NamedSharding(
                    self.mesh,
                    P(None, None, None, "sp") if v.ndim >= 4 else P(),
                ),
            )
            for k, v in self._data.items()
        }

    def _train_engine(self):
        return self._sp_engine

    def _leaf_spec(self, shape, name: str = "") -> P:
        return P()  # params replicated; the sequence axis is the sharded one

    def _wrap_phase_program(self, local_train, qdq, phase_two: bool):
        mesh = self.mesh
        scan_round = obd_scan_round_program(
            local_train, qdq, phase_two, guard_active=self._update_guard,
            compute_dtype=self._resident_dtype,
        )

        def round_program(
            global_params, opt_state_s, weights, rngs, bcast_rng, data
        ):
            def shard_body(
                global_params, data, weights, rngs, bcast_rng, opt_state_s
            ):
                # data leaves here are LOCAL sequence blocks; everything
                # else is replicated, incl. the FedOBD block selection and
                # codec (deterministic per replicated inputs)
                return scan_round(
                    global_params, opt_state_s, weights, rngs, bcast_rng,
                    data,
                )

            data_specs = jax.tree.map(
                lambda x: P(None, None, None, "sp") if x.ndim >= 4 else P(),
                data,
            )
            return shard_map_compat(
                shard_body,
                mesh,
                in_specs=(P(), data_specs, P(), P(), P(), P()),
                out_specs=(P(), P(), P(), P()),
            )(global_params, data, weights, rngs, bcast_rng, opt_state_s)

        # jit, gather twin, horizon registration and dispatch come from
        # the shared machinery (spmd_obd.py::_finish_obd_phase_fn)
        return self._finish_obd_phase_fn(round_program, phase_two)


def build_obd_sequence_parallel_session(ctx, session_args, codec: str):
    model_kwargs = dict(ctx.config.model_kwargs)
    return SpmdFedOBDSequenceParallelSession(
        *session_args,
        sequence_parallel=int(model_kwargs.get("sequence_parallel", 0)),
        sp_impl=str(model_kwargs.get("sp_impl", "ring")),
        codec=codec,
    )
