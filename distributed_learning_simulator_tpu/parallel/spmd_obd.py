"""FedOBD as SPMD round programs.

The canonical ``fed_obd_train.sh`` workload (100 clients, block dropout,
NNADQ transport — reference ``method/fed_obd``) on the fast path: each
phase-1 round — every selected client's local epochs, its opportunistic
block-dropout selection, the NNADQ transport distortion, and the weighted
FedAvg reduction — is ONE jitted program over the ``clients`` mesh axis.
Phase 2 (per-epoch aggregation, reference ``fed_obd/worker.py:47-53``) is a
second program invoked once per epoch.

In-program equivalents of the host-side machinery:

* block selection (``obd_algorithm.py``): per-block L2 deltas via segment
  sums, greedy keep under the ``1-dropout_rate`` budget as a ``lax.scan``
  over blocks in score order — per-client data-dependent selection without
  leaving the device;
* ``ParameterMessage.complete`` (server fills dropped keys from the old
  global): ``where(block_kept, local, global)`` before the weighted psum;
* NNADQ endpoints: ``nnadq_quantize_dequantize`` applied to kept uploads
  and to the broadcast global (``quant_broadcast=True``, reference
  ``fed_obd/server.py:14-15``); payload bytes are accounted analytically
  from the adaptive bit-widths the codec chose in-program.

Host side keeps the reference's phase state machine (rounds → phase 2 on
exhaustion/plateau → end), round records, and best-model artifact.

Optimizer continuation (``reuse_learning_rate``, reference
``util/model.py:6-23``): phase 1 rebuilds each client's optimizer per round
(AggregationWorker semantics) but RETURNS the final per-slot optimizer
states; at the phase switch those states seed phase 2, and every phase-2
epoch threads them through — the schedule position and momentum continue
across the switch and across phase-2 epochs exactly as on the threaded
executor (``method/fed_obd/worker.py`` + ``Trainer.load_parameter_dict``
with ``reuse_learning_rate=True``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..method.fed_obd.obd_algorithm import get_module_blocks
from ..ops.pytree import tree_cast
from ..ops.quantization import nnadq_quantize_dequantize
from ..utils.logging import get_logger
from .mesh import put_sharded
from .spmd import (
    SpmdFedAvgSession,
    guard_client_update,
    guarded_average,
    scan_local_epochs_carry,
    shard_map_compat,
)
from jax.sharding import PartitionSpec as P


def _masked_slot_merge(keep, new_tree, old_tree):
    """Per-slot ``where`` over ``[S, ...]`` state pytrees: slots with
    ``keep[i]`` take the new leaf rows, the rest keep the old ones
    (``keep`` broadcasts over each leaf's trailing dims)."""
    return jax.tree.map(
        lambda new, old: jnp.where(
            keep.reshape(keep.shape + (1,) * (new.ndim - 1)), new, old
        ),
        new_tree,
        old_tree,
    )


class SpmdFedOBDSession(SpmdFedAvgSession):
    """Two-phase FedOBD with block dropout + quantized transport, one
    program per phase.  ``codec`` selects the wire numerics: ``"nnadq"``
    (fed_obd) or ``"qsgd"`` (fed_obd_sq, reference
    ``method/fed_obd/__init__.py:16-22``)."""

    _uses_val_policy = False  # own round program; no val policy

    def __init__(self, *args, codec: str = "nnadq", **kwargs) -> None:
        self._phase2_fn = None
        self._codec = codec
        #: un-jitted phase programs (phase_two -> fn) and their gather
        #: twins — the horizon builder scans these, same trace as the
        #: per-round path (populated by the base ``_wrap_phase_program``;
        #: the ep/sp subclasses override it and stay per-round/dense)
        self._phase_program_fns: dict[bool, object] = {}
        self._gather_phase_program_fns: dict[bool, object] = {}
        self._obd_horizon_fns: dict[tuple[bool, int], object] = {}
        #: out_shardings pins per phase (``_finish_obd_phase_fn``) — the
        #: donated-layout record shardcheck certifies pre-dispatch
        self._phase_out_shardings: dict[bool, object] = {}
        super().__init__(*args, **kwargs)
        # THE per-round client-key contract, shared with the threaded
        # fed_obd worker (engine/executor.py::obd_aligned_round_stream):
        # ``split(round_rng, client_slots(worker_number, make_mesh()))``,
        # worker i at row i.  On jax 0.4's non-partitionable threefry,
        # split PREFIXES depend on the split count, so every OBD layout
        # must split to the SAME count and slice/take its rows — the
        # whole-mesh-per-client subclasses (ep/sp, whose meshes have no
        # clients axis and whose n_slots is just worker_number) override
        # ``_stream_slots`` to this default-mesh count; deriving their
        # keys from ``split(rng, n_slots)`` instead silently diverges
        # from the client-axis (and threaded) trajectories wherever the
        # model consumes training rng (the root cause behind the
        # pre-existing expert-parallel OBD parity failure, visible once
        # the set_mesh crash was fixed).
        self._stream_slots = self.n_slots
        # per-round client keys for the gather path: rows of the SAME
        # full-population split the dense path uses, taken at the
        # selected ids device-side.  ``_stream_slots`` is read at TRACE
        # time (first dispatch), not here: the ep/sp subclasses override
        # it to the default-mesh count AFTER this __init__ returns, and
        # capturing the client-axis value would silently diverge their
        # gather stream from the dense path's.
        if self._selection_gather or self._population_streamed:
            # the streamed path derives its cohort keys the same way the
            # gather path does: the selected rows of the full-population
            # split, taken by WORKER ID — bit-identical to the dense
            # slice of the same split
            session = self
            self._split_sel_rngs = jax.jit(
                lambda round_rng, sel_idx: jnp.take(
                    jax.random.split(round_rng, session._stream_slots),
                    sel_idx,
                    axis=0,
                ),
                out_shardings=self._client_sharding,
            )
        # streamed populations, OBD flavor: alongside the host-resident
        # client data (base __init__), the per-slot OPTIMIZER states live
        # in a SPARSE host store whose default row is one slot's fresh
        # optimizer init — "never written" IS the fresh-init contract, so
        # never-selected clients keep fresh state without materializing
        # the population.  Each phase-1 round fetches only the cohort's
        # opt rows, the program participation-merges them (weight-0
        # padding keeps its old rows), and the updated rows write back
        # asynchronously behind the next round's prefetch.
        self._opt_population = None
        self._writeback = None
        self._phase2_streamed_ready = False
        if self._population_streamed:
            from ..util.population import PopulationStore, WritebackQueue

            self._opt_population = PopulationStore.lazy(
                self._fresh_opt_row, self.n_slots
            )
            self._writeback = WritebackQueue(self._opt_population)
            self._ckpt.register_finalizer(
                "opt_writeback", self._writeback.close
            )

    @property
    def _obd_selection_active(self) -> bool:
        """Whether ``random_client_number`` leaves clients out of phase-1
        rounds — the condition under which phase 1 carries (and merges)
        the per-slot optimizer-state buffer on BOTH the dense and gather
        paths, so the phase-2 seed is well-defined: each slot's state from
        its last participation (fresh init if never selected)."""
        return self._selected_per_round < self.config.worker_number

    @property
    def _phase1_carries_opt(self) -> bool:
        """Whether phase-1 programs carry + participation-merge the
        per-slot opt-state buffer: under an ACTIVE selection every OBD
        layout does (client-axis AND the whole-mesh ep/sp scans), so a
        slot's phase-2 seed is the state from its last participation and
        the dense/gather paths agree on it bit-exactly.  Full
        participation keeps the legacy carry-less semantics (every slot
        trains every round; the last round's states seed phase 2).
        Streamed populations ALWAYS carry: the cohort's fetched opt rows
        enter every phase-1 program and the merged rows write back to
        the host store — the store row is each slot's last-participation
        state by construction."""
        if getattr(self, "_population_streamed", False):
            return True
        return self._obd_selection_active and (
            type(self) is SpmdFedOBDSession or self._whole_mesh_fused
        )

    @classmethod
    def _bespoke_round_program_reason(cls) -> str | None:
        # THE class-level OBD gate (selection gather, horizon fusion and
        # the update guard all key off it, here and in tools/shardcheck's
        # conf validator): every layout whose phase programs flow through
        # _finish_obd_phase_fn — the client-axis session and the ep/sp
        # whole-mesh scans — gets the full fused machinery
        if cls is not SpmdFedOBDSession and not cls._whole_mesh_fused:
            return (
                f"{cls.__name__} lays clients out as a"
                " whole-mesh-per-client scan (own phase programs)"
            )
        return None

    @classmethod
    def _horizon_unsupported_reason(cls) -> str | None:
        reason = cls._bespoke_round_program_reason()
        if reason is None:
            return None
        return (
            "round_horizon > 1 requires a fusable round program;"
            f" {reason} — run it with round_horizon=1"
        )

    def _selection_gather_unsupported_reason(self) -> str | None:
        return self._bespoke_round_program_reason()

    @classmethod
    def _class_population_store_reason(cls) -> str | None:
        """The client-axis OBD session streams: its phase programs are
        shape-polymorphic in the slot axis and take the client stacks
        (and the per-slot opt carry) as explicit arguments.  The
        whole-mesh ep/sp layouts scan clients inside one program with
        the stacks closed over — they defer to a follow-up."""
        if cls is SpmdFedOBDSession:
            return None
        return (
            f"{cls.__name__} scans clients inside one whole-mesh program"
            " with the stacked client state closed over — streamed"
            " populations defer to a follow-up there"
        )

    def _population_store_unsupported_reason(self) -> str | None:
        reason = super()._population_store_unsupported_reason()
        if reason is not None:
            return reason
        horizon = int(
            self.config.algorithm_kwargs.get("round_horizon", 1) or 1
        )
        if horizon > 1:
            return (
                "the streamed OBD path fetches each round's cohort opt"
                " rows and writes the merged rows back between"
                " dispatches; round fusion (round_horizon > 1) would"
                " trap that writeback inside one program — run streamed"
                " fed_obd with round_horizon=1"
            )
        return None

    def _horizon_capable(self) -> bool:
        return self._bespoke_round_program_reason() is None

    def _update_guard_unsupported_reason(self) -> str | None:
        # the phase programs compile the guard in (per-client upload
        # hygiene + survivor-renormalized total) on the client-axis AND
        # whole-mesh layouts (obd_scan_round_program's guard mode)
        return self._bespoke_round_program_reason()

    def _opt_carry_out_sharding(self):
        """out_shardings pin for the per-slot opt-state carry.  The
        whole-mesh layouts pin it REPLICATED: their donated carry enters
        replicated, and an unpinned output can come back expert-sharded
        from GSPMD propagation — a donation aliasing size mismatch at
        runtime.  The client-axis layout leaves it to the compiler (the
        carry is ``P("clients")``-sharded by the shard_map out_specs)."""
        return self._replicated if self._whole_mesh_fused else None

    def _select_indices(self, round_number: int):
        """Gather-path selection, OBD flavor: ascending selected worker
        ids padded to ``s_pad`` with DISTINCT unselected slot ids at
        weight 0 (the FedAvg base pads with id 0; the OBD phase programs
        scatter per-slot optimizer states back through these ids, and a
        duplicated index would make the scatter's write order — and the
        carried state — unspecified)."""
        from ..utils.selection import select_workers

        selected = sorted(
            select_workers(
                self.config.seed,
                round_number,
                self.config.worker_number,
                self.config.algorithm_kwargs.get("random_client_number"),
            )
        )
        taken = set(selected)
        padding = [i for i in range(self.n_slots) if i not in taken]
        idx = np.asarray(
            selected + padding[: self.s_pad - len(selected)], np.int32
        )
        weights = np.zeros(self.s_pad, np.float32)
        weights[: len(selected)] = self._dataset_sizes[selected]
        from ..util.faults import apply_fault_plan

        # dropped ids masked out of the S_pad row at weight 0 — the
        # masked-merge then keeps their opt states untouched, exactly like
        # an unselected round (a dropout IS a missed participation)
        weights = apply_fault_plan(
            self._fault_plan,
            self._min_quorum,
            round_number,
            idx,
            weights,
            self.config.worker_number,
        )
        return idx, weights

    # ------------------------------------------- streamed-population path
    def _cohort_ids(self, round_number: int) -> np.ndarray:
        """The round's cohort ids WITHOUT the fault/quorum fold (see the
        base class) — the OBD id construction: selected workers padded
        with DISTINCT unselected ids (``_select_indices``' scatter-safety
        contract doubles as the writeback's last-writer-wins one)."""
        from ..utils.selection import select_workers

        selected = sorted(
            select_workers(
                self.config.seed,
                round_number,
                self.config.worker_number,
                self.config.algorithm_kwargs.get("random_client_number"),
            )
        )
        taken = set(selected)
        padding = [i for i in range(self.n_slots) if i not in taken]
        return np.asarray(
            selected + padding[: self.s_pad - len(selected)], np.int32
        )

    def _fresh_opt_row(self):
        """ONE slot's fresh optimizer state as host numpy — the sparse
        opt store's default row.  Init from the compute-dtype view so
        the rows byte-match the in-program ``optimizer.init`` over the
        residency cast (``_opt_state_template``)."""
        cdtype = self._resident_dtype
        params = self.engine.init_params(self.config.seed)
        row = self.engine.optimizer.init(
            params if cdtype is None else tree_cast(params, cdtype)
        )
        return jax.tree.map(np.asarray, row)

    def _take_cohort_opt(self, ids: np.ndarray):
        """Place the cohort's per-slot optimizer rows for this round.
        Pending writebacks drain first so round r-1's merged rows are
        visible (last-writer-wins store).  The phase programs DONATE
        this buffer, and ``device_put`` of aligned host numpy ALIASES
        the python-owned storage — ``jnp.copy`` gives XLA-owned buffers
        the donation can legally consume."""
        self._writeback.drain()
        rows = self._opt_population.fetch(ids)
        placed = put_sharded(rows, self._client_sharding)
        return jax.tree.map(jnp.copy, placed)

    def _materialize_streamed_phase2(self):
        """Phase 2 trains EVERY client each epoch — there is no cohort
        to stream.  At the switch the full population materializes on
        device once: the stacked data through the prefetcher's fetch
        hook and the opt buffer merged from each slot's last
        participation (fresh init if never selected).  Documented
        limitation: streamed fed_obd's phase 2 needs the population
        resident (the reference workload is 100 clients; the
        million-client streaming target is the single-phase fed_avg
        family)."""
        self._writeback.drain()
        all_ids = np.arange(self.n_slots, dtype=np.int64)
        (self._cohort_data, self._cohort_val), _nbytes = self._fetch_cohort(
            all_ids
        )
        rows = self._opt_population.fetch(all_ids)
        placed = put_sharded(rows, self._client_sharding)
        self._phase2_streamed_ready = True
        return jax.tree.map(jnp.copy, placed)

    def _drain_writeback_spans(self) -> None:
        """Emit ``writeback`` spans for completed async writebacks —
        from the SESSION thread (the worker only collects timings; the
        trace recorder is never touched off-thread)."""
        if self._writeback is None:
            return
        for job in self._writeback.pop_completed():
            seconds = job.pop("seconds", 0.0)
            if self._trace.enabled:
                self._trace.span_record("writeback", seconds, **job)

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        config = self.config
        self._dropout_rate = float(config.algorithm_kwargs["dropout_rate"])
        self._nnadq_weight = float(
            config.endpoint_kwargs.get("worker", {}).get("weight", 0.01)
        )
        # static block structure from the parameter template
        template = jax.eval_shape(
            lambda: self.engine.init_params(config.seed)
        )
        keys = list(template.keys())
        blocks = get_module_blocks(keys)
        self._block_id = {
            k: i for i, block in enumerate(blocks) for k in block
        }
        self._block_sizes = np.zeros(len(blocks), np.float32)
        for k in keys:
            self._block_sizes[self._block_id[k]] += int(
                np.prod(template[k].shape)
            )
        self._total_params = float(self._block_sizes.sum())
        self._phase1_fn = self._build_phase_fn(phase_two=False)
        return self._phase1_fn

    def _train_engine(self):
        """The engine the round program trains with — a sharded-model twin
        in the expert-parallel subclass."""
        return self.engine

    def _build_phase_fn(self, phase_two: bool):
        import math

        engine = self._train_engine()
        epochs = 1 if phase_two else self.config.epoch
        weight_cfg = self._nnadq_weight
        block_sizes = jnp.asarray(self._block_sizes)
        block_id = self._block_id
        threshold = (1.0 - self._dropout_rate) * self._total_params
        guard_active = self._update_guard
        max_update_norm = self._max_update_norm

        if self._codec == "qsgd":
            from ..ops.quantization import qsgd_quantize_dequantize

            level = int(
                self.config.endpoint_kwargs.get("worker", {}).get(
                    "quantization_level", 255
                )
            )
            qbits = math.ceil(math.log2(level + 1)) + 1  # level plane + signs

            def qdq(x, key):
                return qsgd_quantize_dequantize(x, key, level), jnp.float32(qbits)

        else:

            def qdq(x, key):
                return nnadq_quantize_dequantize(x, weight_cfg)

        def keep_mask(local, global_params):
            """Greedy block selection under the parameter budget
            (obd_algorithm.get_block_parameter, reference
            ``obd_algorithm.py:88-127``)."""
            sq = jnp.zeros(block_sizes.shape[0])
            for k, v in local.items():
                d = v.astype(jnp.float32) - global_params[k].astype(jnp.float32)
                sq = sq.at[block_id[k]].add(jnp.sum(jnp.square(d)))
            score = jnp.sqrt(sq) / block_sizes
            order = jnp.argsort(-score)
            sizes_ord = block_sizes[order]

            def body(partial, size_i):
                keep = partial + size_i <= threshold
                return partial + size_i * keep, keep

            _, keep_ord = jax.lax.scan(body, jnp.float32(0.0), sizes_ord)
            return jnp.zeros(block_sizes.shape[0], bool).at[order].set(keep_ord)

        def local_train(
            global_params, data, weight, rng, opt_state=None,
            compute_global=None,
        ):
            rng, quant_rng = jax.random.split(rng)
            if compute_global is None:
                compute_global = global_params
            # phase 1: optimizer rebuilt per round (opt_state None); phase 2:
            # reuse_learning_rate continuation from the carried state.
            # Under AMP residency ``compute_global`` is the ONE compute-dtype
            # cast of the broadcast (made outside the client scan): training
            # runs bf16-resident, while the deltas, the keep_mask scores and
            # the dropped-block fallback below stay anchored to the f32
            # broadcast — dropped blocks never accumulate cast rounding.
            params, opt_out, summed = scan_local_epochs_carry(
                engine, epochs, compute_global, data, rng, opt_state
            )

            selected = (weight > 0).astype(jnp.float32)
            upload = {}
            upload_bits = jnp.float32(0.0)
            if phase_two:
                # per-epoch full-delta uploads through the codec
                for i, (k, v) in enumerate(params.items()):
                    delta = v.astype(jnp.float32) - global_params[k].astype(
                        jnp.float32
                    )
                    dq, bits = qdq(delta, jax.random.fold_in(quant_rng, i))
                    upload[k] = global_params[k].astype(jnp.float32) + dq
                    upload_bits += bits * v.size
            else:
                keep = keep_mask(params, global_params)
                for i, (k, v) in enumerate(params.items()):
                    mask = keep[block_id[k]]
                    g = global_params[k].astype(jnp.float32)
                    # the codec sees the block DIFF, as the reference sends
                    # (``method/fed_obd/worker.py:68`` get_parameter_diff):
                    # a delta's span is the span of one round's movement, so
                    # the quantization step stays far below the values' own
                    # scale — quantizing VALUES instead snaps the per-round
                    # drift back to the grid and stalls training
                    dq, bits = qdq(
                        v.astype(jnp.float32) - g,
                        jax.random.fold_in(quant_rng, i),
                    )
                    # complete(): dropped blocks fall back to the old global
                    upload[k] = jnp.where(mask, g + dq, g)
                    upload_bits += mask * bits * v.size
            if guard_active:
                # update hygiene on the codec'd upload (what aggregation
                # would actually consume) — the guard shared with the
                # FedAvg round program (spmd.py::guard_client_update).
                # The slot's opt-state continuation keeps its trained
                # state: rejection excludes the upload, it does not roll
                # back the client's local trajectory.
                weight, summed = guard_client_update(
                    upload, global_params, weight, summed, max_update_norm
                )
            contribution = jax.tree.map(lambda p: p * weight, upload)
            summed = dict(summed, upload_bits=upload_bits * selected)
            return contribution, opt_out, summed

        return self._wrap_phase_program(local_train, qdq, phase_two)

    def _wrap_phase_program(self, local_train, qdq, phase_two: bool):
        """Client-axis layout: slots over the ``clients`` mesh axis,
        chunk-scanned vmap inside ``shard_map``, psum aggregation.  The
        expert-parallel subclass overrides this with a whole-mesh-per-
        client GSPMD layout (clients as a plain scan).

        Selection-aware additions (PR 3 machinery extended to the OBD
        phase programs):

        * under an ACTIVE ``random_client_number`` selection, phase 1
          carries a per-slot ``[n_slots]`` optimizer-state buffer and
          WHERE-MERGES each round's freshly trained states into it for the
          selected slots only — a slot's phase-2 seed is the state from
          its LAST PARTICIPATION (the threaded reference's semantics:
          unselected workers do not train), and the dense and gather paths
          agree on it bit-exactly;
        * with ``selection_gather`` on, a gather twin trains only the
          ``s_pad`` gathered slots: ``jnp.take`` on the stacked client
          data along the slot axis before ``shard_map``, and the
          optimizer-state merge becomes a scatter back into the carried
          buffer (``_select_indices`` pads the id rows with DISTINCT
          unselected slot ids so every slot is written at most once —
          duplicate scatter indices have unspecified write order)."""

        def chunk_size(slots_local: int) -> int:
            mb = self.client_chunk
            if mb <= 0:
                mb = 8 if jax.default_backend() == "tpu" else slots_local
            mb = max(1, min(mb, slots_local))
            while slots_local % mb:
                mb -= 1
            return mb

        cdtype = self._resident_dtype

        def round_program(global_params, opt_state_s, weights, rngs, bcast_rng, data):
            def shard_body(global_params, opt_state_s, data, weights, rngs, bcast_rng):
                slots_local = weights.shape[0]
                mb = chunk_size(slots_local)
                # AMP residency: ONE cast of the broadcast per phase program
                # (outside the chunk scan) — every slot trains from the same
                # compute-dtype view instead of re-converting per kernel
                compute_global = (
                    tree_cast(global_params, cdtype)
                    if cdtype is not None
                    else global_params
                )

                def run_slots(d, w, r, o):
                    # phase 1: o is None (optimizer rebuilt per round)
                    return jax.vmap(
                        local_train,
                        in_axes=(None, 0, 0, 0, 0 if phase_two else None, None),
                    )(global_params, d, w, r, o, compute_global)

                if mb == slots_local:
                    # phase 1 rebuilds optimizers per round: the carried
                    # buffer (when present) is consumed by the merge below,
                    # never by training
                    contributions, opt_out, metrics = run_slots(
                        data, weights, rngs,
                        opt_state_s if phase_two else None,
                    )
                    local_sum = jax.tree.map(
                        lambda c: jnp.sum(c, axis=0), contributions
                    )
                    metrics = jax.tree.map(lambda m: jnp.sum(m), metrics)
                else:
                    # scan client chunks to bound activation memory (same
                    # time-multiplexing as SpmdFedAvgSession.shard_body)
                    n_chunks = slots_local // mb

                    def to_chunks(tree):
                        return jax.tree.map(
                            lambda x: x.reshape(n_chunks, mb, *x.shape[1:]), tree
                        )

                    chunks = (
                        to_chunks(data),
                        to_chunks(weights),
                        to_chunks(rngs),
                        to_chunks(opt_state_s) if phase_two else None,
                    )
                    _, _, met_shapes = jax.eval_shape(
                        run_slots, *jax.tree.map(lambda x: x[0], chunks)
                    )

                    def chunk_body(acc, chunk):
                        data_k, w_k, r_k, o_k = chunk
                        contrib, opt_k, met = run_slots(data_k, w_k, r_k, o_k)
                        acc_sum, acc_met = acc
                        acc_sum = jax.tree.map(
                            lambda a, c: a + jnp.sum(c, axis=0), acc_sum, contrib
                        )
                        acc_met = jax.tree.map(
                            lambda a, m: a + jnp.sum(m), acc_met, met
                        )
                        # per-slot optimizer states collect as scan outputs
                        return (acc_sum, acc_met), opt_k

                    init = (
                        jax.tree.map(
                            lambda p: jnp.zeros(p.shape, jnp.float32),
                            global_params,
                        ),
                        jax.tree.map(lambda s: jnp.zeros((), s.dtype), met_shapes),
                    )
                    (local_sum, metrics), opt_chunks = jax.lax.scan(
                        chunk_body, init, chunks
                    )
                    opt_out = jax.tree.map(
                        lambda x: x.reshape(slots_local, *x.shape[2:]),
                        opt_chunks,
                    )
                if not phase_two and opt_state_s is not None:
                    # selection-aware phase 1: the carried buffer keeps the
                    # unselected slots' states (their last participation);
                    # only selected slots take this round's trained states
                    opt_out = _masked_slot_merge(
                        weights > 0, opt_out, opt_state_s
                    )
                global_sum = jax.tree.map(
                    lambda s: jax.lax.psum(s, axis_name="clients"), local_sum
                )
                if self._update_guard:
                    # survivor renormalization: sum of the guard's
                    # effective per-slot weights (rejected slots at zero);
                    # a zero-survivor round keeps the old global instead
                    # of zeroing the model
                    metrics = dict(metrics)
                    total_weight = jax.lax.psum(
                        metrics.pop("_eff_weight"), axis_name="clients"
                    )
                    new_global = guarded_average(
                        global_sum, total_weight, global_params
                    )
                else:
                    total_weight = jax.lax.psum(
                        jnp.sum(weights), axis_name="clients"
                    )
                    new_global = jax.tree.map(
                        lambda s, g: (
                            s / jnp.maximum(total_weight, 1e-12)
                        ).astype(g.dtype),
                        global_sum,
                        global_params,
                    )
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                    metrics,
                )
                # quant_broadcast: what clients train from next round is the
                # codec-distorted global; the exact average stays server-side
                bcast = {}
                bcast_bits = jnp.float32(0.0)
                for i, (k, v) in enumerate(new_global.items()):
                    vq, bits = qdq(
                        v.astype(jnp.float32), jax.random.fold_in(bcast_rng, i)
                    )
                    bcast[k] = vq.astype(v.dtype)
                    bcast_bits += bits * v.size
                metrics = dict(metrics, bcast_bits=bcast_bits)
                return new_global, bcast, opt_out, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(
                    P(),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P(),
                ),
                out_specs=(P(), P(), P("clients"), P()),
            )(global_params, opt_state_s, data, weights, rngs, bcast_rng)

        return self._finish_obd_phase_fn(round_program, phase_two)

    def _finish_obd_phase_fn(
        self, round_program, phase_two: bool, out_shardings=None
    ):
        """The shared tail of every OBD ``_wrap_phase_program`` (the
        client-axis shard_map layout AND the whole-mesh ep/sp scans):
        register the un-jitted ``(global_params, opt_state_s, weights,
        rngs, bcast_rng, data)`` program for the horizon builder, jit the
        dense path, build + jit the gather twin when the selection gather
        is active, and return the dispatch fn.  ``out_shardings`` pins
        the jitted outputs to a stored layout (the expert-parallel
        session's donated round-over-round buffers must never reshard)."""
        # the horizon builder scans this same program — one trace, shared
        # numerics with the per-round path
        self._phase_program_fns[phase_two] = round_program
        self._phase_out_shardings[phase_two] = out_shardings
        jit_kwargs = (
            {"out_shardings": out_shardings} if out_shardings is not None else {}
        )

        gather_jitted = None
        if self._selection_gather:
            client_sharding = self._client_sharding
            session = self

            def gather_phase_program(
                global_params, opt_carry, weights, rngs, sel_idx, bcast_rng, data
            ):
                """The SAME phase program over a gathered ``[s_pad]`` slot
                stack (device-side ``jnp.take`` — the full client stack
                stays resident), with the per-slot optimizer states
                gathered in (phase 2) / scattered back (both phases) so
                the carried ``[n_slots]`` buffer matches the dense merge
                bit-exactly.  Data leaves are constrained back to their
                OWN stored shardings (the client axis on client-axis
                meshes; the sp layout keeps the sequence axis sharded
                through the take)."""

                def take(x, s=None):
                    return jax.lax.with_sharding_constraint(
                        jnp.take(x, sel_idx, axis=0),
                        client_sharding if s is None else s,
                    )

                data_shardings = jax.tree.map(
                    lambda x: x.sharding, session._data
                )
                opt_sel = jax.tree.map(take, opt_carry)
                exact, bcast, opt_out, metrics = round_program(
                    global_params,
                    opt_sel if phase_two else None,
                    weights,
                    rngs,
                    bcast_rng,
                    jax.tree.map(take, data, data_shardings),
                )
                # scatter-back: selected rows take their trained states,
                # padding rows (weight 0, distinct unselected ids) write
                # their own old state back — a no-op per slot
                merged = _masked_slot_merge(weights > 0, opt_out, opt_sel)
                new_carry = jax.tree.map(
                    lambda c, m: jax.lax.with_sharding_constraint(
                        c.at[sel_idx].set(m), client_sharding
                    ),
                    opt_carry,
                    merged,
                )
                return exact, bcast, new_carry, metrics

            self._gather_phase_program_fns[phase_two] = gather_phase_program
            gather_jitted = jax.jit(
                gather_phase_program, donate_argnums=(0, 1), **jit_kwargs
            )

        # data as an argument, not a closure constant (see spmd.py); the
        # carried optimizer states (phase 2 always, phase 1 under an
        # active selection) are donated alongside the params (same shape
        # in and out)
        donate = (0, 1) if (phase_two or self._phase1_carries_opt) else (0,)
        jitted = jax.jit(round_program, donate_argnums=donate, **jit_kwargs)

        # the OBD dispatch tail mirrors _wrap_round_programs: roundtrace's
        # TraceRecorder.dispatch logs a `compile` event whenever the phase
        # program's jit cache grew (enabled-gated int compare, no device
        # touch)
        phase_name = "phase2" if phase_two else "phase1"

        def fn(
            global_params, weights, rngs, bcast_rng, opt_state_s=None,
            sel_idx=None,
        ):
            with self._round_mesh_context():
                if sel_idx is not None:
                    return self._trace.dispatch(
                        f"{phase_name}[gather]",
                        gather_jitted,
                        (
                            global_params, opt_state_s, weights, rngs,
                            sel_idx, bcast_rng, self._data,
                        ),
                        sig_args=(weights, rngs, sel_idx),
                    )
                if self._population_streamed:
                    # phase 1: the placed cohort; phase 2: the full
                    # population, materialized once at the switch — both
                    # ride _cohort_data so the dispatch surface is one
                    return self._trace.dispatch(
                        f"{phase_name}[streamed]",
                        jitted,
                        (
                            global_params, opt_state_s, weights, rngs,
                            bcast_rng, self._cohort_data,
                        ),
                        sig_args=(weights, rngs),
                    )
                return self._trace.dispatch(
                    f"{phase_name}[dense]",
                    jitted,
                    (
                        global_params, opt_state_s, weights, rngs, bcast_rng,
                        self._data,
                    ),
                    sig_args=(weights, rngs),
                )

        fn._jitted = jitted
        fn._jitted_gather = gather_jitted
        return fn

    # ------------------------------------------------------------------
    def _build_obd_horizon_fn(self, phase_two: bool, horizon: int):
        """``horizon`` consecutive SAME-phase rounds as ONE jitted,
        donated ``lax.scan``: the carry is (broadcast params, per-slot
        optimizer states, last exact aggregate, rng chain).  Each step
        advances the chain exactly like the host loop (``split(rng, 3)``
        per aggregate — H=1 and H≥4 trajectories are bit-identical),
        derives the per-slot client keys from the SAME full-population
        split, runs the phase program the per-round path jits (dense or
        gather), and evaluates the EXACT aggregate on the device-resident
        test batches — stacked ``[H, ...]`` metrics come back in one host
        sync.  The broadcast (codec-distorted) global feeds the next
        scanned round while the exact aggregate rides the carry so the
        horizon boundary can checkpoint it, matching the per-round loop's
        bookkeeping."""
        engine = self.engine
        n_slots = self.n_slots
        stream_slots = self._stream_slots
        program = self._phase_program_fns[phase_two]
        gather_program = self._gather_phase_program_fns.get(phase_two)
        use_gather = self._selection_gather and not phase_two
        carry_opt = phase_two or self._phase1_carries_opt
        with_confusion = bool(self.config.use_slow_performance_metrics)

        def horizon_program(
            global_params, opt_state_s, rng, weight_rows, idx_rows, data,
            eval_batches,
        ):
            def body(carry, xs):
                params, opt_s, _exact, rng = carry
                rng, round_rng, bcast_rng = jax.random.split(rng, 3)
                keys = jax.random.split(round_rng, stream_slots)[:n_slots]
                if use_gather:
                    weights, sel_idx = xs
                    client_rngs = jnp.take(keys, sel_idx, axis=0)
                    exact, bcast, opt_s, metrics = gather_program(
                        params, opt_s, weights, client_rngs, sel_idx,
                        bcast_rng, data,
                    )
                else:
                    weights = xs
                    exact, bcast, opt_s, metrics = program(
                        params,
                        opt_s if carry_opt else None,
                        weights,
                        keys,
                        bcast_rng,
                        data,
                    )
                outs = (metrics, engine.eval_fn(exact, eval_batches))
                if with_confusion:
                    outs = outs + (engine.confusion_fn(exact, eval_batches),)
                return (bcast, opt_s, exact, rng), outs

            exact0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype), global_params
            )
            xs = (weight_rows, idx_rows) if use_gather else weight_rows
            carry, outs = jax.lax.scan(
                body, (global_params, opt_state_s, exact0, rng), xs,
                length=horizon,
            )
            bcast, opt_state_s, exact, rng = carry
            return (exact, bcast, opt_state_s, rng), outs

        # the exact/broadcast carries keep the stored per-leaf layout so
        # the donated round-over-round buffers never reshard between
        # horizon chunks (a no-op on the replicated client-axis layout,
        # load-bearing for the ep expert layout)
        jitted = jax.jit(
            horizon_program,
            donate_argnums=(0, 1, 2),
            out_shardings=(
                (
                    self._param_shardings,
                    self._param_shardings,
                    self._opt_carry_out_sharding(),
                    None,
                ),
                None,
            ),
        )

        program_name = (
            f"obd_horizon[{'phase2' if phase_two else 'phase1'},h={horizon}]"
        )

        def fn(global_params, opt_state_s, rng, weight_rows, idx_rows=None):
            with self._round_mesh_context():
                return self._trace.dispatch(
                    program_name,
                    jitted,
                    (
                        global_params, opt_state_s, rng, weight_rows,
                        idx_rows, self._data, self._ensure_eval_batches(),
                    ),
                    sig_args=(weight_rows, idx_rows),
                )

        fn._jitted = jitted
        return fn

    # ------------------------------------------------- shardcheck hooks
    def shardcheck_shardings(self):
        """Base declarations plus the per-slot opt-state carry layout and
        its out_shardings pin (the PR 8 donation-aliasing bug class)."""
        from .introspect import DeclaredSpec

        decls = super().shardcheck_shardings()
        decls.append(
            DeclaredSpec(
                "opt_carry", self.mesh, self._client_sharding.spec
            )
        )
        pin = self._opt_carry_out_sharding()
        if pin is not None:
            decls.append(
                DeclaredSpec("opt_carry_pin", self.mesh, pin.spec)
            )
        return decls

    def shardcheck_programs(self):
        """The OBD dispatch inventory: both phase programs (dense or
        gather, exactly as ``run()`` would dispatch them) plus the fused
        same-phase horizons, described abstractly — see
        :meth:`SpmdFedAvgSession.shardcheck_programs`."""
        from .introspect import (
            ProgramSpec,
            abstract_tree,
            attach_shardings,
            host_abstract,
            key_abstract,
        )

        template = jax.eval_shape(
            lambda: self.engine.init_params(self.config.seed)
        )
        params = attach_shardings(template, self._param_shardings)
        data = abstract_tree(self._data)
        opt_abstract = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=self._client_sharding
            ),
            self._opt_state_template(),
        )
        bcast_rng = key_abstract(self._replicated)
        if self._phase2_fn is None:
            self._phase2_fn = self._build_phase_fn(phase_two=True)
        specs = []

        def dense_args(weights, use_opt):
            return (
                params,
                opt_abstract if use_opt else None,
                host_abstract(weights, self._client_sharding),
                key_abstract(self._client_sharding, (self.n_slots,)),
                bcast_rng,
                data,
            )

        def gather_args(round_number):
            idx, weights = self._select_indices(round_number)
            return (
                params,
                opt_abstract,
                host_abstract(weights, self._client_sharding),
                key_abstract(self._client_sharding, (self.s_pad,)),
                host_abstract(idx, self._client_sharding),
                bcast_rng,
                data,
            )

        def carries(use_opt):
            # the run loop feeds the BROADCAST (out[1]) back as the next
            # round's params and the merged opt buffer (out[2]) back as
            # the carry
            pairs = ((0, lambda out: out[1]),)
            if use_opt:
                pairs = pairs + ((1, lambda out: out[2]),)
            return pairs

        if self._population_streamed:
            # under streamed the stored stacks are HOST numpy; the
            # programs see cohort-shaped placements (phase 1 at s_pad,
            # phase 2 at the materialized full population) — and the
            # phase-1 opt rows are fetched fresh per round, not carried
            from jax.sharding import NamedSharding

            cohort_sharding = NamedSharding(self.mesh, self._slot_spec)

            def cohort_data_abstract(leading):
                return {
                    k: jax.ShapeDtypeStruct(
                        (leading,) + v.shape[1:], v.dtype,
                        sharding=cohort_sharding,
                    )
                    for k, v in self._data.items()
                }

            def cohort_opt_abstract(leading):
                return jax.tree.map(
                    lambda s: jax.ShapeDtypeStruct(
                        (leading,) + s.shape[1:], s.dtype,
                        sharding=self._client_sharding,
                    ),
                    self._opt_state_template(),
                )

            def streamed_p1_args(round_number):
                _idx, weights = self._select_indices(round_number)
                return (
                    params,
                    cohort_opt_abstract(self.s_pad),
                    host_abstract(weights, self._client_sharding),
                    key_abstract(self._client_sharding, (self.s_pad,)),
                    bcast_rng,
                    cohort_data_abstract(self.s_pad),
                )

            specs.append(
                ProgramSpec(
                    name="phase1[streamed]",
                    jitted=self._phase1_fn._jitted,
                    args=streamed_p1_args(1),
                    alt_args=(streamed_p1_args(2),),
                    donate_argnums=(0, 1),
                    mesh=self.mesh,
                    out_pin=self._phase_out_shardings.get(False),
                    carries=carries(False),
                    mesh_context=self._round_mesh_context,
                )
            )
            phase2_weights = self._dataset_sizes.astype(np.float32)

            def streamed_p2_args(_round_number):
                return (
                    params,
                    cohort_opt_abstract(self.n_slots),
                    host_abstract(phase2_weights, self._client_sharding),
                    key_abstract(self._client_sharding, (self.n_slots,)),
                    bcast_rng,
                    cohort_data_abstract(self.n_slots),
                )

            specs.append(
                ProgramSpec(
                    name="phase2[streamed]",
                    jitted=self._phase2_fn._jitted,
                    args=streamed_p2_args(1),
                    alt_args=(streamed_p2_args(2),),
                    donate_argnums=(0, 1),
                    mesh=self.mesh,
                    out_pin=self._phase_out_shardings.get(True),
                    carries=carries(True),
                    mesh_context=self._round_mesh_context,
                )
            )
            return specs

        p1_opt = self._phase1_carries_opt
        if self._selection_gather:
            specs.append(
                ProgramSpec(
                    name="phase1[gather]",
                    jitted=self._phase1_fn._jitted_gather,
                    args=gather_args(1),
                    alt_args=(gather_args(2),),
                    donate_argnums=(0, 1),
                    mesh=self.mesh,
                    out_pin=self._phase_out_shardings.get(False),
                    carries=carries(True),
                    mesh_context=self._round_mesh_context,
                )
            )
        else:
            specs.append(
                ProgramSpec(
                    name="phase1[dense]",
                    jitted=self._phase1_fn._jitted,
                    args=dense_args(self._select_weights(1), p1_opt),
                    alt_args=(
                        dense_args(self._select_weights(2), p1_opt),
                    ),
                    donate_argnums=(0, 1) if p1_opt else (0,),
                    mesh=self.mesh,
                    out_pin=self._phase_out_shardings.get(False),
                    carries=carries(p1_opt),
                    mesh_context=self._round_mesh_context,
                )
            )
        phase2_weights = self._dataset_sizes.astype(np.float32)
        specs.append(
            ProgramSpec(
                name="phase2[dense]",
                jitted=self._phase2_fn._jitted,
                args=dense_args(phase2_weights, True),
                alt_args=(dense_args(phase2_weights, True),),
                donate_argnums=(0, 1),
                mesh=self.mesh,
                out_pin=self._phase_out_shardings.get(True),
                carries=carries(True),
                mesh_context=self._round_mesh_context,
            )
        )
        if not self._horizon_capable():
            return specs
        h = 2
        eval_batches = abstract_tree(self._ensure_eval_batches())
        horizon_pin = (
            (
                self._param_shardings,
                self._param_shardings,
                self._opt_carry_out_sharding(),
                None,
            ),
            None,
        )
        horizon_carries = (
            (0, lambda out: out[0][1]),
            (1, lambda out: out[0][2]),
            (2, lambda out: out[0][3]),
        )
        for phase_two in (False, True):
            fn = self._obd_horizon_fns.get((phase_two, h))
            if fn is None:
                fn = self._obd_horizon_fns[(phase_two, h)] = (
                    self._build_obd_horizon_fn(phase_two, h)
                )
            use_gather = self._selection_gather and not phase_two

            def horizon_args(start_round, phase_two=phase_two,
                             use_gather=use_gather):
                rounds = range(start_round, start_round + h)
                idx_rows = None
                if phase_two:
                    weight_rows = np.stack([phase2_weights] * h)
                elif use_gather:
                    pairs = [self._select_indices(r) for r in rounds]
                    weight_rows = np.stack([w for _i, w in pairs])
                    idx_rows = host_abstract(
                        np.stack([i for i, _w in pairs]),
                        self._horizon_weight_sharding,
                    )
                else:
                    weight_rows = np.stack(
                        [self._select_weights(r) for r in rounds]
                    )
                return (
                    params,
                    opt_abstract,
                    key_abstract(self._replicated),
                    host_abstract(
                        weight_rows, self._horizon_weight_sharding
                    ),
                    idx_rows,
                    data,
                    eval_batches,
                )

            specs.append(
                ProgramSpec(
                    name=(
                        f"horizon[phase2,h={h}]"
                        if phase_two
                        else f"horizon[phase1,h={h}]"
                    ),
                    jitted=fn._jitted,
                    args=horizon_args(1),
                    alt_args=(horizon_args(1 + h),),
                    donate_argnums=(0, 1, 2),
                    mesh=self.mesh,
                    out_pin=horizon_pin,
                    carries=horizon_carries,
                    scanned_len=h,
                    stacked_out=lambda out: out[1],
                    mesh_context=self._round_mesh_context,
                )
            )
        return specs

    # ------------------------------------------------------------------
    def _opt_state_template(self):
        """Abstract [S, ...] optimizer-state pytree (structure + shapes,
        nothing computed).  Under AMP residency clients train — and init
        their optimizers — from the compute-dtype view, so the carried
        buffer (and anything restored into it) follows that dtype; the
        shape-checked ``_load_opt_state`` cast retargets older f32 saves
        automatically."""
        cdtype = self._resident_dtype
        return jax.eval_shape(
            lambda p: jax.vmap(
                self.engine.optimizer.init, in_axes=None, axis_size=self.n_slots
            )(p if cdtype is None else tree_cast(p, cdtype)),
            jax.eval_shape(lambda: self.engine.init_params(self.config.seed)),
        )

    def _save_opt_state(self, stat_key: int) -> None:
        """Queue the per-slot optimizer states to disk, tagged with the
        aggregate they belong to — phase-2 resume then continues momentum
        and schedule position exactly (the SURVEY §5 TPU plan's
        'per-client opt state' checkpoint)."""
        if self._population_streamed:
            # streamed: the durable form IS the host store (npz chunks +
            # manifest, tagged with the aggregate key — the torn-store
            # fallback rides util/population's resume contract).  In
            # phase 2 the carry lives on device; sync it back first.
            self._writeback.drain()
            if self._phase2_streamed_ready and self._opt_state_s is not None:
                state = self._opt_state_s
                if jax.process_count() > 1:
                    state = jax.tree.map(
                        lambda leaf: jax.device_put(leaf, self._replicated),
                        state,
                    )
                self._opt_population.writeback(
                    np.arange(self.n_slots), jax.device_get(state)
                )
            self._opt_population.save(
                os.path.join(
                    self.config.save_dir, "aggregated_model",
                    "opt_population",
                ),
                tag=int(stat_key),
            )
            return
        leaves = jax.tree.leaves(self._opt_state_s)
        if jax.process_count() > 1:
            # the [S, ...] states are client-sharded across hosts; the
            # async writer can only fetch addressable arrays — reshard to
            # replicated first (same dance as _checkpointable)
            leaves = [jax.device_put(leaf, self._replicated) for leaf in leaves]
        payload = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
        payload["stat_key"] = np.int64(stat_key)
        self._ckpt.save_npz(
            os.path.join(self.config.save_dir, "aggregated_model", "opt_state.npz"),
            payload,
        )

    def _load_opt_state(self, resume_dir: str, expect_key: int):
        """The saved optimizer states, or None when absent / from a
        different aggregate than the resume point.  Streamed: adopts the
        restored HOST store in place (and returns None — there is no
        device buffer to hand the run loop); a torn/mismatched store
        falls back to fresh per-slot state with a warning."""
        if self._population_streamed:
            from ..util.population import PopulationStore, WritebackQueue

            store = PopulationStore.load(
                os.path.join(
                    resume_dir, "aggregated_model", "opt_population"
                ),
                default_row=self._fresh_opt_row,
                expect_tag=int(expect_key),
            )
            if store is None:
                get_logger().warning(
                    "no matching streamed opt-state store under %s —"
                    " resuming with fresh per-slot optimizers",
                    resume_dir,
                )
                return None
            self._writeback.close()
            self._opt_population = store
            self._writeback = WritebackQueue(store)
            self._ckpt.register_finalizer(
                "opt_writeback", self._writeback.close
            )
            get_logger().info(
                "restored streamed per-slot opt store (aggregate %d, %d"
                " materialized rows)",
                expect_key,
                len(store.materialized_ids()),
            )
            return None
        path = os.path.join(resume_dir, "aggregated_model", "opt_state.npz")
        if not os.path.isfile(path):
            return None
        with np.load(path) as blob:
            if int(blob["stat_key"]) != expect_key:
                return None
            loaded = {k: blob[k] for k in blob.files if k != "stat_key"}
        template = self._opt_state_template()
        shapes, treedef = jax.tree.flatten(template)
        if len(loaded) != len(shapes):
            get_logger().warning("opt_state.npz does not match the optimizer")
            return None
        leaves = []
        for i, shape in enumerate(shapes):
            leaf = loaded[f"leaf_{i}"]
            if tuple(leaf.shape) != tuple(shape.shape):
                get_logger().warning("opt_state.npz leaf %d shape mismatch", i)
                return None
            leaves.append(leaf.astype(shape.dtype))
        get_logger().info("restored phase-2 optimizer states (aggregate %d)", expect_key)
        return jax.tree.unflatten(treedef, leaves)

    def _try_resume_obd(self, driver) -> tuple[dict, int, int]:
        """(initial params, aggregations already done, phase-1 rounds done).

        ``algorithm_kwargs.resume_dir`` restores the round record and the
        latest round checkpoint, then fast-forwards the phase driver by
        REPLAYING its own transition rules over the recorded aggregates
        (each entry carries the phase that produced it — asserted during
        the replay).  Documented resume deviations, matching the threaded
        server's resume semantics: clients restart from the EXACT aggregate
        rather than the quantized broadcast, and the phase-2 optimizer
        continuation restarts at the resume point."""
        config = self.config
        resume_dir = config.algorithm_kwargs.get("resume_dir")
        if not resume_dir:
            return self.engine.init_params(config.seed), 0, 0
        from ..method.fed_obd.driver import replay_resume
        from ..util.resume import load_resume_state

        params, entries, _last = load_resume_state(resume_dir)
        if params is None:
            get_logger().warning(
                "nothing resumable under %s; starting fresh", resume_dir
            )
            return self.engine.init_params(config.seed), 0, 0
        # replay the RECORDED phase sequence through the driver (one
        # definition of the transition rules, shared with the threaded
        # server); a tail from a superseded schedule is dropped
        kept_keys, phase1_ticks = replay_resume(driver, entries)
        kept = len(kept_keys)
        self._stat = {k: entries[k] for k in kept_keys}
        if 0 in entries:
            self._stat[0] = entries[0]
        dropped = kept < len([k for k in entries if k > 0])
        if dropped and kept:
            # training must continue from the last KEPT aggregate, not the
            # dropped schedule's final params (stat key == round_N.npz name)
            from ..util.resume import load_round_checkpoint

            kept_params = load_round_checkpoint(resume_dir, kept_keys[-1])
            if kept_params is not None:
                params = kept_params
        self._max_acc = max(
            (s.get("test_accuracy", 0.0) for s in self._stat.values()),
            default=0.0,
        )
        # resume landing in phase 2 (or exactly at the switch) continues
        # the optimizer states saved with the last kept aggregate; under
        # an active selection the phase-1 carry (each slot's state from
        # its last participation) is saved/restored the same way
        self._resumed_opt_state = None
        if (
            kept
            and driver.phase is not None
            and (
                not driver.phase.block_dropout or self._phase1_carries_opt
            )
        ):
            self._resumed_opt_state = self._load_opt_state(
                resume_dir, kept_keys[-1]
            )
        get_logger().info(
            "resumed fed_obd from %s: %d aggregates replayed, phase now %s",
            resume_dir,
            kept,
            driver.phase.name if driver.phase else "finished",
        )
        return params, kept, phase1_ticks

    def _all_weights(self) -> np.ndarray:
        weights = np.asarray(self._dataset_sizes, np.float32).copy()
        weights[self.config.worker_number :] = 0.0
        return weights

    def _phase2_weights(self, stat_key: int) -> np.ndarray:
        """Phase-2 (nominally full-participation) weights with the round's
        availability mask folded in — phase-2 epochs drop/corrupt clients
        exactly like phase-1 rounds, keyed by the aggregate's stat key."""
        from ..util.faults import apply_fault_plan

        return apply_fault_plan(
            self._fault_plan,
            self._min_quorum,
            stat_key,
            None,
            self._all_weights(),
            self.config.worker_number,
        )

    def run(self) -> dict:
        """Drive the phases off the SAME :class:`ObdRoundDriver` the
        threaded server uses (``method/fed_obd/driver.py``) — the round
        structure has exactly one definition across executors.

        With ``algorithm_kwargs.round_horizon`` > 1 (client-axis session
        only), consecutive SAME-phase rounds run as one fused dispatch:
        the horizon is clamped to the phase's remaining budget so every
        phase switch lands on a horizon boundary, checkpoints and
        opt-state saves land on boundaries (the exact aggregate rides the
        fused carry), and the rng chain advances in-program — the
        aggregate chain is bit-identical to H=1.  ``early_stop`` needs
        every round's test metric on host before the next round may run,
        so it degrades fusion to per-round, loudly."""
        import time as _time

        from ..engine.engine import (
            slow_metrics_from_confusion,
            stacked_round_metrics,
        )
        from ..method.fed_obd.driver import ObdRoundDriver

        config = self.config
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        driver = ObdRoundDriver.from_config(config)
        init_params, resumed_aggs, resumed_phase1 = self._try_resume_obd(driver)
        # _place_params = stored per-leaf layout + jnp.copy: the copy
        # because device_put of aligned host numpy (the npz resume path)
        # ALIASES the python-owned buffer and the phase programs DONATE
        # these params; the per-leaf layout (replicated client-axis, the
        # expert layout on ep) because the phase outputs are pinned to it
        # — staging the first round replicated would leave the donated
        # expert-sharded leaves unaliasable (two live copies of exactly
        # the model-sharded kernels) and retrace on the second round
        train_params = self._place_params(init_params)
        rng = jax.random.PRNGKey(config.seed)
        for _ in range(resumed_aggs):  # keep the rng stream aligned
            rng, _r, _b = jax.random.split(rng, 3)
        fused = self.round_horizon > 1
        if fused and driver.early_stop:
            get_logger().warning(
                "round_horizon=%d with early_stop: the plateau decision"
                " needs each round's test metric on host before the next"
                " round may run — running per-round (H=1)",
                self.round_horizon,
            )
            fused = False
        if fused:
            # replicate the chain carry up front: the fused program
            # returns it replicated, and a sharding mismatch on the first
            # chunk would retrace per run (see _run_horizon)
            rng = jax.device_put(rng, self._replicated)

        # per-slot optimizer states, carried round-to-round (restored from
        # opt_state.npz when the resume landed on the matching aggregate)
        opt_state_s = getattr(self, "_resumed_opt_state", None)
        if opt_state_s is not None:
            # same aliasing hazard as train_params: the phase programs
            # DONATE these states, so the restored numpy leaves need
            # XLA-owned buffers
            opt_state_s = jax.tree.map(
                jnp.copy, put_sharded(opt_state_s, self._client_sharding)
            )

        def fresh_opt_states():
            # pin the buffer to the session's slot layout (P("clients")
            # client-axis, replicated whole-mesh): the phase programs
            # DONATE this carry, and a compiler-chosen placement here
            # would alias against the pinned carry output with mismatched
            # per-device sizes.  Residency: init from the compute-dtype
            # view so the donated buffer byte-sizes match the in-program
            # optimizer.init over bf16 params (_opt_state_template)
            cdtype = self._resident_dtype
            return jax.jit(
                jax.vmap(
                    lambda p: self.engine.optimizer.init(
                        p if cdtype is None else tree_cast(p, cdtype)
                    ),
                    in_axes=None,
                    axis_size=self.n_slots,
                ),
                out_shardings=self._client_sharding,
            )(train_params)

        def step(fn, params, weights, round_number, phase_label, use_opt,
                 sel_host=None, stream_ids=None):
            nonlocal rng, opt_state_s
            rng, round_rng, bcast_rng = jax.random.split(rng, 3)
            if sel_host is not None:
                sel_idx = put_sharded(sel_host, self._client_sharding)
                client_rngs = self._split_sel_rngs(round_rng, sel_idx)
            elif stream_ids is not None:
                # streamed phase 1: dense-shaped program at the cohort
                # width — keys are the cohort's WORKER-ID rows of the
                # same full-population split (bit-exact vs dense/gather)
                sel_idx = None
                client_rngs = self._split_sel_rngs(
                    round_rng,
                    put_sharded(
                        np.asarray(stream_ids), self._client_sharding
                    ),
                )
            else:
                sel_idx = None
                # split to the shared stream count, slots at the leading
                # rows (identity slice on the client-axis session; the
                # ep/sp layouts take their worker_number rows of the SAME
                # default-mesh split — see _stream_slots)
                client_rngs = put_sharded(
                    jax.random.split(round_rng, self._stream_slots)[
                        : self.n_slots
                    ],
                    self._client_sharding,
                )
            weights = put_sharded(weights, self._client_sharding)
            if use_opt:
                # the opt-state carry is DONATED into the phase program —
                # a queued opt-state checkpoint fetch must win the race
                # with XLA reusing those buffers.  A carry-less phase 1
                # donates only the never-saved broadcast params: no
                # barrier needed there
                self._ckpt.barrier()
            # distinct phase labels: phase 2 compiles its own program
            # mid-run and must get its own compile grace
            exact, bcast, opt_state_s, metrics = self._watchdog.call(
                lambda: (
                    fn(
                        params, weights, client_rngs, bcast_rng,
                        opt_state_s if use_opt else None, sel_idx,
                    )
                    if sel_idx is not None
                    else fn(
                        params, weights, client_rngs, bcast_rng,
                        opt_state_s if use_opt else None,
                    )
                ),
                phase=phase_label,
                round_number=round_number,
            )
            self._trace.event(
                "dispatch", program=phase_label, round=round_number
            )
            self._opt_state_s = opt_state_s  # observable continuation state
            return exact, bcast, {
                k: float(np.asarray(v)) for k, v in metrics.items()
            }

        tick = resumed_phase1  # client-selection stream continues
        with self._ckpt:  # flush async round checkpoints at exit
            while not driver.finished:
                spec = driver.phase
                phase_two = not spec.block_dropout
                phase_label = "round-phase2" if phase_two else "round"
                if phase_two and self._phase2_fn is None:
                    self._phase2_fn = self._build_phase_fn(phase_two=True)
                carry_opt = phase_two or self._phase1_carries_opt
                h = (
                    max(1, min(self.round_horizon, driver.remaining))
                    if fused
                    else 1
                )
                if (
                    (carry_opt or h > 1)
                    and opt_state_s is None
                    and not self._population_streamed
                ):
                    # fresh per-slot optimizers: phase 2 with no phase-1
                    # rounds before it, the first carrying phase-1 round
                    # (never-selected slots keep these init states as
                    # their phase-2 seed), or a fused phase-1 scan — its
                    # carry needs a structure-stable opt buffer even when
                    # the rounds themselves rebuild optimizers
                    opt_state_s = fresh_opt_states()
                if phase_two:
                    base_key = max(self._stat) if self._stat else 0
                    keys = [base_key + i + 1 for i in range(h)]
                else:
                    keys = [tick + i + 1 for i in range(h)]
                    tick += h
                # profile_rounds keys off the stat keys (the OBD round
                # numbering the record rows use)
                self._trace.maybe_profile_start(keys[0], keys[-1])
                if h == 1:
                    key = keys[0]
                    round_start = _time.monotonic()
                    sel_host = None
                    stream_ids = None
                    if phase_two:
                        fn = self._phase2_fn
                        weights = self._phase2_weights(key)
                        if (
                            self._population_streamed
                            and not self._phase2_streamed_ready
                        ):
                            opt_state_s = self._materialize_streamed_phase2()
                    else:
                        fn = self._phase1_fn
                        if self._selection_gather:
                            sel_host, weights = self._select_indices(key)
                        elif self._population_streamed:
                            stream_ids = self._cohort_ids(key)
                            _idx, weights = self._select_indices(key)
                            self._take_cohort(key, stream_ids)
                            self._schedule_next_cohort(key + 1)
                            opt_state_s = self._take_cohort_opt(stream_ids)
                        else:
                            weights = self._select_weights(key)
                    participating = int((weights != 0).sum())
                    exact, train_params, met = step(
                        fn, train_params, weights, key, phase_label,
                        use_opt=carry_opt, sel_host=sel_host,
                        stream_ids=stream_ids,
                    )
                    if stream_ids is not None:
                        # the merged cohort rows drain back to the host
                        # store behind the next round's prefetch; the
                        # weight-0 padding rows write their own old
                        # values (a per-slot no-op)
                        self._writeback.submit(
                            stream_ids,
                            self._opt_state_s,
                            round=key,
                            bytes=int(
                                self._opt_population.row_nbytes
                                * len(stream_ids)
                            ),
                        )
                        opt_state_s = None
                        self._drain_writeback_spans()
                    with self._trace.span("eval", round=key):
                        metric = self._watchdog.call(
                            lambda: self._evaluate(exact),
                            phase="eval",
                            round_number=key,
                        )  # phase 2: check_acc semantics
                    self._trace.event("dispatch", program="eval", round=key)
                    self._trace.event("host_sync", round=key)
                    self._trace.hbm_watermark(key)
                    self._trace.count("rounds")
                    self._trace_fault_event(
                        key,
                        met.get("rejected_updates", 0),
                        selected=(
                            range(self.config.worker_number)
                            if phase_two
                            else None
                        ),
                    )
                    self._record_obd(
                        key, metric, met, exact, save_dir, spec.name,
                        round_seconds=_time.monotonic() - round_start,
                    )
                    self._post_guard_quorum(
                        key, participating, met.get("rejected_updates", 0)
                    )
                    improved = True
                    if driver.early_stop:
                        improved = self._has_improvement()
                    decision = driver.after_aggregate(
                        improved=improved, check_acc=spec.check_acc
                    )
                else:
                    fnh = self._obd_horizon_fns.get((phase_two, h))
                    if fnh is None:
                        fnh = self._obd_horizon_fns[(phase_two, h)] = (
                            self._build_obd_horizon_fn(phase_two, h)
                        )
                    if phase_two:
                        idx_rows = None
                        host_rows = np.stack(
                            [self._phase2_weights(k) for k in keys]
                        )
                        weight_rows = put_sharded(
                            host_rows, self._horizon_weight_sharding
                        )
                    else:
                        host_rows, weight_rows, idx_rows = (
                            self._horizon_selection_rows(keys[0], h)
                        )
                    # params, the opt carry AND the rng chain are donated
                    # into the fused program — pending background fetches
                    # must finish first
                    self._ckpt.barrier()
                    chunk_start = _time.monotonic()
                    (exact, train_params, opt_state_s, rng), outs = (
                        self._watchdog.call(
                            lambda gp=train_params, o=opt_state_s, r=rng,
                            w=weight_rows, i=idx_rows: fnh(gp, o, r, w, i),
                            phase=phase_label,
                            round_number=keys[-1],
                        )
                    )
                    self._opt_state_s = opt_state_s
                    self._trace.event(
                        "dispatch",
                        program=f"obd_horizon[{phase_label},h={h}]",
                        round=keys[-1],
                        rounds=h,
                    )
                    # ONE host sync per horizon: the stacked metric fetch
                    train_mets = {
                        k: np.asarray(v) for k, v in outs[0].items()
                    }
                    per_round = stacked_round_metrics(outs[1])
                    confusion = np.asarray(outs[2]) if len(outs) > 2 else None
                    self._trace.event("host_sync", round=keys[-1])
                    self._trace.hbm_watermark(keys[-1])
                    chunk_seconds = _time.monotonic() - chunk_start
                    self._trace.span_record(
                        "horizon",
                        chunk_seconds,
                        first_round=keys[0],
                        last_round=keys[-1],
                        rounds=h,
                        phase=spec.name,
                    )
                    self._trace.count("rounds", h)
                    for i, key in enumerate(keys):
                        metric = per_round[i]
                        if confusion is not None:
                            metric.update(
                                slow_metrics_from_confusion(confusion[i])
                            )
                        met = {k: float(v[i]) for k, v in train_mets.items()}
                        self._trace_fault_event(
                            key,
                            met.get("rejected_updates", 0),
                            selected=(
                                range(self.config.worker_number)
                                if phase_two
                                else None
                            ),
                        )
                        # only the boundary's exact aggregate materialized
                        self._record_obd(
                            key, metric, met,
                            exact if key == keys[-1] else None,
                            save_dir, spec.name,
                            # in-chunk rounds don't materialize individually;
                            # the chunk's amortized share matches the FedAvg
                            # fused rows
                            round_seconds=chunk_seconds / h,
                        )
                        self._post_guard_quorum(
                            key,
                            (host_rows[i] != 0).sum(),
                            met.get("rejected_updates", 0),
                        )
                        # h never exceeds the phase budget, so only the
                        # final tick can switch phases / end training
                        decision = driver.after_aggregate(
                            improved=True, check_acc=spec.check_acc
                        )
                if decision.annotations or carry_opt:
                    # the states entering phase 2 (at the switch), after
                    # every phase-2 aggregate, and — under an active
                    # selection — after every carrying phase-1 boundary
                    # are what a resume needs
                    self._save_opt_state(keys[-1])
                if decision.annotations:
                    get_logger().info(
                        "phase switch -> %s",
                        driver.phase and driver.phase.name,
                    )
                    self._trace.event(
                        "phase_switch",
                        round=keys[-1],
                        phase=(driver.phase.name if driver.phase else "end"),
                    )
                # kills fire only after the chunk's records, the boundary
                # checkpoint, and the opt-state save are all queued — the
                # writer drains on the raise (``with self._ckpt``), so the
                # resume replay finds a consistent phase state
                self._maybe_kill(keys[0], keys[-1])
                self._trace.maybe_profile_stop(keys[-1])
                if decision.end_training:
                    break
        return {"performance": self._stat}

    # ------------------------------------------------------------------
    def _record_obd(
        self, stat_key, metric, round_metrics, exact, save_dir, phase_name="",
        round_seconds=0.0,
    ):
        mb = 1 / 8e6
        extra = {
            "received_mb": round_metrics["upload_bits"] * mb,
            "sent_mb": round_metrics["bcast_bits"] * mb,
            "round_seconds": round_seconds,
            # which phase produced this aggregate — lets a resume replay
            # the driver's transitions from the record alone
            "phase": phase_name,
        }
        if "rejected_updates" in round_metrics:
            extra["rejected_updates"] = round_metrics["rejected_updates"]
        if exact is None:
            # mid-horizon round under fusion: the exact aggregate was
            # never materialized — stat row only; checkpoints land on
            # horizon boundaries (the FedAvg fused loop's contract, and
            # what resume expects: the latest round with BOTH a
            # checkpoint and a record row)
            self._note_round(stat_key, metric, save_dir, extra=extra)
            self._max_acc = max(self._max_acc, metric["accuracy"])
        else:
            self._record(stat_key, metric, exact, save_dir, extra=extra)
        if round_metrics["upload_bits"]:
            # wire bits / full-precision full-model bits per selected client
            # — the combined dropout × quantization saving (analyze_log
            # derives the same product from the threaded path's logs)
            get_logger().info(
                "wire ratio %.4f",
                round_metrics["upload_bits"]
                / (self._total_params * 32 * max(1, self._selected_count)),
            )

    @property
    def _selected_count(self) -> int:
        n = self.config.algorithm_kwargs.get("random_client_number")
        return int(n) if n else self.config.worker_number

    def _has_improvement(self) -> bool:
        """5-point plateau on test accuracy (AggregationServer._convergent,
        reference ``aggregation_server.py:166-184``)."""
        accs = [s["test_accuracy"] for s in self._stat.values()]
        if len(accs) < 6:
            return True
        return max(accs[-5:]) > max(accs[:-5])
