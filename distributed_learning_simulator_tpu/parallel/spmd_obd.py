"""FedOBD as SPMD round programs.

The canonical ``fed_obd_train.sh`` workload (100 clients, block dropout,
NNADQ transport — reference ``method/fed_obd``) on the fast path: each
phase-1 round — every selected client's local epochs, its opportunistic
block-dropout selection, the NNADQ transport distortion, and the weighted
FedAvg reduction — is ONE jitted program over the ``clients`` mesh axis.
Phase 2 (per-epoch aggregation, reference ``fed_obd/worker.py:47-53``) is a
second program invoked once per epoch.

In-program equivalents of the host-side machinery:

* block selection (``obd_algorithm.py``): per-block L2 deltas via segment
  sums, greedy keep under the ``1-dropout_rate`` budget as a ``lax.scan``
  over blocks in score order — per-client data-dependent selection without
  leaving the device;
* ``ParameterMessage.complete`` (server fills dropped keys from the old
  global): ``where(block_kept, local, global)`` before the weighted psum;
* NNADQ endpoints: ``nnadq_quantize_dequantize`` applied to kept uploads
  and to the broadcast global (``quant_broadcast=True``, reference
  ``fed_obd/server.py:14-15``); payload bytes are accounted analytically
  from the adaptive bit-widths the codec chose in-program.

Host side keeps the reference's phase state machine (rounds → phase 2 on
exhaustion/plateau → end), round records, and best-model artifact.

Optimizer continuation (``reuse_learning_rate``, reference
``util/model.py:6-23``): phase 1 rebuilds each client's optimizer per round
(AggregationWorker semantics) but RETURNS the final per-slot optimizer
states; at the phase switch those states seed phase 2, and every phase-2
epoch threads them through — the schedule position and momentum continue
across the switch and across phase-2 epochs exactly as on the threaded
executor (``method/fed_obd/worker.py`` + ``Trainer.load_parameter_dict``
with ``reuse_learning_rate=True``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..method.fed_obd.obd_algorithm import get_module_blocks
from ..ops.quantization import nnadq_quantize_dequantize
from ..utils.logging import get_logger
from .mesh import put_sharded
from .spmd import SpmdFedAvgSession, scan_local_epochs_carry, shard_map_compat
from jax.sharding import PartitionSpec as P


class SpmdFedOBDSession(SpmdFedAvgSession):
    """Two-phase FedOBD with block dropout + quantized transport, one
    program per phase.  ``codec`` selects the wire numerics: ``"nnadq"``
    (fed_obd) or ``"qsgd"`` (fed_obd_sq, reference
    ``method/fed_obd/__init__.py:16-22``)."""

    _uses_val_policy = False  # own round program; no val policy

    def __init__(self, *args, codec: str = "nnadq", **kwargs) -> None:
        self._phase2_fn = None
        self._codec = codec
        super().__init__(*args, **kwargs)

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        config = self.config
        self._dropout_rate = float(config.algorithm_kwargs["dropout_rate"])
        self._nnadq_weight = float(
            config.endpoint_kwargs.get("worker", {}).get("weight", 0.01)
        )
        # static block structure from the parameter template
        template = jax.eval_shape(
            lambda: self.engine.init_params(config.seed)
        )
        keys = list(template.keys())
        blocks = get_module_blocks(keys)
        self._block_id = {
            k: i for i, block in enumerate(blocks) for k in block
        }
        self._block_sizes = np.zeros(len(blocks), np.float32)
        for k in keys:
            self._block_sizes[self._block_id[k]] += int(
                np.prod(template[k].shape)
            )
        self._total_params = float(self._block_sizes.sum())
        self._phase1_fn = self._build_phase_fn(phase_two=False)
        return self._phase1_fn

    def _train_engine(self):
        """The engine the round program trains with — a sharded-model twin
        in the expert-parallel subclass."""
        return self.engine

    def _build_phase_fn(self, phase_two: bool):
        import math

        engine = self._train_engine()
        epochs = 1 if phase_two else self.config.epoch
        weight_cfg = self._nnadq_weight
        block_sizes = jnp.asarray(self._block_sizes)
        block_id = self._block_id
        threshold = (1.0 - self._dropout_rate) * self._total_params

        if self._codec == "qsgd":
            from ..ops.quantization import qsgd_quantize_dequantize

            level = int(
                self.config.endpoint_kwargs.get("worker", {}).get(
                    "quantization_level", 255
                )
            )
            qbits = math.ceil(math.log2(level + 1)) + 1  # level plane + signs

            def qdq(x, key):
                return qsgd_quantize_dequantize(x, key, level), jnp.float32(qbits)

        else:

            def qdq(x, key):
                return nnadq_quantize_dequantize(x, weight_cfg)

        def keep_mask(local, global_params):
            """Greedy block selection under the parameter budget
            (obd_algorithm.get_block_parameter, reference
            ``obd_algorithm.py:88-127``)."""
            sq = jnp.zeros(block_sizes.shape[0])
            for k, v in local.items():
                d = v.astype(jnp.float32) - global_params[k].astype(jnp.float32)
                sq = sq.at[block_id[k]].add(jnp.sum(jnp.square(d)))
            score = jnp.sqrt(sq) / block_sizes
            order = jnp.argsort(-score)
            sizes_ord = block_sizes[order]

            def body(partial, size_i):
                keep = partial + size_i <= threshold
                return partial + size_i * keep, keep

            _, keep_ord = jax.lax.scan(body, jnp.float32(0.0), sizes_ord)
            return jnp.zeros(block_sizes.shape[0], bool).at[order].set(keep_ord)

        def local_train(global_params, data, weight, rng, opt_state=None):
            rng, quant_rng = jax.random.split(rng)
            # phase 1: optimizer rebuilt per round (opt_state None); phase 2:
            # reuse_learning_rate continuation from the carried state
            params, opt_out, summed = scan_local_epochs_carry(
                engine, epochs, global_params, data, rng, opt_state
            )

            selected = (weight > 0).astype(jnp.float32)
            upload = {}
            upload_bits = jnp.float32(0.0)
            if phase_two:
                # per-epoch full-delta uploads through the codec
                for i, (k, v) in enumerate(params.items()):
                    delta = v.astype(jnp.float32) - global_params[k].astype(
                        jnp.float32
                    )
                    dq, bits = qdq(delta, jax.random.fold_in(quant_rng, i))
                    upload[k] = global_params[k].astype(jnp.float32) + dq
                    upload_bits += bits * v.size
            else:
                keep = keep_mask(params, global_params)
                for i, (k, v) in enumerate(params.items()):
                    mask = keep[block_id[k]]
                    g = global_params[k].astype(jnp.float32)
                    # the codec sees the block DIFF, as the reference sends
                    # (``method/fed_obd/worker.py:68`` get_parameter_diff):
                    # a delta's span is the span of one round's movement, so
                    # the quantization step stays far below the values' own
                    # scale — quantizing VALUES instead snaps the per-round
                    # drift back to the grid and stalls training
                    dq, bits = qdq(
                        v.astype(jnp.float32) - g,
                        jax.random.fold_in(quant_rng, i),
                    )
                    # complete(): dropped blocks fall back to the old global
                    upload[k] = jnp.where(mask, g + dq, g)
                    upload_bits += mask * bits * v.size
            contribution = jax.tree.map(lambda p: p * weight, upload)
            summed = dict(summed, upload_bits=upload_bits * selected)
            return contribution, opt_out, summed

        return self._wrap_phase_program(local_train, qdq, phase_two)

    def _wrap_phase_program(self, local_train, qdq, phase_two: bool):
        """Client-axis layout: slots over the ``clients`` mesh axis,
        chunk-scanned vmap inside ``shard_map``, psum aggregation.  The
        expert-parallel subclass overrides this with a whole-mesh-per-
        client GSPMD layout (clients as a plain scan)."""

        def chunk_size(slots_local: int) -> int:
            mb = self.client_chunk
            if mb <= 0:
                mb = 8 if jax.default_backend() == "tpu" else slots_local
            mb = max(1, min(mb, slots_local))
            while slots_local % mb:
                mb -= 1
            return mb

        def round_program(global_params, opt_state_s, weights, rngs, bcast_rng, data):
            def shard_body(global_params, opt_state_s, data, weights, rngs, bcast_rng):
                slots_local = weights.shape[0]
                mb = chunk_size(slots_local)

                def run_slots(d, w, r, o):
                    # phase 1: o is None (optimizer rebuilt per round)
                    return jax.vmap(
                        local_train,
                        in_axes=(None, 0, 0, 0, 0 if phase_two else None),
                    )(global_params, d, w, r, o)

                if mb == slots_local:
                    contributions, opt_out, metrics = run_slots(
                        data, weights, rngs, opt_state_s
                    )
                    local_sum = jax.tree.map(
                        lambda c: jnp.sum(c, axis=0), contributions
                    )
                    metrics = jax.tree.map(lambda m: jnp.sum(m), metrics)
                else:
                    # scan client chunks to bound activation memory (same
                    # time-multiplexing as SpmdFedAvgSession.shard_body)
                    n_chunks = slots_local // mb

                    def to_chunks(tree):
                        return jax.tree.map(
                            lambda x: x.reshape(n_chunks, mb, *x.shape[1:]), tree
                        )

                    chunks = (
                        to_chunks(data),
                        to_chunks(weights),
                        to_chunks(rngs),
                        to_chunks(opt_state_s) if phase_two else None,
                    )
                    _, _, met_shapes = jax.eval_shape(
                        run_slots, *jax.tree.map(lambda x: x[0], chunks)
                    )

                    def chunk_body(acc, chunk):
                        data_k, w_k, r_k, o_k = chunk
                        contrib, opt_k, met = run_slots(data_k, w_k, r_k, o_k)
                        acc_sum, acc_met = acc
                        acc_sum = jax.tree.map(
                            lambda a, c: a + jnp.sum(c, axis=0), acc_sum, contrib
                        )
                        acc_met = jax.tree.map(
                            lambda a, m: a + jnp.sum(m), acc_met, met
                        )
                        # per-slot optimizer states collect as scan outputs
                        return (acc_sum, acc_met), opt_k

                    init = (
                        jax.tree.map(
                            lambda p: jnp.zeros(p.shape, jnp.float32),
                            global_params,
                        ),
                        jax.tree.map(lambda s: jnp.zeros((), s.dtype), met_shapes),
                    )
                    (local_sum, metrics), opt_chunks = jax.lax.scan(
                        chunk_body, init, chunks
                    )
                    opt_out = jax.tree.map(
                        lambda x: x.reshape(slots_local, *x.shape[2:]),
                        opt_chunks,
                    )
                global_sum = jax.tree.map(
                    lambda s: jax.lax.psum(s, axis_name="clients"), local_sum
                )
                total_weight = jax.lax.psum(jnp.sum(weights), axis_name="clients")
                new_global = jax.tree.map(
                    lambda s, g: (s / jnp.maximum(total_weight, 1e-12)).astype(
                        g.dtype
                    ),
                    global_sum,
                    global_params,
                )
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                    metrics,
                )
                # quant_broadcast: what clients train from next round is the
                # codec-distorted global; the exact average stays server-side
                bcast = {}
                bcast_bits = jnp.float32(0.0)
                for i, (k, v) in enumerate(new_global.items()):
                    vq, bits = qdq(
                        v.astype(jnp.float32), jax.random.fold_in(bcast_rng, i)
                    )
                    bcast[k] = vq.astype(v.dtype)
                    bcast_bits += bits * v.size
                metrics = dict(metrics, bcast_bits=bcast_bits)
                return new_global, bcast, opt_out, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(
                    P(),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P(),
                ),
                out_specs=(P(), P(), P("clients"), P()),
            )(global_params, opt_state_s, data, weights, rngs, bcast_rng)

        # data as an argument, not a closure constant (see spmd.py); phase 2
        # also donates the carried optimizer states (same shape in and out)
        donate = (0, 1) if phase_two else (0,)
        jitted = jax.jit(round_program, donate_argnums=donate)

        def fn(global_params, weights, rngs, bcast_rng, opt_state_s=None):
            return jitted(
                global_params, opt_state_s, weights, rngs, bcast_rng, self._data
            )

        return fn

    # ------------------------------------------------------------------
    def _opt_state_template(self):
        """Abstract [S, ...] optimizer-state pytree (structure + shapes,
        nothing computed)."""
        return jax.eval_shape(
            lambda p: jax.vmap(
                self.engine.optimizer.init, in_axes=None, axis_size=self.n_slots
            )(p),
            jax.eval_shape(lambda: self.engine.init_params(self.config.seed)),
        )

    def _save_opt_state(self, stat_key: int) -> None:
        """Queue the per-slot optimizer states to disk, tagged with the
        aggregate they belong to — phase-2 resume then continues momentum
        and schedule position exactly (the SURVEY §5 TPU plan's
        'per-client opt state' checkpoint)."""
        leaves = jax.tree.leaves(self._opt_state_s)
        if jax.process_count() > 1:
            # the [S, ...] states are client-sharded across hosts; the
            # async writer can only fetch addressable arrays — reshard to
            # replicated first (same dance as _checkpointable)
            leaves = [jax.device_put(leaf, self._replicated) for leaf in leaves]
        payload = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
        payload["stat_key"] = np.int64(stat_key)
        self._ckpt.save_npz(
            os.path.join(self.config.save_dir, "aggregated_model", "opt_state.npz"),
            payload,
        )

    def _load_opt_state(self, resume_dir: str, expect_key: int):
        """The saved optimizer states, or None when absent / from a
        different aggregate than the resume point."""
        path = os.path.join(resume_dir, "aggregated_model", "opt_state.npz")
        if not os.path.isfile(path):
            return None
        with np.load(path) as blob:
            if int(blob["stat_key"]) != expect_key:
                return None
            loaded = {k: blob[k] for k in blob.files if k != "stat_key"}
        template = self._opt_state_template()
        shapes, treedef = jax.tree.flatten(template)
        if len(loaded) != len(shapes):
            get_logger().warning("opt_state.npz does not match the optimizer")
            return None
        leaves = []
        for i, shape in enumerate(shapes):
            leaf = loaded[f"leaf_{i}"]
            if tuple(leaf.shape) != tuple(shape.shape):
                get_logger().warning("opt_state.npz leaf %d shape mismatch", i)
                return None
            leaves.append(leaf.astype(shape.dtype))
        get_logger().info("restored phase-2 optimizer states (aggregate %d)", expect_key)
        return jax.tree.unflatten(treedef, leaves)

    def _try_resume_obd(self, driver) -> tuple[dict, int, int]:
        """(initial params, aggregations already done, phase-1 rounds done).

        ``algorithm_kwargs.resume_dir`` restores the round record and the
        latest round checkpoint, then fast-forwards the phase driver by
        REPLAYING its own transition rules over the recorded aggregates
        (each entry carries the phase that produced it — asserted during
        the replay).  Documented resume deviations, matching the threaded
        server's resume semantics: clients restart from the EXACT aggregate
        rather than the quantized broadcast, and the phase-2 optimizer
        continuation restarts at the resume point."""
        config = self.config
        resume_dir = config.algorithm_kwargs.get("resume_dir")
        if not resume_dir:
            return self.engine.init_params(config.seed), 0, 0
        from ..method.fed_obd.driver import replay_resume
        from ..util.resume import load_resume_state

        params, entries, _last = load_resume_state(resume_dir)
        if params is None:
            get_logger().warning(
                "nothing resumable under %s; starting fresh", resume_dir
            )
            return self.engine.init_params(config.seed), 0, 0
        # replay the RECORDED phase sequence through the driver (one
        # definition of the transition rules, shared with the threaded
        # server); a tail from a superseded schedule is dropped
        kept_keys, phase1_ticks = replay_resume(driver, entries)
        kept = len(kept_keys)
        self._stat = {k: entries[k] for k in kept_keys}
        if 0 in entries:
            self._stat[0] = entries[0]
        dropped = kept < len([k for k in entries if k > 0])
        if dropped and kept:
            # training must continue from the last KEPT aggregate, not the
            # dropped schedule's final params (stat key == round_N.npz name)
            from ..util.resume import load_round_checkpoint

            kept_params = load_round_checkpoint(resume_dir, kept_keys[-1])
            if kept_params is not None:
                params = kept_params
        self._max_acc = max(
            (s.get("test_accuracy", 0.0) for s in self._stat.values()),
            default=0.0,
        )
        # resume landing in phase 2 (or exactly at the switch) continues the
        # optimizer states saved with the last kept aggregate
        self._resumed_opt_state = None
        if kept and driver.phase is not None and not driver.phase.block_dropout:
            self._resumed_opt_state = self._load_opt_state(
                resume_dir, kept_keys[-1]
            )
        get_logger().info(
            "resumed fed_obd from %s: %d aggregates replayed, phase now %s",
            resume_dir,
            kept,
            driver.phase.name if driver.phase else "finished",
        )
        return params, kept, phase1_ticks

    def _all_weights(self) -> np.ndarray:
        weights = np.asarray(self._dataset_sizes, np.float32).copy()
        weights[self.config.worker_number :] = 0.0
        return weights

    def run(self) -> dict:
        """Drive the phases off the SAME :class:`ObdRoundDriver` the
        threaded server uses (``method/fed_obd/driver.py``) — the round
        structure has exactly one definition across executors."""
        from ..method.fed_obd.driver import ObdRoundDriver

        config = self.config
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        driver = ObdRoundDriver.from_config(config)
        init_params, resumed_aggs, resumed_phase1 = self._try_resume_obd(driver)
        # jnp.copy after placement: device_put of aligned host numpy (the
        # npz resume path) ALIASES the python-owned buffer, and the round
        # program donates these params — XLA must own the memory it reuses
        # (see SpmdFedAvgSession._place_params)
        train_params = jax.tree.map(
            jnp.copy, put_sharded(init_params, self._replicated)
        )
        rng = jax.random.PRNGKey(config.seed)
        for _ in range(resumed_aggs):  # keep the rng stream aligned
            rng, _r, _b = jax.random.split(rng, 3)

        # per-slot optimizer states, carried round-to-round (restored from
        # opt_state.npz when the resume landed on the matching aggregate)
        opt_state_s = getattr(self, "_resumed_opt_state", None)
        if opt_state_s is not None:
            # same aliasing hazard as train_params: phase 2 DONATES these
            # states, so the restored numpy leaves need XLA-owned buffers
            opt_state_s = jax.tree.map(
                jnp.copy, put_sharded(opt_state_s, self._client_sharding)
            )

        def step(fn, params, weights, round_number, phase_label, use_opt):
            nonlocal rng, opt_state_s
            rng, round_rng, bcast_rng = jax.random.split(rng, 3)
            client_rngs = put_sharded(
                jax.random.split(round_rng, self.n_slots), self._client_sharding
            )
            weights = put_sharded(weights, self._client_sharding)
            if use_opt:
                # opt_state_s is DONATED into the phase-2 program — a
                # queued opt-state checkpoint fetch must win the race with
                # XLA reusing those buffers.  Phase 1 donates only the
                # never-saved broadcast params: no barrier needed there
                self._ckpt.barrier()
            # distinct phase labels: phase 2 compiles its own program
            # mid-run and must get its own compile grace
            exact, bcast, opt_state_s, metrics = self._watchdog.call(
                lambda: fn(
                    params,
                    weights,
                    client_rngs,
                    bcast_rng,
                    opt_state_s if use_opt else None,
                ),
                phase=phase_label,
                round_number=round_number,
            )
            self._opt_state_s = opt_state_s  # observable continuation state
            return exact, bcast, {
                k: float(np.asarray(v)) for k, v in metrics.items()
            }

        tick = resumed_phase1  # client-selection stream continues
        with self._ckpt:  # flush async round checkpoints at exit
            while not driver.finished:
                spec = driver.phase
                if spec.block_dropout:
                    fn = self._phase1_fn
                    tick += 1
                    weights = self._select_weights(tick)
                    stat_key = tick
                else:
                    if self._phase2_fn is None:
                        self._phase2_fn = self._build_phase_fn(phase_two=True)
                    if opt_state_s is None:
                        # phase 2 with no phase-1 rounds before it: fresh
                        # per-slot optimizers (nothing to continue from)
                        opt_state_s = jax.jit(
                            jax.vmap(
                                self.engine.optimizer.init,
                                in_axes=None,
                                axis_size=self.n_slots,
                            )
                        )(train_params)
                    fn = self._phase2_fn
                    weights = self._all_weights()
                    stat_key = max(self._stat) + 1 if self._stat else 1
                exact, train_params, met = step(
                    fn,
                    train_params,
                    weights,
                    stat_key,
                    "round" if spec.block_dropout else "round-phase2",
                    use_opt=not spec.block_dropout,
                )
                metric = self._watchdog.call(
                    lambda: self._evaluate(exact),
                    phase="eval",
                    round_number=stat_key,
                )  # phase 2: check_acc semantics
                self._record_obd(
                    stat_key, metric, met, exact, save_dir, spec.name
                )
                improved = True
                if driver.early_stop:
                    improved = self._has_improvement()
                decision = driver.after_aggregate(
                    improved=improved, check_acc=spec.check_acc
                )
                if decision.annotations or not spec.block_dropout:
                    # the states entering phase 2 (at the switch) and after
                    # every phase-2 epoch are what a resume needs
                    self._save_opt_state(stat_key)
                if decision.annotations:
                    get_logger().info(
                        "phase switch -> %s",
                        driver.phase and driver.phase.name,
                    )
                if decision.end_training:
                    break
        return {"performance": self._stat}

    # ------------------------------------------------------------------
    def _record_obd(
        self, stat_key, metric, round_metrics, exact, save_dir, phase_name=""
    ):
        mb = 1 / 8e6
        self._record(
            stat_key,
            metric,
            exact,
            save_dir,
            extra={
                "received_mb": round_metrics["upload_bits"] * mb,
                "sent_mb": round_metrics["bcast_bits"] * mb,
                # which phase produced this aggregate — lets a resume replay
                # the driver's transitions from the record alone
                "phase": phase_name,
            },
        )
        if round_metrics["upload_bits"]:
            # wire bits / full-precision full-model bits per selected client
            # — the combined dropout × quantization saving (analyze_log
            # derives the same product from the threaded path's logs)
            get_logger().info(
                "wire ratio %.4f",
                round_metrics["upload_bits"]
                / (self._total_params * 32 * max(1, self._selected_count)),
            )

    @property
    def _selected_count(self) -> int:
        n = self.config.algorithm_kwargs.get("random_client_number")
        return int(n) if n else self.config.worker_number

    def _has_improvement(self) -> bool:
        """5-point plateau on test accuracy (AggregationServer._convergent,
        reference ``aggregation_server.py:166-184``)."""
        accs = [s["test_accuracy"] for s in self._stat.values()]
        if len(accs) < 6:
            return True
        return max(accs[-5:]) > max(accs[:-5])
