"""Sparse-transport methods as SPMD round programs.

* ``fed_dropout_avg`` (reference ``method/fed_dropout_avg``): per-element
  Bernoulli dropout of the uploaded parameters; aggregation divides the
  masked weighted sum by the per-element surviving weight — here two psums
  (numerator and per-element denominator) over the ``clients`` axis.
* ``single_model_afd`` (reference ``method/smafd`` building blocks,
  ``ErrorFeedbackWorker`` + ``RandomDropoutAlgorithm``): error-feedback
  sparsified delta uploads.  The per-client residual is a device-resident
  state carried across rounds through the program — no host round-trips.
  ``topk_ratio`` selects magnitude thresholding (per-tensor k-th value via
  ``lax.top_k``; ties at the threshold admit extra elements — bounded by
  the tie multiplicity m: both paths agree on every element strictly above/
  below the threshold, the drift is < m kept elements, and for continuous
  float32 deltas ties have measure zero so the kept sets are identical —
  asserted in ``tests/test_smafd_topk_drift.py``.  The threaded path's
  native ``nth_element`` picker stays exact); otherwise random whole-tensor
  dropout under the ``1-dropout_rate`` parameter budget, matching
  ``RandomDropoutAlgorithm``.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import put_sharded
from .spmd import SpmdFedAvgSession, scan_local_epochs, shard_map_compat


class SpmdFedDropoutAvgSession(SpmdFedAvgSession):
    def _upload_cost_factor(self) -> float:
        return 1.0 - float(self.config.algorithm_kwargs["dropout_rate"])

    def _build_round_fn(self):
        engine = self.engine
        epochs = self.config.epoch
        dropout_rate = float(self.config.algorithm_kwargs["dropout_rate"])

        def local_train(global_params, data, weight, rng, val=None):
            rng, drop_rng = jax.random.split(rng)
            params, summed = scan_local_epochs(
                engine, epochs, global_params, data, rng, val_data=val
            )

            num, den = {}, {}
            send_num = jnp.float32(0.0)
            for i, (k, v) in enumerate(params.items()):
                keep = jax.random.bernoulli(
                    jax.random.fold_in(drop_rng, i),
                    p=1.0 - dropout_rate,
                    shape=v.shape,
                ).astype(jnp.float32)
                dropped = v.astype(jnp.float32) * keep
                # aggregation weight = (element survived) × dataset size
                # (reference ``fed_dropout_avg/algorithm.py:8-19``; a zero
                # PARAMETER VALUE also zeroes the weight there — the `!= 0`
                # test cannot tell a dropped element from a zero one)
                elem_w = (dropped != 0).astype(jnp.float32) * weight
                num[k] = dropped * elem_w
                den[k] = elem_w
                send_num += jnp.sum(keep) * (weight > 0)
            summed = dict(summed, send_num=send_num)
            return {"num": num, "den": den}, summed

        def round_program(global_params, weights, rngs, data, val):
            def shard_body(global_params, data, val, weights, rngs):
                contributions, metrics = jax.vmap(
                    local_train, in_axes=(None, 0, 0, 0, 0)
                )(global_params, data, weights, rngs, val if val else None)
                local_sum = jax.tree.map(
                    lambda c: jnp.sum(c, axis=0), contributions
                )
                global_sum = jax.tree.map(
                    lambda s: jax.lax.psum(s, axis_name="clients"), local_sum
                )
                new_global = {
                    k: (
                        global_sum["num"][k]
                        / jnp.where(
                            global_sum["den"][k] == 0, 1.0, global_sum["den"][k]
                        )
                    ).astype(global_params[k].dtype)
                    for k in global_params
                }
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                    metrics,
                )
                return new_global, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(
                    P(),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                ),
                out_specs=(P(), P()),
            )(global_params, data, val, weights, rngs)

        jitted = jax.jit(round_program, donate_argnums=(0,))

        def fn(global_params, weights, rngs):
            return jitted(
                global_params, weights, rngs, self._data, self._val_data or {}
            )

        return fn


class SpmdSMAFDSession(SpmdFedAvgSession):
    """single_model_afd: error-feedback sparsified delta uploads with the
    residual state living on device across rounds.

    The per-client residual is CHECKPOINTED alongside each round
    (``aggregated_model/err_state.npz``, tagged with its round) and
    restored on ``resume_dir`` — a resumed run is bit-identical to an
    uninterrupted one (``tests/test_resume.py``), retiring round 3's last
    documented resume deviation (reference residual semantics:
    ``simulation_lib/worker/error_feedback_worker.py:9-19``).  The file is
    worker_number × model-size; a missing/mismatched file degrades to a
    zero restart with a loud warning rather than failing the resume."""

    def _err_path(self, base_dir: str) -> str:
        import os

        return os.path.join(base_dir, "aggregated_model", "err_state.npz")

    def _record(self, round_number, metric, global_params, save_dir, extra=None):
        super()._record(round_number, metric, global_params, save_dir, extra)
        err_state = self._err_state
        if jax.process_count() > 1:
            # P("clients")-sharded residuals are non-addressable on a pod;
            # the async writer needs replicated arrays (same dance as
            # spmd_obd._save_opt_state)
            err_state = {
                k: jax.device_put(v, self._replicated)
                for k, v in err_state.items()
            }
        payload = dict(err_state)
        payload["__round__"] = np.int64(round_number)
        self._ckpt.save_npz(self._err_path(self.config.save_dir), payload)

    def _init_global_params(self):
        params, start_round = super()._init_global_params()
        if start_round > 1:
            from ..utils.logging import get_logger

            restored = None
            path = self._err_path(
                str(self.config.algorithm_kwargs.get("resume_dir"))
            )
            import os

            if os.path.isfile(path):
                with np.load(path) as blob:
                    if int(blob.get("__round__", -1)) == start_round - 1:
                        loaded = {
                            k: blob[k] for k in blob.files if k != "__round__"
                        }
                        if set(loaded) == set(self._err_state) and all(
                            loaded[k].shape == self._err_state[k].shape
                            for k in loaded
                        ):
                            restored = loaded
            if restored is not None:
                # jnp.copy: the residuals are DONATED into the round
                # program, and device_put of host numpy can alias the
                # python-owned buffer (see SpmdFedAvgSession._place_params)
                self._err_state = jax.tree.map(
                    jnp.copy,
                    put_sharded(
                        restored, NamedSharding(self.mesh, P("clients"))
                    ),
                )
                get_logger().info(
                    "smafd resume: restored error-feedback residuals "
                    "(round %d)", start_round - 1
                )
            else:
                get_logger().warning(
                    "smafd resume: err_state.npz missing or from a "
                    "different round — error-feedback residuals restart "
                    "at zero"
                )
        return params, start_round

    def _upload_cost_factor(self) -> float:
        kwargs = self.config.algorithm_kwargs
        if kwargs.get("topk_ratio") is not None:
            return float(kwargs["topk_ratio"])
        return 1.0 - float(kwargs.get("dropout_rate", 0.0))

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        err0 = jax.tree.map(
            lambda p: np.zeros((self.n_slots, *p.shape), np.float32),
            self.engine.init_params(self.config.seed),
        )
        # jnp.copy: donated state must live in XLA-owned buffers, not
        # (possibly aliased) host numpy memory — see _place_params
        self._err_state = jax.tree.map(
            jnp.copy,
            put_sharded(err0, NamedSharding(self.mesh, P("clients"))),
        )

    def _build_round_fn(self):
        engine = self.engine
        epochs = self.config.epoch
        kwargs = self.config.algorithm_kwargs
        topk_ratio = kwargs.get("topk_ratio")
        dropout_rate = float(kwargs.get("dropout_rate", 0.0))

        def sparsify(delta, rng):
            """Returns (sent, send_num)."""
            if topk_ratio is not None:
                sent = {}
                send_num = jnp.float32(0.0)
                for k, v in delta.items():
                    flat = v.reshape(-1)
                    kth = max(1, int(flat.size * float(topk_ratio)))
                    thresh = jax.lax.top_k(jnp.abs(flat), kth)[0][-1]
                    mask = (jnp.abs(v) >= thresh).astype(jnp.float32)
                    sent[k] = v * mask
                    send_num += jnp.sum(mask)
                return sent, send_num
            # random whole-tensor dropout under the parameter budget
            # (RandomDropoutAlgorithm semantics)
            names = list(delta)
            sizes_np = np.asarray(
                [float(delta[k].size) for k in names], np.float32
            )
            sizes = jnp.asarray(sizes_np)
            # threshold as a HOST f32 constant (np.sum), not a device
            # reduction: the threaded worker's aligned replication
            # (method/smafd/worker.py::_aligned_dropout) computes the
            # identical expression, so boundary keep decisions cannot
            # diverge by backend reduction order on big models
            threshold = np.float32(
                (1.0 - dropout_rate) * np.sum(sizes_np, dtype=np.float32)
            )
            order = jax.random.permutation(rng, len(names))

            def body(partial, i):
                size_i = sizes[order[i]]
                keep = partial + size_i <= threshold
                return partial + size_i * keep, keep

            _, keep_ord = jax.lax.scan(
                body, jnp.float32(0.0), jnp.arange(len(names))
            )
            keep = jnp.zeros(len(names), bool).at[order].set(keep_ord)
            sent = {
                k: delta[k] * keep[i].astype(jnp.float32)
                for i, k in enumerate(names)
            }
            send_num = jnp.sum(keep * sizes)
            return sent, send_num

        def local_train(global_params, err, data, weight, rng, val=None):
            rng, sparse_rng = jax.random.split(rng)
            params, summed = scan_local_epochs(
                engine, epochs, global_params, data, rng, val_data=val
            )

            selected = (weight > 0).astype(jnp.float32)
            delta = {
                k: params[k].astype(jnp.float32)
                - global_params[k].astype(jnp.float32)
                + err[k]
                for k in params
            }
            sent, send_num = sparsify(delta, sparse_rng)
            # residual: what was truncated this round; unselected slots keep
            # their residual untouched (they skipped the round)
            new_err = {
                k: selected * (delta[k] - sent[k]) + (1 - selected) * err[k]
                for k in delta
            }
            upload = {
                k: global_params[k].astype(jnp.float32) + sent[k] for k in sent
            }
            contribution = jax.tree.map(lambda p: p * weight, upload)
            summed = dict(summed, send_num=send_num * selected)
            return contribution, new_err, summed

        def round_program(global_params, err_state, weights, rngs, data, val):
            def shard_body(global_params, err_state, data, val, weights, rngs):
                contributions, new_err, metrics = jax.vmap(
                    local_train, in_axes=(None, 0, 0, 0, 0, 0)
                )(
                    global_params, err_state, data, weights, rngs,
                    val if val else None,
                )
                local_sum = jax.tree.map(
                    lambda c: jnp.sum(c, axis=0), contributions
                )
                global_sum = jax.tree.map(
                    lambda s: jax.lax.psum(s, axis_name="clients"), local_sum
                )
                total_weight = jax.lax.psum(jnp.sum(weights), axis_name="clients")
                new_global = jax.tree.map(
                    lambda s, g: (s / jnp.maximum(total_weight, 1e-12)).astype(
                        g.dtype
                    ),
                    global_sum,
                    global_params,
                )
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                    metrics,
                )
                return new_global, new_err, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(
                    P(),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                    P("clients"),
                ),
                out_specs=(P(), P("clients"), P()),
            )(global_params, err_state, data, val, weights, rngs)

        jitted = jax.jit(round_program, donate_argnums=(0, 1))

        def fn(global_params, weights, rngs):
            new_global, self._err_state, metrics = jitted(
                global_params, self._err_state, weights, rngs, self._data,
                self._val_data or {},
            )
            return new_global, metrics

        return fn
