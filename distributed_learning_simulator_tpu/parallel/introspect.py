"""Pre-dispatch program introspection for ``tools/shardcheck``.

A session's correctness contract is only partly visible in source text:
the PR 8 opt-state-carry donation-aliasing mismatch and the ep/sp
gather-stream init-ordering bug were *lowering-level* facts (layouts,
jit cache entries) that no AST pass can see.  This module defines the
neutral record a session hands the certifier BEFORE anything is
dispatched: every jitted program it would run, with ABSTRACT arguments
(``jax.ShapeDtypeStruct`` carrying the real shardings), its donated
positions, its out-shardings pin, and the carry correspondence the
donated buffers ride round-over-round.  The certifier then proves the
sharding/donation/dispatch invariants with ``jax.eval_shape`` +
``jax.jit(...).lower()`` — no execution, no training.

The hooks that build these specs live on the sessions themselves
(``SpmdFedAvgSession.shardcheck_programs`` and the sign-SGD/FedOBD
overrides) so they cannot drift from the dispatch paths they describe.
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass
class ProgramSpec:
    """One jitted program, described abstractly, pre-dispatch.

    ``args``/``alt_args`` are pytrees of ``ShapeDtypeStruct`` (shardings
    attached) matching exactly what the session's run loop would pass:
    ``alt_args`` are additional probes (a different round's host-side
    selection) that must hit the SAME jit cache entry.  ``carries`` maps
    each donated argument position to the output subtree the run loop
    feeds back into that position on the next dispatch — the pair whose
    layouts must agree for donation to be sound.
    """

    name: str  #: e.g. ``round[dense]``, ``horizon[h=2]``
    jitted: object  #: the jax.jit-wrapped callable (never called here)
    args: tuple
    donate_argnums: tuple = ()
    mesh: object = None
    #: out_shardings pin handed to jax.jit, or None (compiler-chosen)
    out_pin: object = None
    #: (donated argnum, fn(out_tree) -> fed-back subtree) pairs
    carries: tuple = ()
    #: same-signature probes — other rounds' abstract inputs
    alt_args: tuple = ()
    #: fused horizon length (0 = per-round program); when set,
    #: ``stacked_out`` extracts the per-round-stacked metrics subtree
    scanned_len: int = 0
    stacked_out: object = None
    #: ambient-mesh context factory wrapping trace/lower (use_mesh on
    #: the expert-parallel layouts), or None
    mesh_context: object = None


@dataclasses.dataclass
class DeclaredSpec:
    """One declared (mesh, PartitionSpec) pair for the sharding-
    vocabulary rule — checked structurally, before any NamedSharding
    construction could mask an unknown axis name with a crash."""

    label: str
    mesh: object
    spec: object  #: jax.sharding.PartitionSpec


def abstract_tree(tree):
    """``ShapeDtypeStruct`` twin of a placed array tree, shardings kept
    — the no-execution stand-in the certifier lowers against."""

    def one(x):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        )

    return jax.tree.map(one, tree)


def attach_shardings(shapes, shardings):
    """Zip an ``eval_shape`` template with a matching sharding tree."""
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        shardings,
    )


def host_abstract(array, sharding):
    """Abstract twin of a host numpy array the run loop would
    ``put_sharded`` at ``sharding``."""
    return jax.ShapeDtypeStruct(array.shape, array.dtype, sharding=sharding)


def key_abstract(sharding=None, leading=()):
    """Abstract PRNG key rows: ``leading + PRNGKey(0).shape``."""
    key = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    return jax.ShapeDtypeStruct(
        tuple(leading) + key.shape, key.dtype, sharding=sharding
    )


def named_sharding_decls(label, tree):
    """DeclaredSpecs for every NamedSharding-placed leaf of ``tree``."""
    decls = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        spec = getattr(sharding, "spec", None)
        if mesh is not None and spec is not None:
            decls.append(
                DeclaredSpec(
                    f"{label}{jax.tree_util.keystr(path)}", mesh, spec
                )
            )
    return decls
