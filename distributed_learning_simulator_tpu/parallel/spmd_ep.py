"""FedAvg rounds with expert-parallel MoE clients as one GSPMD program.

``model_kwargs.expert_parallel: N`` gives the whole mesh to each client's
MoE model: an ``("ep",)`` mesh shards the expert axis of the Switch-style
feed-forward kernels (``models/moe.py`` — ``w_in``/``w_out`` stored
``P("ep", None, None)``), clients train one after another inside the
round program (``lax.scan``), and the weighted aggregation accumulates
on device.  Unlike the sequence-parallel session (``spmd_sp.py``, manual
``shard_map`` + ring collectives), expert parallelism is left to GSPMD:
the round program is a plain ``jit`` over sharded parameters and the
model's ``with_sharding_constraint`` annotations — XLA inserts the
token dispatch/combine all-to-alls over ICI.  That is the TPU-native
shape of the design: declare layouts, let the compiler place
collectives (the reference has no model-sharding story at all,
SURVEY.md §5).

Semantics are IDENTICAL to the unsharded client-axis session — GSPMD
partitioning preserves the math and the rng stream is the client-axis
one (``tests/test_expert_parallel_config.py`` pins ep=4 against the
client-axis trajectory).  Central evaluation uses the UNSHARDED engine,
sharing the parameter structure exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..engine.engine import ComputeEngine
from .mesh import use_mesh
from .spmd import (
    SpmdFedAvgSession,
    scan_weighted_clients,
    whole_mesh_session_shapes,
)


class SpmdExpertParallelSession(SpmdFedAvgSession):
    #: whole-mesh layout routed through the shared fused-round machinery:
    #: selection gather, round-horizon fusion and the update guard all
    #: apply (spmd.py::_wrap_round_programs)
    _whole_mesh_fused = True

    def __init__(
        self,
        config,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        expert_parallel: int,
    ) -> None:
        devices = jax.devices()
        if expert_parallel > len(devices):
            raise ValueError(
                f"expert_parallel={expert_parallel} exceeds the "
                f"{len(devices)}-device mesh"
            )
        kwargs = dict(getattr(config, "model_kwargs", {}) or {})
        kwargs.pop("expert_parallel", None)
        self._n_experts = int(kwargs.get("n_experts", 4))
        if self._n_experts % expert_parallel:
            raise ValueError(
                f"expert_parallel={expert_parallel} must divide "
                f"n_experts={self._n_experts}"
            )
        ep_mesh = Mesh(
            np.asarray(devices[:expert_parallel]), axis_names=("ep",)
        )
        # the ep-mode twin: same factory, same parameter structure, forward
        # annotated with expert-axis sharding constraints for GSPMD
        from ..models import create_model_context

        kwargs["ep_axis"] = "ep"
        ep_model_ctx = create_model_context(
            config.model_name, dataset_collection, **kwargs
        )
        ep_model_ctx.compute_dtype = model_ctx.compute_dtype
        self._ep_engine = ComputeEngine(
            ep_model_ctx, engine.hyper_parameter, total_steps=engine.total_steps
        )
        super().__init__(
            config, dataset_collection, model_ctx, engine, practitioners,
            mesh=ep_mesh,
        )
        if not any(spec != P() for spec in self._param_specs.values()):
            raise ValueError(
                f"expert_parallel set but model {config.model_name!r} has no "
                "expert-stacked kernels to shard (expected an MoE model, "
                "e.g. MoETransformerClassificationModel)"
            )

    def _leaf_spec(self, shape, name: str = "") -> P:
        # the expert-stacked feed-forward kernels [E, d_model, d_ff] /
        # [E, d_ff, d_model] shard their leading expert axis; everything
        # else replicates — by declaration (moe.py), not shape heuristics
        # (an attention out-kernel [nhead, head_dim, d_model] with
        # nhead == n_experts must NOT match)
        from ..models.moe import is_expert_param

        leaf = jax.ShapeDtypeStruct(shape, jnp.float32)
        if is_expert_param(name, leaf, self._n_experts):
            return P("ep", None, None)
        return P()

    def _round_mesh_context(self):
        # bare-PartitionSpec sharding constraints inside the MoE model
        # resolve against the ambient mesh (version-compat helper: jax
        # 0.4 has no jax.sharding.set_mesh)
        return use_mesh(self.mesh)

    def _build_round_fn(self):
        engine = self._ep_engine
        epochs = self.config.epoch
        guard_active = self._update_guard
        max_update_norm = self._max_update_norm
        _, metrics_shape = whole_mesh_session_shapes(self)

        def round_program(global_params, weights, rngs, data, val):
            return scan_weighted_clients(
                engine, epochs, global_params, data, weights, rngs,
                metrics_shape, val_data=val if val else None,
                guard_active=guard_active, max_update_norm=max_update_norm,
                compute_dtype=self._resident_dtype,
            )

        # out_shardings pin the new globals to the stored expert layout so
        # the donated round-over-round buffers never reshard; the gather
        # twin, horizon builder and dispatch fn (all under use_mesh via
        # _round_mesh_context) come from the shared machinery
        return self._wrap_round_programs(
            round_program, out_shardings=(self._param_shardings, None)
        )


def build_expert_parallel_session(ctx, session_args, session_kwargs):
    config = ctx.config
    model_kwargs = dict(config.model_kwargs)
    return SpmdExpertParallelSession(
        *session_args,
        expert_parallel=int(model_kwargs.get("expert_parallel", 0)),
    )
