"""Shapley-value methods as SPMD programs.

One program trains every client slot for the round and returns the STACKED
per-client parameters (no reduction — the SV engines need individual
uploads).  Subset metrics then evaluate directly on that device-resident
stack: a 0/1 worker mask per subset, masked weighted average, and central
inference — vmapped over subsets, with XLA inserting the cross-slot
collectives from the shardings.  Per round this replaces the reference's
"one full test inference per evaluated subset" (SURVEY.md §3.3 HOT) with a
handful of batched programs, and client params never visit the host.

Engines are the same host-side ``shapley/`` classes the threaded path uses
(GTG / multi-round / hierarchical); ``choose_best_subset``,
``need_init_performance`` (round-0 metric), per-round SV dicts, and
``shapley_values.json`` artifacts match the threaded server
(``method/shapley_value``)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..engine.batching import make_epoch_batches
from ..ml_type import MachineLearningPhase as Phase
from ..utils.logging import get_logger
from .mesh import put_sharded
from .spmd import SpmdFedAvgSession, scan_local_epochs, shard_map_compat

ENGINE_FOR = {
    "GTG_shapley_value": "GTGShapleyValue",
    "multiround_shapley_value": "MultiRoundShapleyValue",
    "Hierarchical_shapley_value": "HierarchicalShapleyValue",
}


class SpmdShapleySession(SpmdFedAvgSession):
    _uses_val_policy = False  # own round program; no val policy

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        from .. import shapley

        engine_name = ENGINE_FOR[self.config.distributed_algorithm]
        self._engine_cls = getattr(shapley, engine_name)
        self._sv_engine = None
        self.shapley_values: dict[int, dict] = {}
        self.shapley_values_S: dict[int, dict] = {}
        self._eval_batches = put_sharded(
            make_epoch_batches(
                self.dc.get_dataset(Phase.Test), self.config.batch_size
            ),
            self._replicated,
        )
        self._subset_eval = self._build_subset_eval()

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        engine = self.engine
        epochs = self.config.epoch

        def local_train(global_params, data, weight, rng):
            params, summed = scan_local_epochs(
                engine, epochs, global_params, data, rng
            )
            return jax.tree.map(lambda p: p.astype(jnp.float32), params), summed

        def round_program(global_params, weights, rngs, data):
            def shard_body(global_params, data, weights, rngs):
                params_s, metrics = jax.vmap(
                    local_train, in_axes=(None, 0, 0, 0)
                )(global_params, data, weights, rngs)
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                    metrics,
                )
                return params_s, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(P(), P("clients"), P("clients"), P("clients")),
                out_specs=(P("clients"), P()),
            )(global_params, data, weights, rngs)

        jitted = jax.jit(round_program)

        def fn(global_params, weights, rngs):
            return jitted(global_params, weights, rngs, self._data)

        return fn

    def _build_subset_eval(self):
        engine = self.engine

        @jax.jit
        def subset_eval(params_s, masks, weights, batches):
            def agg_one(mask):
                w = mask * weights
                tw = jnp.maximum(jnp.sum(w), 1e-12)
                return jax.tree.map(
                    lambda v: jnp.einsum("s,s...->...", w, v) / tw, params_s
                )

            params = jax.vmap(agg_one)(masks)
            return jax.vmap(lambda p: engine.eval_fn(p, batches))(params)

        return subset_eval

    # ------------------------------------------------------------------
    def _batch_metric(self, params_s, weights):
        workers = list(range(self.config.worker_number))

        def metric_many(subsets: list) -> list[float]:
            chunk = 16
            masks = np.zeros((len(subsets), self.n_slots), np.float32)
            for i, subset in enumerate(subsets):
                for w in subset:
                    masks[i, int(w)] = 1.0
            out: list[float] = []
            for start in range(0, len(subsets), chunk):
                part = masks[start : start + chunk]
                if part.shape[0] < chunk:
                    part = np.pad(part, ((0, chunk - part.shape[0]), (0, 0)))
                    part[len(masks) - start :, 0] = 1.0
                res = self._subset_eval(
                    params_s, jnp.asarray(part), weights, self._eval_batches
                )
                count = np.maximum(np.asarray(res["count"]), 1.0)
                acc = np.asarray(res["correct"]) / count
                out.extend(float(a) for a in acc[: len(masks) - start])
            return out[: len(subsets)]

        return workers, metric_many

    def _engine_kwargs(self) -> dict:
        """Same engine configuration as the threaded servers — shared
        definition in ``shapley.sv_engine_kwargs``."""
        from ..shapley import sv_engine_kwargs

        return sv_engine_kwargs(
            self.config,
            hierarchical=self.config.distributed_algorithm
            == "Hierarchical_shapley_value",
        )

    def run(self) -> dict:
        config = self.config
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        # resume from a previous session's latest round checkpoint (same
        # discovery as fed_avg/GNN/OBD: util/resume.py), else fresh init
        global_params, start_round = self._init_global_params()
        if start_round == 1:
            # need_init_performance: round-0 metric seeds the SV engine
            # (reference ``shapley_value_server.py:4-7``)
            init_metric = self._evaluate(global_params)
            self._stat[0] = {f"test_{k}": v for k, v in init_metric.items()}
        else:
            self._restore_sv_records(start_round)
        rng = jax.random.PRNGKey(config.seed)
        for _ in range(start_round - 1):  # resume: keep the rng stream aligned
            rng, _unused = jax.random.split(rng)
        choose_best = bool(config.algorithm_kwargs.get("choose_best_subset", False))

        with self._ckpt:  # flush async round checkpoints at exit
            self._run_rounds(
                config, global_params, rng, choose_best, save_dir, start_round
            )

        self._dump_sv()
        return {
            "performance": {k: v for k, v in self._stat.items() if k > 0},
            "sv": self.shapley_values,
            "sv_S": self.shapley_values_S,
        }

    def _restore_sv_records(self, start_round: int) -> None:
        """Bring forward the previous session's per-round SV dicts (dumped
        incrementally, so they survive a crash); a tail from rounds at or
        beyond the resume point is superseded and dropped.  The rebuilt
        engine is seeded with the last recorded round accuracy (its
        ``last_round_metric`` carry — with ``choose_best_subset`` the
        recorded metric is the chosen subset's, a documented deviation
        matching the threaded server's resume)."""
        resume_dir = self.config.algorithm_kwargs.get("resume_dir")
        for name, target in (
            ("shapley_values.json", self.shapley_values),
            ("shapley_values_S.json", self.shapley_values_S),
        ):
            path = os.path.join(resume_dir, name)
            if os.path.isfile(path):
                try:
                    with open(path, encoding="utf8") as f:
                        # int-normalize BOTH key levels (round and worker
                        # id) so restored rounds index identically to
                        # freshly computed ones
                        target.update(
                            {
                                int(k): {int(w): sv for w, sv in v.items()}
                                for k, v in json.load(f).items()
                            }
                        )
                except (json.JSONDecodeError, ValueError, AttributeError, TypeError):
                    # a crash mid-write can only leave a stale-but-valid
                    # file (writes go through os.replace), but tolerate a
                    # corrupt one from any source: params/round still
                    # resume, only that SV history is lost
                    get_logger().warning(
                        "unreadable %s; resuming without its SV history",
                        path,
                    )
        for d in (self.shapley_values, self.shapley_values_S):
            for k in [k for k in d if k >= start_round]:
                del d[k]
        get_logger().info(
            "resumed shapley session at round %d (%d SV rounds restored)",
            start_round,
            len(self.shapley_values),
        )

    def _dump_sv(self) -> None:
        """Both SV artifacts, rewritten after every round — same names as
        the threaded server (``method/shapley_value``).  Written to a temp
        file then ``os.replace``d so a crash mid-write (the exact window
        the per-round rewrite exists to survive) can never leave a
        truncated file for resume to choke on."""
        for name, source in (
            ("shapley_values.json", self.shapley_values),
            ("shapley_values_S.json", self.shapley_values_S),
        ):
            path = os.path.join(self.config.save_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "wt", encoding="utf8") as f:
                json.dump({str(k): v for k, v in source.items()}, f)
            os.replace(tmp, path)

    def _run_rounds(
        self, config, global_params, rng, choose_best, save_dir, start_round=1
    ):
        for round_number in range(start_round, config.round + 1):
            weights = put_sharded(
                self._select_weights(round_number), self._client_sharding
            )
            rng, round_rng = jax.random.split(rng)
            client_rngs = put_sharded(
                jax.random.split(round_rng, self.n_slots), self._client_sharding
            )
            params_s, _ = self._watchdog.call(
                lambda: self._round_fn(global_params, weights, client_rngs),
                phase="round",
                round_number=round_number,
            )

            workers, metric_many = self._batch_metric(params_s, weights)
            if self._sv_engine is None:
                # fresh start: the round-0 init metric; resume: the last
                # recorded round's accuracy (the engine's running
                # ``last_round_metric`` carry)
                self._sv_engine = self._engine_cls(
                    players=workers,
                    last_round_metric=self._stat[max(self._stat)][
                        "test_accuracy"
                    ],
                    **self._engine_kwargs(),
                )
            # each subset-batch evaluation gets its own deadline — the SV
            # metric callbacks are the round's dominant device work and must
            # not hang unguarded
            def guarded_many(subsets, rn=round_number, fn=metric_many):
                return self._watchdog.call(
                    lambda: fn(subsets), phase="eval", round_number=rn
                )

            self._sv_engine.set_metric_function(
                lambda subset: guarded_many([subset])[0]
            )
            self._sv_engine.set_batch_metric_function(guarded_many)
            self._sv_engine.compute(round_number=round_number)
            self.shapley_values[round_number] = dict(
                self._sv_engine.shapley_values[round_number]
            )
            self.shapley_values_S[round_number] = dict(
                self._sv_engine.shapley_values_S[round_number]
            )
            self._dump_sv()  # incremental: survives a crash, feeds resume

            agg_mask = np.zeros(self.n_slots, np.float32)
            if choose_best and self.shapley_values_S[round_number]:
                for w in self.shapley_values_S[round_number]:
                    agg_mask[int(w)] = 1.0
                get_logger().info(
                    "use subset %s", sorted(self.shapley_values_S[round_number])
                )
            else:
                agg_mask[: config.worker_number] = 1.0
            global_params = jax.tree.map(
                lambda v: jnp.einsum(
                    "s,s...->...",
                    jnp.asarray(agg_mask * self._dataset_sizes)
                    / max(float((agg_mask * self._dataset_sizes).sum()), 1e-12),
                    v,
                ),
                params_s,
            )
            metric = self._watchdog.call(
                lambda gp=global_params: self._evaluate(gp),
                phase="eval",
                round_number=round_number,
            )
            self._record(round_number, metric, global_params, save_dir)
