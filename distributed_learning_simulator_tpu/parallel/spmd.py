"""The SPMD fast path: one XLA program per federated round.

This is the heart of the TPU-first design (SURVEY.md §7): instead of N
worker threads time-sharing the chip (the simulation-faithful path in
``training.py``), the whole round — **every selected client's local epochs
plus the weighted FedAvg reduction** — is a single jitted program laid out
over a ``Mesh(("clients", "model"))``:

* client state (params, opt-state, rng) and client data are stacked on a
  leading ``clients`` axis, sharded over the mesh's ``clients`` axis;
* local training is ``vmap`` over the per-device client slots inside
  ``shard_map``; epochs/batches are ``lax.scan`` — no host round-trips;
* aggregation is a weighted ``psum`` over ICI — the reference's
  pipe-and-pickle hot loop (``server/server.py:64-85``) becomes one
  collective;
* client selection is a 0/1 weight mask (SURVEY.md §5 "treat selection as
  masking"), so the compiled program is round-invariant.

The host keeps the reference's control surface: per-round selection,
round_record.json, best-model artifact, early stop.
"""

import contextlib
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import DistributedTrainingConfig
from ..engine.batching import fixed_size_partition
from ..engine.engine import (
    ComputeEngine,
    maybe_slow_metrics,
    slow_metrics_from_confusion,
    stacked_round_metrics,
    summarize_metrics,
)
from ..ml_type import MachineLearningPhase as Phase
from ..ops.pytree import ParamVecLayout, flat_stack_weighted_sum, tree_cast
from ..util.checkpoint import atomic_json_dump
from ..utils.logging import get_logger
from .mesh import client_slots, make_mesh, put_sharded


def _client_phase_indices(config, practitioners, phase):
    """Worker-ordered per-client index arrays for one dataset phase."""
    indices = []
    for practitioner in sorted(practitioners, key=lambda p: p.worker_id):
        sampled = practitioner.get_sampler(config.dataset_name).sample(
            practitioner.practitioner_id
        )
        indices.append(np.asarray(sampled.get(phase, []), np.int64))
    return indices


def _stack_slot_batches(dataset, per_client_indices, n_slots, batch_size):
    """THE slot-stacking contract, shared by the training and validation
    stacks: pad every client's index set to ``n_batches × batch_size``
    (mask 0 on padding), add zero-weight padding slots up to ``n_slots``,
    and reshape to ``[C, n_batches, B, ...]``.  Returns (data, n_batches)."""
    max_size = max((len(i) for i in per_client_indices), default=0)
    n_batches = max(1, (max_size + batch_size - 1) // batch_size)
    slot_size = n_batches * batch_size
    inputs, targets, masks = [], [], []
    for idx in per_client_indices:
        padded, mask = fixed_size_partition(idx, slot_size)
        inputs.append(dataset.inputs[padded])
        targets.append(dataset.targets[padded])
        masks.append(mask)
    while len(inputs) < n_slots:  # zero-weight padding slots
        inputs.append(np.zeros_like(inputs[0]))
        targets.append(np.zeros_like(targets[0]))
        masks.append(np.zeros_like(masks[0]))

    def stack(parts, extra_shape):
        return np.stack(parts).reshape(
            n_slots, n_batches, batch_size, *extra_shape
        )

    data = {
        "input": stack(inputs, dataset.inputs.shape[1:]),
        "target": stack(targets, ()),
        "mask": stack(masks, ()),
    }
    return data, n_batches


def stack_client_data(config, dataset_collection, practitioners, n_slots):
    """Stack per-client training data to ``[C, n_batches, B, ...]`` with
    zero-weight padding slots; returns (data dict, dataset_sizes, n_batches)."""
    train = dataset_collection.get_dataset(Phase.Training)
    per_client_indices = _client_phase_indices(
        config, practitioners, Phase.Training
    )
    sizes = [len(idx) for idx in per_client_indices]
    data, n_batches = _stack_slot_batches(
        train, per_client_indices, n_slots, config.batch_size
    )
    dataset_sizes = np.asarray(sizes + [0] * (n_slots - len(sizes)), np.float32)
    return data, dataset_sizes, n_batches


def stack_client_val_data(config, dataset_collection, practitioners, n_slots):
    """Per-client VALIDATION batches ``[C, n_batches, B, ...]`` (or None
    when the phase is absent/empty) — the in-program substrate for the
    reference's iid ``choose_model_by_validation`` upload policy
    (``worker/aggregation_worker.py::KeepModelHook``).  Clients whose val
    split is empty get all-masked batches: their accuracy ties at 0 every
    epoch and the ``>=`` keep rule picks the final epoch, matching the
    threaded worker's per-worker disable."""
    if not dataset_collection.has_dataset(Phase.Validation):
        return None
    val = dataset_collection.get_dataset(Phase.Validation)
    if int(np.asarray(val.inputs).shape[0]) == 0:
        return None
    per_client_indices = _client_phase_indices(
        config, practitioners, Phase.Validation
    )
    if max((len(i) for i in per_client_indices), default=0) == 0:
        return None
    data, _ = _stack_slot_batches(
        val, per_client_indices, n_slots, config.batch_size
    )
    return data


def shard_map_compat(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


# in-program QSGD for quantized-upload methods on the SPMD path: on ICI
# there is no byte stream to pack — aggregation must just see the same
# dequantized levels the reference server would (``fed_paq`` = FedAvg +
# StochasticQuant endpoints, ``method/fed_paq/__init__.py:7-14``); the
# numerics live in ops/quantization.py, shared with the threaded codec
from ..ops.quantization import qsgd_quantize_dequantize as qsgd_dequantized


def scan_local_epochs(
    engine, epochs: int, global_params, data, rng, opt_state=None,
    val_data=None,
):
    """One client's local training: ``epochs`` of minibatch SGD from the
    fresh global params, optimizer rebuilt (AggregationWorker semantics,
    ``util/model.py:6-23``) unless ``opt_state`` is given
    (``reuse_learning_rate`` continuation — FedOBD phase 2).  Returns
    (params, summed metrics).  With ``val_data`` (the iid
    ``choose_model_by_validation`` policy — KeepModelHook semantics,
    reference ``aggregation_worker.py:33-44``), the returned params are
    the round's BEST epoch by validation accuracy (``>=``: later epoch
    wins ties), not the final ones.  Shared by every SPMD session's
    local-train body; use :func:`scan_local_epochs_carry` to also get
    the final optimizer state back."""
    params, _, metrics = scan_local_epochs_carry(
        engine, epochs, global_params, data, rng, opt_state, val_data
    )
    return params, metrics


def scan_local_epochs_carry(
    engine, epochs: int, global_params, data, rng, opt_state=None,
    val_data=None,
):
    # best-params mode (val_data) returns the FINAL epoch's opt_state, which
    # does not correspond to the returned best-epoch params — combining it
    # with opt-state continuation (reuse_learning_rate semantics, FedOBD
    # phase 2) would resume momentum from the wrong trajectory point
    assert opt_state is None or val_data is None, (
        "scan_local_epochs_carry: opt_state continuation cannot be combined "
        "with the best-params-by-validation policy (the returned opt_state "
        "is the final epoch's, not the best epoch's)"
    )
    if opt_state is None:
        opt_state = engine.optimizer.init(global_params)
    epoch_rngs = jax.random.split(rng, epochs)

    if val_data is None:

        def epoch_body(carry, epoch_rng):
            params, opt_state = carry
            params, opt_state, metrics = engine.train_epoch_fn(
                params, opt_state, data, epoch_rng
            )
            return (params, opt_state), metrics

        (params, opt_state), metrics = jax.lax.scan(
            epoch_body, (global_params, opt_state), epoch_rngs
        )
        return params, opt_state, jax.tree.map(lambda x: jnp.sum(x), metrics)

    def epoch_body(carry, epoch_rng):
        params, opt_state, best_params, best_acc = carry
        params, opt_state, metrics = engine.train_epoch_fn(
            params, opt_state, data, epoch_rng
        )
        summed = engine.eval_fn(params, val_data)
        acc = summed["correct"] / jnp.maximum(summed["count"], 1.0)
        better = acc >= best_acc
        best_params = jax.tree.map(
            lambda b, p: jnp.where(better, p, b), best_params, params
        )
        return (
            params,
            opt_state,
            best_params,
            jnp.where(better, acc, best_acc),
        ), metrics

    (params, opt_state, best_params, _), metrics = jax.lax.scan(
        epoch_body,
        (global_params, opt_state, global_params, jnp.float32(-1.0)),
        epoch_rngs,
    )
    return (
        best_params,
        opt_state,
        jax.tree.map(lambda x: jnp.sum(x), metrics),
    )


def guard_client_update(
    params,
    global_params,
    weight,
    summed,
    max_update_norm,
    sharded=None,
    reduce_axis=None,
):
    """THE device-side update-hygiene check, shared by the FedAvg round
    program and the OBD phase programs (one definition — the guard
    semantics must never drift between methods): reject a client whose
    round delta (``params − global_params``, leaf-paired) is non-finite or
    norm-exploded, or whose aggregation weight arrived poisoned (the
    FaultPlan corrupt-injection channel).  Returns ``(eff_weight,
    summed')`` — the rejected slot's effective weight is exactly zero, and
    the per-slot reject flag plus the effective weight ride the metrics
    tree (``_eff_weight`` is popped by the shard bodies to form the
    survivor-renormalized total weight).

    ``reduce_axis`` is the cross-stage flavor (the pipeline session):
    inside its shard_map the ``sharded`` leaves (the stacked trunk) are
    per-STAGE local slices, so each stage guards its OWN slice — local
    non-finite count and local norm contribution — and the verdict is
    all-reduced along the axis (``psum`` of the slice stats; replicated
    leaves are counted once).  Every stage then derives the IDENTICAL
    effective weight, which is exactly the consistency the old pipeline
    carve-out could not provide, with the same global-delta semantics as
    the client-axis guard."""
    if reduce_axis is None:
        finite = jnp.bool_(True)
        norm_sq = jnp.float32(0.0)
        for p, g in zip(
            jax.tree.leaves(params), jax.tree.leaves(global_params)
        ):
            delta = p.astype(jnp.float32) - g.astype(jnp.float32)
            finite = finite & jnp.all(jnp.isfinite(delta))
            norm_sq = norm_sq + jnp.sum(jnp.square(delta))
    else:
        sharded = sharded or {}
        local_nonfinite = jnp.float32(0.0)
        local_norm = jnp.float32(0.0)
        repl_finite = jnp.bool_(True)
        repl_norm = jnp.float32(0.0)
        for key in params:
            delta = params[key].astype(jnp.float32) - global_params[
                key
            ].astype(jnp.float32)
            if sharded.get(key):
                # stage-local slice: contribute this stage's share
                local_nonfinite = local_nonfinite + jnp.sum(
                    jnp.where(jnp.isfinite(delta), 0.0, 1.0)
                )
                local_norm = local_norm + jnp.sum(jnp.square(delta))
            else:
                # replicated leaf: identical on every stage, count once
                repl_finite = repl_finite & jnp.all(jnp.isfinite(delta))
                repl_norm = repl_norm + jnp.sum(jnp.square(delta))
        norm_sq = jax.lax.psum(local_norm, reduce_axis) + repl_norm
        finite = (
            jax.lax.psum(local_nonfinite, reduce_axis) == 0
        ) & repl_finite
    ok = finite & jnp.isfinite(weight)
    if max_update_norm > 0:
        ok = ok & (norm_sq <= jnp.float32(max_update_norm) ** 2)
    participating = (weight != 0).astype(jnp.float32)  # NaN != 0
    eff_weight = jnp.where(ok, weight, jnp.float32(0.0))
    summed = dict(
        summed,
        rejected_updates=jnp.where(ok, 0.0, participating),
        _eff_weight=eff_weight,
    )
    return eff_weight, summed


def guarded_average(global_sum, total_weight, params_in):
    """Survivor-renormalized average for guard-compiled programs: with at
    least one surviving weight this is the plain weighted average; with
    ZERO survivors (every upload rejected) the round keeps the OLD global
    params — dividing an all-zero sum by the epsilon floor would silently
    replace the trained model with zeros.  The host-side post-guard quorum
    check aborts such a round loudly right after the fetch."""
    return jax.tree.map(
        lambda s, old: jnp.where(
            total_weight > 0,
            (s / jnp.maximum(total_weight, 1e-12)).astype(old.dtype),
            old,
        ),
        global_sum,
        params_in,
    )


def whole_mesh_session_shapes(session):
    """Trace-time (params, metrics) shape templates for sessions that give
    the WHOLE mesh to one client at a time (sequence-parallel, expert-
    parallel): traced with the session's UNSHARDED engine — the sharded
    twin may need a bound mesh axis, and the structures are identical."""
    outer_engine = session.engine
    params_shape = jax.eval_shape(
        lambda: outer_engine.init_params(session.config.seed)
    )
    cdata_shape = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), session._data
    )
    metrics_shape = jax.eval_shape(
        lambda gp, cd, rng: scan_local_epochs(
            outer_engine, session.config.epoch, gp, cd, rng
        )[1],
        params_shape,
        cdata_shape,
        jax.ShapeDtypeStruct((2,), jnp.uint32),
    )
    return params_shape, metrics_shape


def scan_weighted_clients(
    engine,
    epochs: int,
    global_params,
    data,
    weights,
    rngs,
    metrics_shape,
    val_data=None,
    guard_active: bool = False,
    max_update_norm: float = 0.0,
    guard_sharded=None,
    guard_reduce_axis=None,
    compute_dtype=None,
):
    """Clients one after another as a ``lax.scan`` (the round body of the
    whole-mesh-per-client sessions, ``spmd_sp.py``/``spmd_ep.py``), with
    the client-axis rng contract: each client reserves a quant rng before
    training even when no codec is configured, so trajectories match the
    client-axis session's to float order (the equivalence tests pin it).
    Unselected clients flow through masked to weight 0 — SPMD needs a
    uniform program.  Returns (weighted-average params, summed metrics).

    ``guard_active`` compiles the shared update guard
    (:func:`guard_client_update`) into the scan body: a rejected client's
    effective weight is exactly zero, the total weight accumulates over
    SURVIVORS alongside the params, a zero-survivor round keeps the old
    global (:func:`guarded_average`), and the summed metrics gain the
    ``rejected_updates`` count — the same semantics the client-axis
    shard bodies compile in.  ``guard_sharded``/``guard_reduce_axis``
    select the cross-stage guard flavor (the pipeline session: per-stage
    slice stats all-reduced along ``pp`` — :func:`guard_client_update`).

    ``compute_dtype`` (amp residency, ``algorithm_kwargs.amp_resident``)
    casts the f32 master to the compute dtype ONCE here, before the
    client scan: the per-kernel ``_cast_for_compute`` inside the scan
    body then sees already-bf16 leaves (``astype`` is the identity), so
    the whole scan runs convert-free, client momentum follows the
    compute dtype (``optax`` inits from the params it is handed), and
    the weighted f32 accumulation below re-applies the master update
    exactly once per round — the classic mixed-precision recipe.  The
    guard compares each client against the cast view it actually
    started from.  ``None`` preserves the per-kernel-cast path
    bit-exactly."""
    train_globals = (
        tree_cast(global_params, compute_dtype)
        if compute_dtype is not None
        else global_params
    )

    def body(acc, xs):
        cdata, cval, weight, rng = xs
        rng, _ = jax.random.split(rng)
        params, summed = scan_local_epochs(
            engine, epochs, train_globals, cdata, rng,
            val_data=cval if cval else None,
        )
        # train-metric mask from the PRE-guard weight (the dense path's
        # selection flag); the guard's reject count rides separately,
        # unmasked, so a rejected participant is still counted
        selected = (weight > 0).astype(jnp.float32)
        if guard_active:
            acc_params, acc_metrics, acc_w, acc_rej = acc
            weight, summed = guard_client_update(
                params,
                train_globals,
                weight,
                summed,
                max_update_norm,
                sharded=guard_sharded,
                reduce_axis=guard_reduce_axis,
            )
            acc_w = acc_w + summed.pop("_eff_weight")
            acc_rej = acc_rej + summed.pop("rejected_updates")
        else:
            acc_params, acc_metrics = acc
        acc_params = jax.tree.map(
            lambda a, p: a + p.astype(jnp.float32) * weight,
            acc_params,
            params,
        )
        acc_metrics = jax.tree.map(
            lambda a, m: a + m * selected, acc_metrics, summed
        )
        if guard_active:
            return (acc_params, acc_metrics, acc_w, acc_rej), None
        return (acc_params, acc_metrics), None

    # accumulator shapes come from the params ACTUALLY in scope — under a
    # sharding session's shard_map these are local slices (pp: the trunk's
    # stage slice), not the unsharded template shapes
    zero_params = jax.tree.map(
        lambda g: jnp.zeros(g.shape, jnp.float32), global_params
    )
    zero_metrics = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), metrics_shape
    )
    init = (zero_params, zero_metrics)
    if guard_active:
        init = init + (jnp.float32(0.0), jnp.float32(0.0))
    carry, _ = jax.lax.scan(
        body,
        init,
        (data, val_data if val_data else {}, weights, rngs),
    )
    if guard_active:
        acc_params, metrics, total, rejected = carry
        new_global = guarded_average(acc_params, total, global_params)
        return new_global, dict(metrics, rejected_updates=rejected)
    acc_params, metrics = carry
    total = jnp.maximum(jnp.sum(weights), 1e-12)
    new_global = jax.tree.map(
        lambda a, g: (a / total).astype(g.dtype), acc_params, global_params
    )
    return new_global, metrics


class TraceCounterMixin:
    """Shared roundtrace surface for the SPMD sessions (requires
    ``self._trace``, ``self._fault_plan``, ``self._update_guard``,
    ``self.config``): the legacy counters, DERIVED from the trace
    recorder — the run loops emit ``dispatch``/``host_sync`` events at
    exactly the old increment sites, so the values stay pinned identical
    by the test_round_horizon / test_selection_gather dispatch-budget
    tests — plus the per-round ``fault`` event helper."""

    @property
    def dispatch_count(self) -> int:
        return self._trace.counters.get("dispatch", 0)

    @property
    def host_sync_count(self) -> int:
        return self._trace.counters.get("host_sync", 0)

    @property
    def rounds_run(self) -> int:
        return self._trace.counters.get("rounds", 0)

    def reset_dispatch_stats(self) -> None:
        self._trace.reset_counters("dispatch", "host_sync", "rounds")

    def cost_ledger(self) -> dict[str, dict[str, float]]:
        """Price every program this session would dispatch — the
        ``shardcheck_programs()`` inventory AOT-lowered and compiled
        under each spec's mesh context, nothing executed (the costwatch
        ledger; ``tools/costview --ledger`` and bench read it)."""
        from ..util.costwatch import session_cost_ledger

        return session_cost_ledger(self)

    def _trace_fault_event(
        self, round_number: int, rejected, selected=None
    ) -> None:
        """One ``fault`` trace event per faulted-machinery round: the
        guard's reject count plus how many SELECTED clients the round's
        availability mask dropped (the PR 7 weight-row masking) — every
        value is host state the loop already owns, fetched at the round's
        existing sync point, so the event costs nothing extra.
        ``selected`` overrides the cohort (OBD phase 2 participates
        fully while its stat keys keep advancing the selection stream)."""
        plan = self._fault_plan
        if not self._trace.enabled or plan is None:
            return
        if not (plan.injection_active or self._update_guard):
            return
        dropped = 0
        if plan.injection_active:
            if selected is None:
                from ..utils.selection import select_workers

                selected = select_workers(
                    self.config.seed,
                    round_number,
                    self.config.worker_number,
                    self.config.algorithm_kwargs.get("random_client_number"),
                )
            dropped = len(
                plan.dropped_clients(round_number, self.config.worker_number)
                & set(selected)
            )
        self._trace.event(
            "fault",
            round=round_number,
            rejected_updates=int(rejected),
            dropped_clients=dropped,
        )


class SpmdFedAvgSession(TraceCounterMixin):
    """FedAvg-family rounds as single SPMD programs.

    Supported method semantics: fed_avg (full/delta uploads are equivalent
    under full participation averaging) with random client selection, and
    fed_paq (``quantization_level`` set: client uploads pass through QSGD
    quantize→dequantize before the weighted psum).
    """

    #: whether this session's round program consumes ``_val_data`` (the
    #: iid best-of-round upload policy) — subclasses with their own round
    #: programs that ignore it opt out so __init__ skips the stack+put
    _uses_val_policy = True

    #: capability flag for the whole-mesh-per-client family (ep/sp/pp —
    #: the whole mesh to ONE client at a time, clients as a ``lax.scan``):
    #: subclasses that route their round programs through
    #: :meth:`_wrap_round_programs` set this True, which unlocks the
    #: selection-aware gather, round-horizon fusion, and the device-side
    #: update guard on their layouts.  Bespoke sessions (GNN, sparse,
    #: Shapley, smafd) leave it False and the knobs keep rejecting loudly.
    _whole_mesh_fused = False

    def __init__(
        self,
        config: DistributedTrainingConfig,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        mesh: Mesh | None = None,
        quantization_level: int | None = None,
        client_chunk: int = 0,
    ) -> None:
        self.config = config
        self.dc = dataset_collection
        self.model_ctx = model_ctx
        self.engine = engine
        self.mesh = mesh if mesh is not None else make_mesh()
        from .watchdog import DeadlineWatchdog

        # config.watchdog_seconds guards the SPMD path too (VERDICT r2
        # item 4): a wedged round program / eval fetch aborts with a
        # diagnostic instead of hanging the controller
        self._watchdog = DeadlineWatchdog.from_config(config, self.mesh)
        # FSDP over the inner ``model`` axis (SURVEY.md §7 item 10: "inner
        # mesh axis for TP/FSDP of larger client models"): client slots
        # partition over BOTH axes (every device trains clients), global
        # params are STORED sharded per-leaf over ``model``, all-gathered on
        # use and reduce-scattered after aggregation.  Enabled whenever the
        # mesh has a model axis; ``algorithm_kwargs.model_sharding: none``
        # opts out (params replicated, model axis idle for the base method).
        self._model_axis = int(self.mesh.shape.get("model", 1))
        model_sharding = str(
            config.algorithm_kwargs.get("model_sharding", "fsdp")
        )
        if model_sharding not in ("fsdp", "none"):
            raise ValueError(
                f"model_sharding must be 'fsdp' or 'none', got {model_sharding!r}"
            )
        self._fsdp = (
            self._model_axis > 1
            and model_sharding == "fsdp"
            and type(self) is SpmdFedAvgSession
        )
        slot_axes = ("clients", "model") if self._fsdp else ("clients",)
        # a session may bring a mesh without a clients axis (the
        # sequence-parallel session's ("sp",) mesh gives every device to
        # ONE client's model; clients are then a scan, not an axis)
        slot_axes = tuple(a for a in slot_axes if a in self.mesh.shape)
        self.n_slots = client_slots(config.worker_number, self.mesh, slot_axes)
        self.quantization_level = quantization_level
        # ``client_chunk: auto`` resolves from the tools/autotune
        # calibration cache — but the key needs ``s_pad``, so the value
        # is parsed here and resolved a few lines down, once the
        # selection-gather geometry is known
        raw_chunk = client_chunk or config.algorithm_kwargs.get(
            "client_chunk", 0
        )
        self._client_chunk_auto = (
            isinstance(raw_chunk, str) and raw_chunk.strip().lower() == "auto"
        )
        self.client_chunk = 0 if self._client_chunk_auto else int(raw_chunk or 0)
        # ---- selection-aware gather: O(selected) round compute ----
        # Under partial participation the dense round program trains every
        # one of the ``n_slots`` client slots and zero-masks the unselected
        # ones at aggregation — at 1000 clients / 100 selected ~90% of the
        # device FLOPs multiply into zero.  Host-side we compute the round's
        # selected worker ids (deterministic, ``utils/selection.py``), pad
        # them to a FIXED ``s_pad`` (static shapes — no retraces; divisible
        # by the slot axes so ``shard_map`` stays balanced), and the jitted
        # round program gathers the selected slots' data/val/weights/rngs
        # along the slot axis (``jnp.take`` + sharding constraint) BEFORE
        # entering ``shard_map``, so the client-chunk scan runs over
        # ``s_pad`` slots instead of ``n_slots``.  The full client stack
        # stays device-resident — selection is a device-side gather, no
        # per-round host restaging.  Trajectories are bit-identical to the
        # dense path: per-client rng streams are fold_in-indexed by WORKER
        # ID (the gather carries the ids), and unselected slots contributed
        # exact zeros.  ``algorithm_kwargs.selection_gather: false`` is the
        # escape hatch; FSDP and full participation fall back loudly.
        k = config.algorithm_kwargs.get("random_client_number")
        self._selected_per_round = min(
            int(k) if k is not None else config.worker_number,
            config.worker_number,
        )
        selection_active = k is not None and int(k) < config.worker_number
        sg_requested = config.algorithm_kwargs.get("selection_gather")
        self._selection_gather = bool(
            selection_active
            and self._selection_gather_unsupported_reason() is None
            and sg_requested is not False
        )
        if sg_requested and not self._selection_gather:
            if not selection_active:
                reason = (
                    "full participation (no random_client_number below"
                    " worker_number) — nothing to skip"
                )
            else:
                reason = self._selection_gather_unsupported_reason()
            get_logger().warning(
                "selection_gather requested but unsupported: %s — falling"
                " back to the dense O(population) round path",
                reason,
            )
        # ---- streamed populations (util/population.py) ----
        # ``algorithm_kwargs.population_store: streamed`` keeps the full
        # population's stacked client state HOST-resident and places only
        # the round's selected ``[S_pad]`` cohort (the horizon's union of
        # ``[H, S_pad]`` ids under fusion, fetched once per chunk) —
        # double-buffered, so round r+1's transfer hides under round r's
        # dispatched program.  The cohort-shaped programs are the SAME
        # shape-polymorphic dense programs traced at ``s_pad``, and the
        # per-client rng streams are fold_in-indexed by WORKER ID — the
        # same two facts that made selection gather bit-exact make the
        # streamed path bit-exact (pinned, tests/test_population_store).
        # Round MEMORY now scales with participants the way gather made
        # round COMPUTE scale: HBM watermarks stay flat as the population
        # grows (bench ``population_scaling``).
        store_mode = (
            str(
                config.algorithm_kwargs.get("population_store", "device")
                or "device"
            )
            .strip()
            .lower()
        )
        if store_mode not in ("device", "streamed"):
            raise ValueError(
                "algorithm_kwargs.population_store must be 'device' or"
                f" 'streamed', got {store_mode!r}"
            )
        self._population_streamed = store_mode == "streamed"
        if self._population_streamed:
            streamed_reason = self._population_store_unsupported_reason()
            if streamed_reason is not None:
                raise ValueError(
                    "algorithm_kwargs.population_store=streamed is"
                    f" unsupported here: {streamed_reason} — drop the"
                    " knob for this session"
                )
            # the device-gather twin reads slot stacks that are no longer
            # resident; under streaming the placed cohort IS the
            # selection, so the dense-shaped program runs at s_pad
            self._selection_gather = False
        self.s_pad = (
            client_slots(self._selected_per_round, self.mesh, slot_axes)
            if (self._selection_gather or self._population_streamed)
            else self.n_slots
        )
        if self._client_chunk_auto:
            # cache hit -> the calibrated winner, indistinguishable from
            # the same constant set by hand; miss -> 0 (the hand-set
            # default heuristic in ``chunk_size``) after a loud warning
            from ..util.calibration import resolve_client_chunk

            self.client_chunk = resolve_client_chunk(
                self,
                path=config.algorithm_kwargs.get("calibration_path"),
            )
        # ---- fault tolerance (util/faults.py) ----
        # The availability mask rides the SAME host-built weight rows
        # selection does (a dropped client's weight is zeroed, a corrupt
        # one's is NaN'd, in _select_weights/_select_indices) — the jitted
        # round programs are untouched, so an empty fault_tolerance config
        # is bit-exact and zero-overhead, and the mask composes with the
        # gather ([S_pad] rows) and fused-horizon ([H, S_pad] matrices)
        # machinery for free.  The update guard IS a program change
        # (per-client delta hygiene + survivor-renormalized total weight),
        # gated at trace time by ``self._update_guard``.
        from ..util.faults import FaultPlan

        self._fault_plan = FaultPlan.from_config(config)
        self._min_quorum = int(
            config.algorithm_kwargs.get("min_client_quorum", 0) or 0
        )
        self._update_guard = bool(
            self._fault_plan is not None and self._fault_plan.update_guard
        )
        self._max_update_norm = (
            self._fault_plan.max_update_norm if self._fault_plan else 0.0
        )
        if self._update_guard:
            guard_reason = self._update_guard_unsupported_reason()
            if guard_reason is not None:
                raise ValueError(
                    "fault_tolerance.update_guard is unsupported here: "
                    f"{guard_reason} — drop the knob for this session"
                )
        #: earliest FaultPlan kill round reached but not yet fired —
        #: kills only fire once the killed round is durably resumable
        self._kill_armed_round: int | None = None
        # ---- buffered-asynchronous aggregation (util/buffered.py) ----
        # ``aggregation_mode: buffered`` replays the deterministic arrival
        # schedule the threaded executor's buffer flushes follow: each
        # round trains the SAME cohort it does today, but a straggling
        # client's contribution is routed into a pending ring that merges
        # at its landing flush with the staleness discount folded into the
        # host-built weight rows (the PR 7 trick — no per-round host
        # syncs, ≤ 1 dispatch/round, fuses with gather and round-horizon).
        # With no stragglers and no buffer overflow the schedule is
        # depth-0 and the session traces the UNCHANGED synchronous
        # programs — bit-exact (pinned).
        from ..util.buffered import BufferedSettings

        self._buffered = BufferedSettings.from_config(config)
        self._arrival_schedule = None
        self._buffered_depth = 0
        if self._buffered is not None:
            buffered_reason = self._buffered_unsupported_reason()
            if buffered_reason is not None:
                raise ValueError(
                    "algorithm_kwargs.aggregation_mode=buffered is"
                    f" unsupported here: {buffered_reason} — drop the knob"
                    " for this session"
                )
            from ..util.buffered import (
                compute_arrival_schedule,
                selection_uploaders,
            )

            self._arrival_schedule = compute_arrival_schedule(
                self._buffered,
                self._fault_plan,
                config.worker_number,
                config.round,
                selection_uploaders(config),
            )
            self._buffered_depth = self._arrival_schedule.max_staleness
        #: whether the buffered round programs are actually traced — a
        #: depth-0 schedule (no stragglers, no overflow) degenerates to
        #: the synchronous programs, bit-exactly
        self._buffered_active = self._buffered_depth > 0
        #: device pending ring (buffered): (f32 sums tree with a leading
        #: [depth] dim, [depth] weight totals) — the updates trained but
        #: not yet landed, carried donated round over round
        self._pending = None
        self._round_delays = None  # device [S] delay row for the dispatch
        self._horizon_delay_rows = None  # device [H, S] rows under fusion
        self._buffered_program_fn = None
        self._buffered_gather_program_fn = None
        #: origins below this are pre-resume phantoms: their pending
        #: contributions died with the killed process, so cohort
        #: accounting and the flush quorum must not count them ("resume
        #: drains the buffer" — the threaded server keeps the same floor)
        self._buffered_origin_floor = 1
        # round-horizon fusion (``algorithm_kwargs.round_horizon``): fuse H
        # consecutive rounds into ONE jitted, donated ``lax.scan`` over
        # rounds, with per-round test evaluation in-program — the host
        # touches the device once per horizon instead of 3-4 times per
        # round (selection weights are host-precomputed per horizon; the
        # rng chain advances inside the program, bit-identical to the
        # host-side H=1 chain).
        self.round_horizon = max(
            1, int(config.algorithm_kwargs.get("round_horizon", 1) or 1)
        )
        # checkpoint cadence: round_N.npz every N rounds (the final round
        # always).  ``config.checkpoint_every`` 0 = auto: every round at
        # H=1 (the legacy cadence), every horizon boundary under fusion.
        self._checkpoint_every = max(
            1,
            int(getattr(config, "checkpoint_every", 0) or 0)
            or self.round_horizon,
        )
        self._last_ckpt_round = 0
        # round_record.json flush cadence (atomic tmp+rename writes; the
        # record used to be fully rewritten via a non-atomic open EVERY
        # round — O(rounds²) I/O on long runs).  Default: per round at
        # H=1, per horizon under fusion; always flushed at run exit
        # through the checkpoint writer's finalizer hook.
        self._record_flush_every = max(
            1,
            int(config.algorithm_kwargs.get("record_flush_every", 0) or 0)
            or self.round_horizon,
        )
        self._record_path: str | None = None
        self._record_dirty = False
        self._stat: dict[int, dict] = {}
        self._max_acc = 0.0
        #: accuracy high-water mark over PROMOTABLE (checkpointed) rounds
        #: — kept separate from ``_max_acc`` so a better mid-horizon (or
        #: un-checkpointed) round cannot permanently starve the
        #: best_global_model.npz promotion of later boundary rounds
        self._best_ckpt_acc = 0.0
        self._eval_batches = None  # device-resident, built on first eval
        # roundtrace telemetry (util/telemetry.py): the recorder's integer
        # counters back the legacy dispatch_count/host_sync_count/
        # rounds_run attributes (bench.py dispatch budgets); with
        # config.telemetry.enabled it additionally streams span/event
        # records to <save_dir>/server/trace.jsonl — zero new dispatches,
        # zero new host syncs, bit-exact trajectories either way
        from ..util.telemetry import TraceRecorder

        self._trace = TraceRecorder.from_config(config)
        from ..util.checkpoint import AsyncCheckpointWriter

        self._ckpt = AsyncCheckpointWriter()
        self._ckpt.register_finalizer("round_record", self._flush_record)
        # the trace tail flushes through the same exit-finalizer hook the
        # record flusher rides (error path included)
        self._ckpt.register_finalizer("roundtrace", self._trace.close)
        self._ckpt_queued_round: int | None = None

        # amp residency (algorithm_kwargs.amp_resident, default on under
        # use_amp): the round programs cast the f32 master to the compute
        # dtype ONCE per round and carry bf16 params/activations/deltas
        # through the client scan, applying the f32 master update once in
        # the aggregation epilogue.  `amp_resident: false` preserves the
        # legacy per-kernel-cast path bit-exactly (parity pins + fallback).
        self._amp_resident = (
            self.engine.model_ctx.compute_dtype != jnp.float32
            and bool(config.algorithm_kwargs.get("amp_resident", True))
        )

        self._data, self._dataset_sizes, self.n_batches = stack_client_data(
            config, dataset_collection, practitioners, self.n_slots
        )
        # residency satellite: batch INPUT leaves stored in the compute
        # dtype once at placement — the per-step _cast_for_compute in the
        # loss path then sees already-cast leaves (astype is the identity,
        # so this is bit-identical to casting at use)
        self._data = self._hoist_batch_cast(self._data)

        # ---- shardings ----
        if self._fsdp:
            self._slot_spec = P(("clients", "model"))
        elif "clients" in self.mesh.shape:
            self._slot_spec = P("clients")
        else:
            self._slot_spec = P()  # clients-as-scan meshes: slots replicated
        self._client_sharding = NamedSharding(self.mesh, self._slot_spec)
        self._replicated = NamedSharding(self.mesh, P())
        template = jax.eval_shape(
            lambda: self.engine.init_params(config.seed)
        )
        self._param_specs = {
            k: self._leaf_spec(v.shape, k) for k, v in template.items()
        }
        self._param_shardings = {
            k: NamedSharding(self.mesh, spec)
            for k, spec in self._param_specs.items()
        }

        # streamed-population state (populated below when active)
        self._population = None
        self._population_val = None
        self._cohort_data = None
        self._cohort_val = None
        self._cohort_prefetch = None
        self._horizon_pos_rows = None

        if self._population_streamed:
            # the stacked client data stays HOST-resident (post-hoist, so
            # fetched cohort rows are placement-ready); only the selected
            # cohort is ever placed, via the double-buffered prefetcher
            from ..util.population import CohortPrefetcher, PopulationStore

            self._population = PopulationStore.from_stacked(self._data)
            self._cohort_prefetch = CohortPrefetcher(self._fetch_cohort)
            self._ckpt.register_finalizer(
                "cohort_prefetch", self._cohort_prefetch.close
            )
        else:
            self._data = put_sharded(
                self._data, NamedSharding(self.mesh, self._slot_spec)
            )

        # iid upload policy (reference ``enable_choose_model_by_validation``,
        # ``aggregation_worker.py:33-44``): clients upload their round's
        # best epoch by validation accuracy — the SPMD program needs the
        # per-client validation batches in-program for that.  Skipped when
        # a single epoch makes best == final (the in-round val eval is a
        # full extra forward per client), and for subclasses whose round
        # programs do not consume it (OBD/Shapley).
        self._val_data = None
        if (
            self._uses_val_policy
            and config.dataset_sampling == "iid"
            and config.epoch > 1
        ):
            val = stack_client_val_data(
                config, dataset_collection, practitioners, self.n_slots
            )
            if val is not None:
                val = self._hoist_batch_cast(val)
                if self._population_streamed:
                    # host-resident like the train stacks; the dispatch
                    # routes the placed cohort's val rows instead of
                    # ``self._val_data`` (left None so nothing full-size
                    # ever reaches a program)
                    from ..util.population import PopulationStore

                    self._population_val = PopulationStore.from_stacked(val)
                else:
                    self._val_data = put_sharded(
                        val, NamedSharding(self.mesh, self._slot_spec)
                    )

        # per-client rng fold chain, device-resident end to end: the old
        # path materialized the folded keys on host (``np.asarray`` of the
        # vmapped fold_in) before re-uploading them — a device→host→device
        # bounce on the round critical path.  The stream is bit-identical
        # (same fold_in chain, just never fetched).
        slot_indices = jnp.arange(self.n_slots)
        self._fold_rngs = jax.jit(
            lambda round_rng: jax.vmap(
                lambda i: jax.random.fold_in(round_rng, i)
            )(slot_indices),
            out_shardings=self._client_sharding,
        )
        # gather-path twin: fold the SAME per-worker streams, but only for
        # the round's selected ids — ``fold_in`` is indexed by worker id
        # alone, so gathering the folded keys by id keeps the stream
        # bit-identical to the dense path's
        self._fold_sel_rngs = jax.jit(
            lambda round_rng, sel_idx: jax.vmap(
                lambda i: jax.random.fold_in(round_rng, i)
            )(sel_idx),
            out_shardings=self._client_sharding,
        )
        # horizon-fused weight rows: [H, n_slots] with rounds replicated
        # and slots sharded like every other slot-stacked input
        self._horizon_weight_sharding = NamedSharding(
            self.mesh, P(None, *self._slot_spec)
        )
        #: un-jitted round program (global_params, weights, rngs, data,
        #: val) -> (new_global, metrics) — set by the base
        #: ``_build_round_fn`` so the horizon builder can scan it.
        #: Subclasses with their own round functions leave it None and
        #: cannot fuse rounds.
        self._round_program_fn = None
        #: gather-path twins (selection-aware sessions only)
        self._gather_program_fn = None
        self._jitted_gather_round_fn = None
        self._horizon_fns: dict[int, object] = {}
        #: out_shardings pin handed to ``_wrap_round_programs`` (None =
        #: compiler-chosen) — recorded so shardcheck can certify the
        #: donated round-over-round layouts pre-dispatch
        self._round_out_shardings = None
        self._round_fn = self._build_round_fn()
        if self.round_horizon > 1 and not self._horizon_capable():
            raise ValueError(
                self._horizon_unsupported_reason()
                or (
                    "round_horizon > 1 requires a fusable round program;"
                    f" {type(self).__name__} builds its own round"
                    " function — run it with round_horizon=1"
                )
            )

    # ---------------------------------------------------- capability gates
    # The fused-round knobs (round_horizon / selection_gather /
    # fault_tolerance.update_guard) are gated per session CLASS.  The
    # class-level halves below are the single source of truth shared by
    # the runtime gates AND the conf↔capability validator
    # (``tools/shardcheck``): a misconfigured YAML fails at lint time
    # with the exact reason the session would raise at round 1.

    @classmethod
    def _bespoke_round_program_reason(cls) -> str | None:
        """Class-level core of every fused-knob gate: sessions that build
        their own round programs without registering them through
        :meth:`_wrap_round_programs` cannot fuse, gather, or guard.
        Whole-mesh-per-client subclasses declare support via
        ``_whole_mesh_fused``; sessions that extend the machinery to
        their own round programs (FedOBD) override this."""
        if cls is not SpmdFedAvgSession and not cls._whole_mesh_fused:
            return f"{cls.__name__} builds its own round program"
        return None

    @classmethod
    def _horizon_unsupported_reason(cls) -> str | None:
        """Why ``round_horizon > 1`` cannot fuse this CLASS's rounds
        (None = fusable) — the message ``__init__`` raises and the conf
        validator reports."""
        if cls is not SpmdFedAvgSession and not cls._whole_mesh_fused:
            return (
                "round_horizon > 1 requires a fusable round program;"
                f" {cls.__name__} builds its own round function —"
                " run it with round_horizon=1"
            )
        return None

    @classmethod
    def _class_update_guard_reason(cls) -> str | None:
        """Class-level update-guard gate (every fusable layout supports
        the guard since the pipeline session grew its cross-stage verdict
        reduction)."""
        return cls._bespoke_round_program_reason()

    @classmethod
    def _class_buffered_reason(cls) -> str | None:
        """Class-level ``aggregation_mode: buffered`` gate: the buffered
        replay (pending-ring round programs) is implemented on the
        client-axis FedAvg family (fed_avg / fed_paq); every other
        session still runs round-barriered and must reject the knob
        loudly instead of silently dropping it."""
        if cls is not SpmdFedAvgSession:
            return (
                "buffered aggregation (aggregation_mode: buffered) is"
                " implemented on the client-axis FedAvg family;"
                f" {cls.__name__} still runs round-barriered"
            )
        return None

    @classmethod
    def _class_population_store_reason(cls) -> str | None:
        """Class-level ``population_store: streamed`` gate: the streamed
        cohort path needs a round program that is shape-polymorphic in
        the slot axis and takes its client stacks as explicit arguments —
        the client-axis FedAvg family's program shape.  Whole-mesh
        layouts (ep/sp/pp) scan clients inside ONE program with the
        stacks closed over, so they defer to a follow-up and must reject
        the knob loudly instead of silently keeping state resident."""
        if cls is not SpmdFedAvgSession:
            return (
                "the streamed population store (population_store:"
                " streamed) is implemented on the client-axis FedAvg"
                f" family; {cls.__name__} keeps its per-client state"
                " device-resident"
            )
        return None

    @classmethod
    def capability_gates(cls) -> dict[str, str | None]:
        """The session class's static capability surface: fused-round
        knob -> rejection reason (None = supported at the class level;
        instance state such as FSDP can still fall back at runtime with
        a logged warning).  Consumed by ``tools/shardcheck``'s
        conf↔capability cross-validation."""
        return {
            "round_horizon": cls._horizon_unsupported_reason(),
            "selection_gather": cls._bespoke_round_program_reason(),
            "update_guard": cls._class_update_guard_reason(),
            "aggregation_mode": cls._class_buffered_reason(),
            "population_store": cls._class_population_store_reason(),
        }

    def _selection_gather_unsupported_reason(self) -> str | None:
        """Why this session cannot run the selection-aware gather (None =
        supported): the class-level gate plus instance-state fallbacks
        (FSDP stores params in the dense slot layout)."""
        reason = self._bespoke_round_program_reason()
        if reason is not None:
            return reason
        if self._fsdp:
            return (
                "FSDP model sharding stores params in the dense slot"
                " layout (all-gather/reduce_scatter are population-"
                "shaped)"
            )
        return None

    def _horizon_capable(self) -> bool:
        """Whether ``round_horizon > 1`` can fuse this session's rounds.
        The base rule: the un-jitted FedAvg round program must exist for
        the horizon builder to scan.  Sessions with their own fused run
        loops (FedOBD) override this."""
        return self._round_program_fn is not None

    def _update_guard_unsupported_reason(self) -> str | None:
        """Why this session cannot compile the device-side update guard
        into its round program (None = supported) — delegates to the
        class-level gate shared with the conf validator."""
        return self._class_update_guard_reason()

    def _population_store_unsupported_reason(self) -> str | None:
        """Why this session cannot stream its population (None =
        supported): the class-level gate plus instance-state fallbacks
        (FSDP partitions slots over BOTH mesh axes and all-gathers
        population-shaped params — its slot layout is dense by
        construction)."""
        reason = self._class_population_store_reason()
        if reason is not None:
            return reason
        if self._fsdp:
            return (
                "FSDP model sharding stores params in the dense slot"
                " layout (all-gather/reduce_scatter are population-"
                "shaped) — run streamed populations with"
                " model_sharding: none"
            )
        return None

    def _buffered_unsupported_reason(self) -> str | None:
        """Why this session cannot run buffered-asynchronous aggregation
        (None = supported): the class-level gate plus instance-state
        fallbacks (FSDP's population-shaped all-gather/reduce_scatter
        layout has no replicated pending-ring home)."""
        reason = self._class_buffered_reason()
        if reason is not None:
            return reason
        if self._fsdp:
            return (
                "FSDP model sharding stores params in the dense slot"
                " layout; the buffered pending ring is replicated-only"
            )
        return None

    def _round_mesh_context(self):
        """Ambient-mesh context wrapping every program trace/dispatch —
        the expert-parallel layouts override with ``use_mesh`` so
        bare-``PartitionSpec`` constraints inside their models resolve
        (jax 0.4 compat: ``mesh.py::use_mesh``)."""
        return contextlib.nullcontext()

    def _maybe_kill(self, first_round: int, last_round: int | None = None) -> None:
        """Arm any FaultPlan-scheduled process kill in the (inclusive)
        round range, and fire the earliest armed kill only once the
        killed round is DURABLY resumable — its checkpoint written
        (``_last_ckpt_round``) and its record rows flushed.  The plan is
        stateless on the premise that a resumed run starts past the
        killed round and never re-trips the same kill; that only holds if
        the kill waits for a checkpoint ≥ its round, so sparse
        ``checkpoint_every`` / horizon cadences simply DEFER the kill to
        the next durable boundary (the final round always checkpoints and
        flushes, so an armed kill fires by run end).  Called inside the
        ``with self._ckpt:`` block — the raise drains the writer."""
        plan = self._fault_plan
        if plan is None:
            return
        last = first_round if last_round is None else last_round
        self._kill_armed_round = plan.arm_kill(
            first_round, last, self._kill_armed_round
        )
        plan.fire_armed_kill(
            self._kill_armed_round,
            self._last_ckpt_round,
            record_durable=not self._record_dirty,
        )

    def _post_guard_quorum(
        self, round_number: int, participating, rejected
    ) -> None:
        """The quorum semantics guard-active rounds document (migrating.md
        "Fault tolerance"): survivors = uploads that reached aggregation −
        guard-rejected, with a floor of 1.  A fully-rejected round already
        kept the OLD params in-program (``guarded_average``) — this
        surfaces it as a loud abort instead of a silent no-op round.  The
        counts arrive host-side with the round's one metric sync, so the
        check costs nothing extra."""
        if not self._update_guard:
            return
        if self._buffered_active:
            # buffered replay: this round's in-program rejects belong to
            # the flushes their contributions were SCHEDULED to land in
            # (a rejected straggler thins a later flush), so subtracting
            # them from this round's flush cohort would abort the wrong
            # round.  The explicit flush-cohort quorum is enforced
            # pre-dispatch by _buffered_flush_quorum (corrupt-aware), and
            # an all-rejected flush keeps the old params — a well-defined
            # no-op, not a degenerate aggregate.
            return
        survivors = int(participating) - int(rejected)
        quorum = max(self._min_quorum, 1)
        if survivors < quorum:
            from ..util.faults import QuorumLostError

            message = (
                f"round {round_number}: {survivors} surviving uploads after "
                f"update-guard rejections ({int(rejected)} rejected of "
                f"{int(participating)}) below min_client_quorum={quorum} — "
                "aborting loudly (the round kept the previous params)"
            )
            get_logger().error(message)
            raise QuorumLostError(message)

    def _buffered_round_extras(self, round_number: int) -> dict:
        """Per-flush stat columns + telemetry for the buffered replay —
        every value is host schedule state, zero device touches.  Emits
        one ``staleness`` event per late-merged update and a
        ``buffer_flush`` event per flush (the threaded executor's
        ``buffer_flush`` SPAN measures a real wall-clock window; the
        replay's flush IS the round, so an event carries the counts)."""
        schedule = self._arrival_schedule
        floor = self._buffered_origin_floor
        cohort = schedule.live_cohort(round_number, floor)
        stale = schedule.stale_count(round_number, floor)
        backlog = schedule.buffer_depth_after(round_number, floor)
        if self._trace.enabled:
            for item in cohort:
                if item.staleness:
                    self._trace.event(
                        "staleness",
                        round=round_number,
                        worker=item.worker,
                        origin=item.origin,
                        staleness=item.staleness,
                        discount=round(item.discount, 6),
                    )
            self._trace.event(
                "buffer_flush",
                round=round_number,
                cohort=len(cohort),
                stale_updates=stale,
                buffer_depth=backlog,
            )
        return {
            "flush_cohort": len(cohort),
            "stale_updates": stale,
            "buffer_depth": backlog,
        }

    def _leaf_spec(self, shape, name: str = "") -> P:
        """FSDP layout rule: shard a param leaf's leading dim over the
        ``model`` axis when it divides evenly, else keep it replicated."""
        if self._fsdp and shape and shape[0] % self._model_axis == 0:
            return P("model")
        return P()

    def _place_params(self, params):
        """Place host params onto the per-leaf (possibly model-sharded)
        layout — multi-host aware: every process passes the FULL global
        array and ``put_sharded`` slices out each host's addressable
        shards; a plain device_put cannot target shards on non-addressable
        devices.

        The trailing on-device copy is load-bearing: ``device_put`` of an
        aligned host numpy array (npz resume / warm start) ALIASES the
        python-owned buffer on the cpu backend, and these params are the
        round program's DONATED argument — XLA would reuse memory python
        still owns (heap corruption, NaN trajectories after resume).  The
        copy's outputs are XLA-allocated, so donation is safe."""
        placed = {
            k: put_sharded(v, self._param_shardings[k])
            for k, v in params.items()
        }
        return jax.tree.map(jnp.copy, placed)

    def _checkpointable(self, params):
        """A view of ``params`` safe to fetch on this host for the npz
        writer.  Single-process: any layout fetches fine.  On a multi-host
        pod, model-sharded leaves span non-addressable devices — reshard
        them to replicated (an all-gather) before handing to the writer."""
        if not self._fsdp or jax.process_count() == 1:
            return params
        return jax.device_put(params, self._replicated)

    # ------------------------------------------------------------------
    def _build_round_fn(self):
        engine = self.engine
        epochs = self.config.epoch
        quant_level = self.quantization_level
        guard_active = self._update_guard
        max_update_norm = self._max_update_norm
        compute_dtype = engine.model_ctx.compute_dtype
        # amp residency (algorithm_kwargs.amp_resident, default on under
        # use_amp): cast the f32 master to the compute dtype ONCE per
        # round and fold the [S_pad] weight row into a flat ParamVec
        # epilogue.  The FSDP layout keeps the per-leaf epilogue (its
        # psum_scatter needs per-leaf sums) but still gets the
        # once-per-round cast, applied to the LOCAL shard so the
        # all_gather moves bf16.
        resident = self._amp_resident
        resident_fold = resident and not self._fsdp

        def train_one(global_params, data, weight, rng, val=None):
            """One client slot: trained params (post-codec), effective
            weight, pre-reduction metrics.  ``global_params`` is whatever
            view the shard body hands over — the f32 master on the
            legacy path, the once-per-round bf16 cast under amp
            residency (the codec delta and the guard then both compare
            against the params the client actually started from)."""
            rng, quant_rng = jax.random.split(rng)
            params, summed = scan_local_epochs(
                engine, epochs, global_params, data, rng, val_data=val
            )
            if quant_level is not None:
                # fed_paq: the upload delta goes through the stochastic
                # codec before aggregation sees it
                leaves, treedef = jax.tree.flatten(params)
                g_leaves = jax.tree.leaves(global_params)
                keys = jax.random.split(quant_rng, len(leaves))
                leaves = [
                    g + qsgd_dequantized(p - g, k, quant_level)
                    for p, g, k in zip(leaves, g_leaves, keys)
                ]
                params = jax.tree.unflatten(treedef, leaves)
            if guard_active:
                # update hygiene (fault_tolerance.update_guard): the
                # shared guard rejects non-finite / norm-exploded deltas
                # and poisoned weights BEFORE the weighted reduction
                weight, summed = guard_client_update(
                    params, global_params, weight, summed, max_update_norm
                )
            return params, weight, summed

        def local_train(global_params, data, weight, rng, val=None):
            """One client slot's round contribution (the per-leaf
            weighted path: legacy, FSDP, and the buffered twin)."""
            params, weight, summed = train_one(
                global_params, data, weight, rng, val
            )
            # weighted contribution; unselected slots contribute zero
            contribution = jax.tree.map(
                lambda p: p.astype(jnp.float32) * weight, params
            )
            return contribution, summed

        def chunk_size(slots_local: int) -> int:
            """Clients trained concurrently per device.  vmapping every
            local slot at once materializes activations for all of them —
            100 time-multiplexed clients of a conv net OOM a single chip —
            so slots are scanned in chunks (the reference time-multiplexes
            workers onto devices the same way, ``algorithm_factory.py:38-58``)."""
            mb = self.client_chunk
            if mb <= 0:
                mb = 8 if jax.default_backend() == "tpu" else slots_local
            mb = max(1, min(mb, slots_local))
            while slots_local % mb:
                mb -= 1
            return mb

        def round_program(global_params, weights, rngs, data, val):
            """shard_map body: scan client chunks, vmap inside each, psum
            the reduction.  ``data`` is an explicit argument — closing over
            the stacked client arrays would bake them into the HLO as
            constants (hundreds of MB of program, slow/oversized compiles).
            ``val`` is the per-client validation stack for the iid
            best-of-round upload policy, or ``{}`` (no leaves) when off."""

            def shard_body(global_params, data, val, weights, rngs):
                params_in = global_params  # per-device (possibly sharded) view
                if self._fsdp:
                    if resident:
                        # cast the LOCAL shard first: the gather then
                        # moves bf16 — half the collective bytes
                        global_params = tree_cast(global_params, compute_dtype)
                    # materialize full params for local training; XLA frees
                    # the gathered copy after the last use
                    global_params = {
                        k: jax.lax.all_gather(v, "model", axis=0, tiled=True)
                        if self._param_specs[k] != P()
                        else v
                        for k, v in global_params.items()
                    }
                elif resident:
                    # THE residency cast: master→compute once per round
                    # (per horizon chunk under fusion) — every per-kernel
                    # _cast_for_compute inside the client scan below then
                    # sees already-bf16 leaves (astype is the identity),
                    # and the f32 master update happens once in the
                    # epilogue
                    global_params = tree_cast(global_params, compute_dtype)
                slots_local = weights.shape[0]
                mb = chunk_size(slots_local)

                if resident_fold:
                    # flat ParamVec epilogue: each chunk's [mb]-stacked
                    # trained params contract against the weight row as
                    # ONE [mb, D] f32 matvec (ops/pytree.py) instead of
                    # broadcasting weights across every param-shaped
                    # tensor — the 26.8 GiB broadcast + 17.1 GiB multiply
                    # families collapse to a [D] accumulator
                    layout = ParamVecLayout.of(params_in)

                    def run_slots_res(d, w, r, v):
                        return jax.vmap(
                            train_one, in_axes=(None, 0, 0, 0, 0)
                        )(global_params, d, w, r, v if v else None)

                    if mb == slots_local:
                        stack, eff_w, metrics = run_slots_res(
                            data, weights, rngs, val
                        )
                        local_vec = flat_stack_weighted_sum(stack, eff_w)
                        metrics = jax.tree.map(lambda m: jnp.sum(m), metrics)
                    else:
                        n_chunks = slots_local // mb

                        def to_chunks(tree):
                            return jax.tree.map(
                                lambda x: x.reshape(
                                    n_chunks, mb, *x.shape[1:]
                                ),
                                tree,
                            )

                        def chunk_body(acc, chunk):
                            data_k, v_k, w_k, r_k = chunk
                            stack, eff_w, met = run_slots_res(
                                data_k, w_k, r_k, v_k
                            )
                            acc_vec, acc_met = acc
                            acc_vec = acc_vec + flat_stack_weighted_sum(
                                stack, eff_w
                            )
                            acc_met = jax.tree.map(
                                lambda a, m: a + jnp.sum(m), acc_met, met
                            )
                            return (acc_vec, acc_met), None

                        chunks = (
                            to_chunks(data),
                            to_chunks(val),
                            to_chunks(weights),
                            to_chunks(rngs),
                        )
                        _, _, met_shapes = jax.eval_shape(
                            lambda d, v, w, r: run_slots_res(d, w, r, v),
                            *jax.tree.map(lambda x: x[0], chunks),
                        )
                        init = (
                            jnp.zeros((layout.size,), jnp.float32),
                            jax.tree.map(
                                lambda s: jnp.zeros((), s.dtype), met_shapes
                            ),
                        )
                        (local_vec, metrics), _ = jax.lax.scan(
                            chunk_body, init, chunks
                        )
                    global_vec = jax.lax.psum(local_vec, axis_name="clients")
                    # f32 sums split back through the static layout: the
                    # one divide + master write-back per round
                    global_sum = layout.split(global_vec, cast=False)
                    if guard_active:
                        metrics = dict(metrics)
                        total_weight = jax.lax.psum(
                            metrics.pop("_eff_weight"), axis_name="clients"
                        )
                        new_global = guarded_average(
                            global_sum, total_weight, params_in
                        )
                    else:
                        total_weight = jax.lax.psum(
                            jnp.sum(weights), axis_name="clients"
                        )
                        new_global = jax.tree.map(
                            lambda s, g: (
                                s / jnp.maximum(total_weight, 1e-12)
                            ).astype(g.dtype),
                            global_sum,
                            params_in,
                        )
                    metrics = jax.tree.map(
                        lambda m: jax.lax.psum(
                            jnp.sum(m), axis_name="clients"
                        ),
                        metrics,
                    )
                    return new_global, metrics

                def run_slots(d, w, r, v):
                    return jax.vmap(
                        local_train, in_axes=(None, 0, 0, 0, 0)
                    )(global_params, d, w, r, v if v else None)

                if mb == slots_local:
                    contributions, metrics = run_slots(
                        data, weights, rngs, val
                    )
                    local_sum = jax.tree.map(
                        lambda c: jnp.sum(c, axis=0), contributions
                    )
                    metrics = jax.tree.map(lambda m: jnp.sum(m), metrics)
                else:
                    n_chunks = slots_local // mb

                    def to_chunks(tree):
                        return jax.tree.map(
                            lambda x: x.reshape(n_chunks, mb, *x.shape[1:]), tree
                        )

                    def chunk_body(acc, chunk):
                        data_k, v_k, w_k, r_k = chunk
                        contrib, met = run_slots(data_k, w_k, r_k, v_k)
                        acc_sum, acc_met = acc
                        acc_sum = jax.tree.map(
                            lambda a, c: a + jnp.sum(c, axis=0), acc_sum, contrib
                        )
                        acc_met = jax.tree.map(
                            lambda a, m: a + jnp.sum(m), acc_met, met
                        )
                        return (acc_sum, acc_met), None

                    chunks = (
                        to_chunks(data),
                        to_chunks(val),
                        to_chunks(weights),
                        to_chunks(rngs),
                    )
                    # metric accumulator structure comes from the train fn
                    # itself (trace-time eval_shape), not hardcoded keys
                    _, met_shapes = jax.eval_shape(
                        lambda d, v, w, r: run_slots(d, w, r, v),
                        *jax.tree.map(lambda x: x[0], chunks),
                    )
                    init = (
                        jax.tree.map(
                            lambda p: jnp.zeros(p.shape, jnp.float32), global_params
                        ),
                        jax.tree.map(
                            lambda s: jnp.zeros((), s.dtype), met_shapes
                        ),
                    )
                    (local_sum, metrics), _ = jax.lax.scan(chunk_body, init, chunks)
                slot_axes = (
                    ("clients", "model") if self._fsdp else "clients"
                )

                def reduce_leaf(key, s):
                    if self._fsdp and self._param_specs[key] != P():
                        # sum over clients, then reduce_scatter over model:
                        # each device keeps only its param shard
                        s = jax.lax.psum(s, axis_name="clients")
                        return jax.lax.psum_scatter(
                            s, "model", scatter_dimension=0, tiled=True
                        )
                    return jax.lax.psum(s, axis_name=slot_axes)

                global_sum = {
                    k: reduce_leaf(k, s) for k, s in local_sum.items()
                }
                if guard_active:
                    # survivor renormalization: the total is the sum of the
                    # guard's EFFECTIVE weights (rejected slots at exactly
                    # zero), carried per-slot through the metrics tree; a
                    # zero-survivor round keeps the old params instead of
                    # zeroing the model
                    metrics = dict(metrics)
                    total_weight = jax.lax.psum(
                        metrics.pop("_eff_weight"), axis_name=slot_axes
                    )
                    new_global = guarded_average(
                        global_sum, total_weight, params_in
                    )
                else:
                    total_weight = jax.lax.psum(
                        jnp.sum(weights), axis_name=slot_axes
                    )
                    new_global = jax.tree.map(
                        lambda s, g: (
                            s / jnp.maximum(total_weight, 1e-12)
                        ).astype(g.dtype),
                        global_sum,
                        params_in,
                    )
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(jnp.sum(m), axis_name=slot_axes),
                    metrics,
                )
                return new_global, metrics

            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(
                    self._param_specs,
                    self._slot_spec,
                    self._slot_spec,
                    self._slot_spec,
                    self._slot_spec,
                ),
                out_specs=(self._param_specs, P()),
            )(global_params, data, val, weights, rngs)

        sync_fn = self._wrap_round_programs(round_program)
        if not self._buffered_active:
            return sync_fn

        # ---- buffered replay twin (aggregation_mode: buffered) ----
        # The SAME per-client training (local_train — quant codec and
        # update guard included), but each slot's weighted contribution is
        # ROUTED by its host-scheduled staleness instead of merging into
        # this round's average: bucket k collects the contributions
        # landing k flushes from now.  Bucket 0 + the pending ring's head
        # form this flush; buckets 1..D refill the ring.  The synchronous
        # program above is traced unchanged, so aggregation_mode off (or a
        # depth-0 schedule) stays bit-exact.
        depth = self._buffered_depth

        def buffered_one(global_params, data, weight, onehot, rng, val):
            contribution, summed = local_train(
                global_params, data, weight, rng, val
            )
            eff_weight = (
                summed["_eff_weight"] if guard_active else weight
            )
            route = onehot > 0  # [depth+1] — exactly one True
            # where(), not multiply: a NaN-poisoned contribution (corrupt
            # injection without the guard) must stay confined to ITS
            # bucket — 0 * NaN would leak it into every bucket
            bucket_contrib = jax.tree.map(
                lambda c: jnp.where(
                    route.reshape((depth + 1,) + (1,) * c.ndim),
                    c[None],
                    jnp.float32(0.0),
                ),
                contribution,
            )
            bucket_weight = jnp.where(route, eff_weight, jnp.float32(0.0))
            if guard_active:
                summed = dict(summed)
                summed.pop("_eff_weight")
            return bucket_contrib, bucket_weight, summed

        def buffered_shard_body(global_params, data, val, weights, delays, rngs):
            if resident:
                # same once-per-round residency cast as the synchronous
                # body; the pending-ring epilogue keeps its per-leaf f32
                # bucket layout (the ring is a round-spanning carry), so
                # only the training interior changes dtype
                global_params = tree_cast(global_params, compute_dtype)
            slots_local = weights.shape[0]
            mb = chunk_size(slots_local)
            onehot = jax.nn.one_hot(delays, depth + 1, dtype=jnp.float32)

            def run_slots(d, w, oh, r, v):
                return jax.vmap(
                    buffered_one, in_axes=(None, 0, 0, 0, 0, 0)
                )(global_params, d, w, oh, r, v if v else None)

            if mb == slots_local:
                contribs, wvecs, metrics = run_slots(
                    data, weights, onehot, rngs, val
                )
                bucket_sums = jax.tree.map(
                    lambda c: jnp.sum(c, axis=0), contribs
                )
                bucket_weights = jnp.sum(wvecs, axis=0)
                metrics = jax.tree.map(lambda m: jnp.sum(m), metrics)
            else:
                n_chunks = slots_local // mb

                def to_chunks(tree):
                    return jax.tree.map(
                        lambda x: x.reshape(n_chunks, mb, *x.shape[1:]),
                        tree,
                    )

                def chunk_body(acc, chunk):
                    data_k, v_k, w_k, oh_k, r_k = chunk
                    contrib, wvec, met = run_slots(
                        data_k, w_k, oh_k, r_k, v_k
                    )
                    acc_sum, acc_w, acc_met = acc
                    acc_sum = jax.tree.map(
                        lambda a, c: a + jnp.sum(c, axis=0), acc_sum, contrib
                    )
                    acc_w = acc_w + jnp.sum(wvec, axis=0)
                    acc_met = jax.tree.map(
                        lambda a, m: a + jnp.sum(m), acc_met, met
                    )
                    return (acc_sum, acc_w, acc_met), None

                chunks = (
                    to_chunks(data),
                    to_chunks(val),
                    to_chunks(weights),
                    to_chunks(onehot),
                    to_chunks(rngs),
                )
                _, _, met_shapes = jax.eval_shape(
                    lambda d, v, w, oh, r: run_slots(d, w, oh, r, v),
                    *jax.tree.map(lambda x: x[0], chunks),
                )
                init = (
                    jax.tree.map(
                        lambda p: jnp.zeros(
                            (depth + 1, *p.shape), jnp.float32
                        ),
                        global_params,
                    ),
                    jnp.zeros((depth + 1,), jnp.float32),
                    jax.tree.map(
                        lambda s: jnp.zeros((), s.dtype), met_shapes
                    ),
                )
                (bucket_sums, bucket_weights, metrics), _ = jax.lax.scan(
                    chunk_body, init, chunks
                )
            bucket_sums = jax.tree.map(
                lambda s: jax.lax.psum(s, axis_name="clients"), bucket_sums
            )
            bucket_weights = jax.lax.psum(bucket_weights, axis_name="clients")
            metrics = jax.tree.map(
                lambda m: jax.lax.psum(jnp.sum(m), axis_name="clients"),
                metrics,
            )
            return bucket_sums, bucket_weights, metrics

        replicated_out = {k: P() for k in self._param_specs}

        def buffered_round_program(
            global_params, pending, weights, delays, rngs, data, val
        ):
            bucket_sums, bucket_weights, metrics = shard_map_compat(
                buffered_shard_body,
                self.mesh,
                in_specs=(
                    self._param_specs,
                    self._slot_spec,
                    self._slot_spec,
                    self._slot_spec,
                    self._slot_spec,
                    self._slot_spec,
                ),
                out_specs=(replicated_out, P(), P()),
            )(global_params, data, val, weights, delays, rngs)
            pend_sums, pend_weights = pending
            flush_sum = jax.tree.map(
                lambda b, p: b[0] + p[0], bucket_sums, pend_sums
            )
            flush_weight = bucket_weights[0] + pend_weights[0]
            # an empty flush (every arrival stale) keeps the old global —
            # the buffered analogue of guarded_average's zero-survivor
            # rule.  Selected on `== 0` (not `> 0`) so a NaN-poisoned
            # flush weight (corrupt injection WITHOUT the guard) divides
            # through and poisons the aggregate VISIBLY, exactly like the
            # synchronous paths — never a silent keep-old swallow.
            new_global = jax.tree.map(
                lambda s, old: jnp.where(
                    flush_weight == 0,
                    old,
                    (s / jnp.maximum(flush_weight, 1e-12)).astype(
                        old.dtype
                    ),
                ),
                flush_sum,
                global_params,
            )
            # ring shift: tomorrow's head is bucket 1 + pending slot 1
            new_pend_sums = jax.tree.map(
                lambda b, p: b[1:]
                + jnp.concatenate([p[1:], jnp.zeros_like(p[:1])]),
                bucket_sums,
                pend_sums,
            )
            new_pend_weights = bucket_weights[1:] + jnp.concatenate(
                [pend_weights[1:], jnp.zeros_like(pend_weights[:1])]
            )
            return (
                new_global,
                (new_pend_sums, new_pend_weights),
            ), metrics

        return self._wrap_buffered_programs(buffered_round_program)

    def _wrap_round_programs(self, round_program, out_shardings=None):
        """The shared tail of every fusable ``_build_round_fn`` (the base
        client-axis session AND the whole-mesh ep/sp/pp subclasses):
        register the un-jitted ``(global_params, weights, rngs, data, val)``
        program for the horizon builder, jit the dense path, build + jit
        the gather twin when the selection gather is active, and return
        the dispatch fn.  ``out_shardings`` pins the jitted outputs to a
        stored layout (the expert-parallel session's donated
        round-over-round buffers must never reshard)."""
        # the horizon builder scans this same program — one trace, shared
        # numerics with the per-round path
        self._round_program_fn = round_program
        self._round_out_shardings = out_shardings
        jit_kwargs = (
            {"out_shardings": out_shardings} if out_shardings is not None else {}
        )
        # donate the old global params: the round returns the new ones, so
        # XLA can reuse the buffer instead of holding both copies live
        jitted = jax.jit(round_program, donate_argnums=(0,), **jit_kwargs)
        # bench introspection handle (compiled memory analysis — the
        # tunneled axon platform returns no runtime memory_stats)
        self._jitted_round_fn = jitted

        if self._selection_gather:
            session = self

            def gather_round_program(
                global_params, weights, rngs, sel_idx, data, val
            ):
                """The SAME round program over a gathered ``[s_pad]`` slot
                stack: a device-side ``jnp.take`` along the slot axis (the
                full ``[C, ...]`` client stack stays resident — no host
                restaging), then the identical round body over ``s_pad``
                slots instead of ``n_slots``.  Each gathered leaf is
                constrained back to ITS OWN stored sharding (trace-time
                read of the resident stacks): the client axis on
                client-axis meshes, the sequence axis on the sp layout —
                a whole-mesh session's take must not re-replicate
                sequence-sharded data."""

                def take(tree, stored):
                    shardings = jax.tree.map(lambda x: x.sharding, stored)
                    return jax.tree.map(
                        lambda x, s: jax.lax.with_sharding_constraint(
                            jnp.take(x, sel_idx, axis=0), s
                        ),
                        tree,
                        shardings,
                    )

                return round_program(
                    global_params,
                    weights,
                    rngs,
                    take(data, session._data),
                    take(val, session._val_data or {}),
                )

            self._gather_program_fn = gather_round_program
            self._jitted_gather_round_fn = jax.jit(
                gather_round_program, donate_argnums=(0,), **jit_kwargs
            )

        # the dispatch tail rides TraceRecorder.dispatch (roundtrace): a
        # `compile` event fires whenever the program's jit cache grew —
        # the dispatch-budget invariant (shardcheck's static
        # `dispatch-budget` rule) observed at runtime.  One int compare
        # per dispatch, enabled-gated, no device touch.
        def fn(global_params, weights, rngs, sel_idx=None):
            with self._round_mesh_context():
                if sel_idx is not None:
                    return self._trace.dispatch(
                        "round[gather]",
                        self._jitted_gather_round_fn,
                        (
                            global_params,
                            weights,
                            rngs,
                            sel_idx,
                            self._data,
                            self._val_data or {},
                        ),
                        sig_args=(weights, rngs, sel_idx),
                    )
                # streamed populations ride the SAME dense-shaped program
                # at cohort shape: the prefetcher placed the [s_pad] rows
                # and _prepare_round_inputs stored them on the session —
                # the program is shape-polymorphic in the slot axis, so
                # the jit cache sees ONE stable signature (zero retraces)
                if self._population_streamed:
                    data, val = self._cohort_data, self._cohort_val
                    label = "round[streamed]"
                else:
                    data, val = self._data, self._val_data
                    label = "round[dense]"
                return self._trace.dispatch(
                    label,
                    jitted,
                    (
                        global_params,
                        weights,
                        rngs,
                        data,
                        val or {},
                    ),
                    sig_args=(weights, rngs),
                )

        return fn

    def _wrap_buffered_programs(self, buffered_round_program):
        """The buffered twin of :meth:`_wrap_round_programs`: register the
        un-jitted ``(global_params, pending, weights, delays, rngs, data,
        val)`` program for the buffered horizon builder, jit it (params
        AND the pending ring donated, both pinned to their stored layouts
        so the round-over-round carries never reshard), build the gather
        twin, and return a dispatch fn with the SYNC dispatch signature —
        the run loop stays oblivious: the delay row and the pending ring
        ride session state set by ``_prepare_round_inputs``."""
        self._buffered_program_fn = buffered_round_program
        out_pin = ((self._param_shardings, self._replicated), None)
        jitted = jax.jit(
            buffered_round_program,
            donate_argnums=(0, 1),
            out_shardings=out_pin,
        )
        self._jitted_buffered_round_fn = jitted
        jitted_gather = None
        if self._selection_gather:
            session = self

            def buffered_gather_program(
                global_params, pending, weights, delays, rngs, sel_idx,
                data, val,
            ):
                """The buffered program over a gathered ``[s_pad]`` slot
                stack — same constrained device-side take as the sync
                gather twin."""

                def take(tree, stored):
                    shardings = jax.tree.map(lambda x: x.sharding, stored)
                    return jax.tree.map(
                        lambda x, s: jax.lax.with_sharding_constraint(
                            jnp.take(x, sel_idx, axis=0), s
                        ),
                        tree,
                        shardings,
                    )

                return buffered_round_program(
                    global_params,
                    pending,
                    weights,
                    delays,
                    rngs,
                    take(data, session._data),
                    take(val, session._val_data or {}),
                )

            self._buffered_gather_program_fn = buffered_gather_program
            jitted_gather = jax.jit(
                buffered_gather_program,
                donate_argnums=(0, 1),
                out_shardings=out_pin,
            )
            self._jitted_buffered_gather_fn = jitted_gather

        def fn(global_params, weights, rngs, sel_idx=None):
            pending = self._ensure_pending()
            delays = self._round_delays
            with self._round_mesh_context():
                if sel_idx is not None:
                    (new_global, self._pending), metrics = (
                        self._trace.dispatch(
                            "round[buffered-gather]",
                            jitted_gather,
                            (
                                global_params,
                                pending,
                                weights,
                                delays,
                                rngs,
                                sel_idx,
                                self._data,
                                self._val_data or {},
                            ),
                            sig_args=(weights, delays, rngs, sel_idx),
                        )
                    )
                else:
                    if self._population_streamed:
                        data, val = self._cohort_data, self._cohort_val
                        label = "round[buffered-streamed]"
                    else:
                        data, val = self._data, self._val_data
                        label = "round[buffered]"
                    (new_global, self._pending), metrics = (
                        self._trace.dispatch(
                            label,
                            jitted,
                            (
                                global_params,
                                pending,
                                weights,
                                delays,
                                rngs,
                                data,
                                val or {},
                            ),
                            sig_args=(weights, delays, rngs),
                        )
                    )
            return new_global, metrics

        return fn

    def _ensure_pending(self) -> tuple:
        """The device pending ring, zero-initialized on first use (and
        after a resume: in-flight updates at a kill are DROPPED, like a
        real buffered deployment restart — docs/migrating.md "Buffered
        aggregation").  The trailing copy keeps the donated buffers
        XLA-owned (the _place_params rule)."""
        if self._pending is None:
            depth = self._buffered_depth
            template = jax.eval_shape(
                lambda: self.engine.init_params(self.config.seed)
            )
            sums = {
                k: jnp.copy(
                    jax.device_put(
                        jnp.zeros((depth, *v.shape), jnp.float32),
                        self._replicated,
                    )
                )
                for k, v in template.items()
            }
            weights = jnp.copy(
                jax.device_put(
                    jnp.zeros((depth,), jnp.float32), self._replicated
                )
            )
            self._pending = (sums, weights)
        return self._pending

    # ------------------------------------------------------------------
    def _build_horizon_fn(self, horizon: int):
        """``horizon`` consecutive rounds as ONE jitted, donated
        ``lax.scan``: the carry is (global_params, rng chain), each step
        splits the chain exactly like the host loop (so H=1 and H=8
        trajectories are bit-identical), folds the per-slot client rngs
        in-program, runs the SAME round program the per-round path jits,
        and evaluates the fresh global on the device-resident test batches
        — stacked ``[H, ...]`` metrics come back in one host fetch."""
        if self._population_streamed:
            return self._build_streamed_horizon_fn(horizon)
        if self._buffered_active:
            return self._build_buffered_horizon_fn(horizon)
        engine = self.engine
        n_slots = self.n_slots
        round_program = self._round_program_fn
        gather_program = self._gather_program_fn
        use_gather = self._selection_gather
        with_confusion = bool(self.config.use_slow_performance_metrics)

        def horizon_program(
            global_params, rng, weight_rows, idx_rows, data, val, eval_batches
        ):
            def body(carry, xs):
                params, rng = carry
                rng, round_rng = jax.random.split(rng)
                if use_gather:
                    # selection-aware: the scanned ``[s_pad]`` id row folds
                    # the SAME per-worker streams the dense path would, and
                    # the gather program trains only the selected slots
                    weights, sel_idx = xs
                    client_rngs = jax.vmap(
                        lambda i: jax.random.fold_in(round_rng, i)
                    )(sel_idx)
                    params, train_metrics = gather_program(
                        params, weights, client_rngs, sel_idx, data, val
                    )
                else:
                    weights = xs
                    client_rngs = jax.vmap(
                        lambda i: jax.random.fold_in(round_rng, i)
                    )(jnp.arange(n_slots))
                    params, train_metrics = round_program(
                        params, weights, client_rngs, data, val
                    )
                eval_summed = engine.eval_fn(params, eval_batches)
                outs = (train_metrics, eval_summed)
                if with_confusion:
                    outs = outs + (engine.confusion_fn(params, eval_batches),)
                return (params, rng), outs

            xs = (weight_rows, idx_rows) if use_gather else weight_rows
            (global_params, rng), outs = jax.lax.scan(
                body, (global_params, rng), xs, length=horizon
            )
            return (global_params, rng), outs

        # the params carry keeps the stored per-leaf layout (replicated,
        # FSDP-sharded, or the ep expert layout) so the donated
        # round-over-round buffers never reshard — without the pin a
        # GSPMD session's second chunk could see differently-laid-out
        # inputs and retrace
        jitted = jax.jit(
            horizon_program,
            donate_argnums=(0, 1),
            out_shardings=((self._param_shardings, None), None),
        )

        def fn(global_params, rng, weight_rows, idx_rows=None):
            with self._round_mesh_context():
                return self._trace.dispatch(
                    f"horizon[h={horizon}]",
                    jitted,
                    (
                        global_params,
                        rng,
                        weight_rows,
                        idx_rows,
                        self._data,
                        self._val_data or {},
                        self._ensure_eval_batches(),
                    ),
                    sig_args=(weight_rows, idx_rows),
                )

        fn._jitted = jitted
        return fn

    def _build_buffered_horizon_fn(self, horizon: int):
        """The buffered twin of :meth:`_build_horizon_fn`: the scan carry
        additionally threads the pending ring, so a straggler's
        contribution trained in chunk ``i`` can land in chunk ``i`` or
        ``i+1`` — the ring crosses horizon boundaries through the donated
        carry exactly like the params do.  Scanned inputs gain the
        ``[H, S]`` staleness-delay rows next to the weight rows; still one
        dispatch and one stacked-metrics sync per horizon."""
        engine = self.engine
        n_slots = self.n_slots
        buffered_program = self._buffered_program_fn
        gather_program = self._buffered_gather_program_fn
        use_gather = self._selection_gather
        with_confusion = bool(self.config.use_slow_performance_metrics)

        def horizon_program(
            global_params,
            pending,
            rng,
            weight_rows,
            delay_rows,
            idx_rows,
            data,
            val,
            eval_batches,
        ):
            def body(carry, xs):
                params, pending, rng = carry
                rng, round_rng = jax.random.split(rng)
                if use_gather:
                    weights, delays, sel_idx = xs
                    client_rngs = jax.vmap(
                        lambda i: jax.random.fold_in(round_rng, i)
                    )(sel_idx)
                    (params, pending), train_metrics = gather_program(
                        params, pending, weights, delays, client_rngs,
                        sel_idx, data, val,
                    )
                else:
                    weights, delays = xs
                    client_rngs = jax.vmap(
                        lambda i: jax.random.fold_in(round_rng, i)
                    )(jnp.arange(n_slots))
                    (params, pending), train_metrics = buffered_program(
                        params, pending, weights, delays, client_rngs,
                        data, val,
                    )
                eval_summed = engine.eval_fn(params, eval_batches)
                outs = (train_metrics, eval_summed)
                if with_confusion:
                    outs = outs + (engine.confusion_fn(params, eval_batches),)
                return (params, pending, rng), outs

            xs = (
                (weight_rows, delay_rows, idx_rows)
                if use_gather
                else (weight_rows, delay_rows)
            )
            (global_params, pending, rng), outs = jax.lax.scan(
                body, (global_params, pending, rng), xs, length=horizon
            )
            return (global_params, pending, rng), outs

        jitted = jax.jit(
            horizon_program,
            donate_argnums=(0, 1, 2),
            out_shardings=(
                (self._param_shardings, self._replicated, None),
                None,
            ),
        )

        def fn(global_params, rng, weight_rows, idx_rows=None):
            pending = self._ensure_pending()
            delay_rows = self._horizon_delay_rows
            with self._round_mesh_context():
                (global_params, pending, rng), outs = self._trace.dispatch(
                    f"horizon[buffered,h={horizon}]",
                    jitted,
                    (
                        global_params,
                        pending,
                        rng,
                        weight_rows,
                        delay_rows,
                        idx_rows,
                        self._data,
                        self._val_data or {},
                        self._ensure_eval_batches(),
                    ),
                    sig_args=(weight_rows, delay_rows, idx_rows),
                )
            self._pending = pending
            return (global_params, rng), outs

        fn._jitted = jitted
        return fn

    def _build_streamed_horizon_fn(self, horizon: int):
        """The streamed-population twin of :meth:`_build_horizon_fn`: the
        chunk's placed stack is the UNION of the horizon's ``[H, S_pad]``
        selected ids (fetched once per chunk — the cohort-union rule),
        and each scanned round takes its own rows by POSITION in that
        union while folding per-client rngs by WORKER ID — positions
        address the placed stack, ids pin the rng streams, so the
        trajectory stays bit-identical to the resident path.  The union
        is padded to the static ``H * S_pad`` so every chunk of the same
        length shares one program shape (zero retraces).  Handles the
        buffered pending-ring carry inline (same composition rule as the
        resident builders)."""
        engine = self.engine
        round_program = self._round_program_fn
        buffered_program = self._buffered_program_fn
        buffered = self._buffered_active
        with_confusion = bool(self.config.use_slow_performance_metrics)
        cohort_sharding = NamedSharding(self.mesh, self._slot_spec)

        def take(tree, pos):
            return jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    jnp.take(x, pos, axis=0), cohort_sharding
                ),
                tree,
            )

        if buffered:

            def horizon_program(
                global_params,
                pending,
                rng,
                weight_rows,
                pos_rows,
                id_rows,
                delay_rows,
                data,
                val,
                eval_batches,
            ):
                def body(carry, xs):
                    params, pending, rng = carry
                    weights, pos, ids, delays = xs
                    rng, round_rng = jax.random.split(rng)
                    client_rngs = jax.vmap(
                        lambda i: jax.random.fold_in(round_rng, i)
                    )(ids)
                    (params, pending), train_metrics = buffered_program(
                        params, pending, weights, delays, client_rngs,
                        take(data, pos), take(val, pos) if val else {},
                    )
                    eval_summed = engine.eval_fn(params, eval_batches)
                    outs = (train_metrics, eval_summed)
                    if with_confusion:
                        outs = outs + (
                            engine.confusion_fn(params, eval_batches),
                        )
                    return (params, pending, rng), outs

                (global_params, pending, rng), outs = jax.lax.scan(
                    body,
                    (global_params, pending, rng),
                    (weight_rows, pos_rows, id_rows, delay_rows),
                    length=horizon,
                )
                return (global_params, pending, rng), outs

            jitted = jax.jit(
                horizon_program,
                donate_argnums=(0, 1, 2),
                out_shardings=(
                    (self._param_shardings, self._replicated, None),
                    None,
                ),
            )

            def fn(global_params, rng, weight_rows, idx_rows=None):
                pending = self._ensure_pending()
                delay_rows = self._horizon_delay_rows
                pos_rows = self._horizon_pos_rows
                with self._round_mesh_context():
                    (global_params, pending, rng), outs = (
                        self._trace.dispatch(
                            f"horizon[buffered-streamed,h={horizon}]",
                            jitted,
                            (
                                global_params,
                                pending,
                                rng,
                                weight_rows,
                                pos_rows,
                                idx_rows,
                                delay_rows,
                                self._cohort_data,
                                self._cohort_val or {},
                                self._ensure_eval_batches(),
                            ),
                            sig_args=(
                                weight_rows, pos_rows, idx_rows, delay_rows
                            ),
                        )
                    )
                self._pending = pending
                return (global_params, rng), outs

            fn._jitted = jitted
            return fn

        def horizon_program(
            global_params,
            rng,
            weight_rows,
            pos_rows,
            id_rows,
            data,
            val,
            eval_batches,
        ):
            def body(carry, xs):
                params, rng = carry
                weights, pos, ids = xs
                rng, round_rng = jax.random.split(rng)
                client_rngs = jax.vmap(
                    lambda i: jax.random.fold_in(round_rng, i)
                )(ids)
                params, train_metrics = round_program(
                    params, weights, client_rngs,
                    take(data, pos), take(val, pos) if val else {},
                )
                eval_summed = engine.eval_fn(params, eval_batches)
                outs = (train_metrics, eval_summed)
                if with_confusion:
                    outs = outs + (engine.confusion_fn(params, eval_batches),)
                return (params, rng), outs

            (global_params, rng), outs = jax.lax.scan(
                body,
                (global_params, rng),
                (weight_rows, pos_rows, id_rows),
                length=horizon,
            )
            return (global_params, rng), outs

        jitted = jax.jit(
            horizon_program,
            donate_argnums=(0, 1),
            out_shardings=((self._param_shardings, None), None),
        )

        def fn(global_params, rng, weight_rows, idx_rows=None):
            pos_rows = self._horizon_pos_rows
            with self._round_mesh_context():
                return self._trace.dispatch(
                    f"horizon[streamed,h={horizon}]",
                    jitted,
                    (
                        global_params,
                        rng,
                        weight_rows,
                        pos_rows,
                        idx_rows,
                        self._cohort_data,
                        self._cohort_val or {},
                        self._ensure_eval_batches(),
                    ),
                    sig_args=(weight_rows, pos_rows, idx_rows),
                )

        fn._jitted = jitted
        return fn

    def round_flops(self, global_params) -> float:
        """Analytic FLOP count for ONE round (bench MFU): XLA's cost
        analysis of a single un-scanned train step × steps per round.
        (Cost-analyzing the whole round program would undercount ~20×:
        XLA prices a ``scan``/while body ONCE, not × trip count.)
        Returns 0.0 when the backend exposes no cost analysis."""
        try:
            engine = self.engine
            batch = jax.tree.map(
                lambda x: jnp.zeros(x.shape[2:], x.dtype), self._data
            )  # [C, n_batches, B, ...] -> one [B, ...] batch
            opt_state = engine.optimizer.init(global_params)
            rng = jax.random.PRNGKey(0)
            from ..util.costwatch import cost_summary

            compiled = (
                jax.jit(engine.train_step_fn)
                .lower(global_params, opt_state, batch, rng)
                .compile()
            )
            step_flops = cost_summary(compiled)["flops"]
            # MFU honesty: price only the clients whose contribution can
            # reach the aggregate — min(worker_number, random_client_number)
            # — so the dense path's zero-weight slot compute is WASTE, not
            # credited FLOPs (``wasted_compute_fraction`` reports it)
            steps = (
                self._selected_per_round * self.config.epoch * self.n_batches
            )
            return step_flops * steps
        except Exception:  # noqa: BLE001 — bench robustness over precision
            return 0.0

    # ------------------------------------------------------------------
    def _base_weight_row(self, round_number: int) -> np.ndarray:
        """The dense ``[n_slots]`` pre-fault selection row (slot = worker
        id, dataset-size weights) — ONE definition of the selection /
        slot-order contract shared by the synchronous fault fold and the
        buffered schedule fold."""
        from ..utils.selection import select_workers

        selected = select_workers(
            self.config.seed,
            round_number,
            self.config.worker_number,
            self.config.algorithm_kwargs.get("random_client_number"),
        )
        weights = np.zeros(self.n_slots, np.float32)
        for worker_id in selected:
            weights[worker_id] = self._dataset_sizes[worker_id]
        return weights

    def _base_index_rows(
        self, round_number: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """The gather-path pre-fault rows: the round's selected worker
        ids (ascending — the dense path's slot order, so the weighted
        reduction sees the contributions in the same order) padded to the
        static ``s_pad`` with id 0 at weight 0, plus their weights —
        shared by both fault-fold flavors like :meth:`_base_weight_row`."""
        from ..utils.selection import select_workers

        selected = sorted(
            select_workers(
                self.config.seed,
                round_number,
                self.config.worker_number,
                self.config.algorithm_kwargs.get("random_client_number"),
            )
        )
        idx = np.zeros(self.s_pad, np.int32)
        idx[: len(selected)] = selected
        weights = np.zeros(self.s_pad, np.float32)
        weights[: len(selected)] = self._dataset_sizes[selected]
        return idx, weights

    def _select_weights(self, round_number: int) -> np.ndarray:
        from ..util.faults import apply_fault_plan

        # fold the round's availability mask into the weight row (dropped
        # → 0, corrupt → NaN) and enforce the quorum — a no-op without a
        # fault plan, so the unfaulted trajectory is bit-exact
        return apply_fault_plan(
            self._fault_plan,
            self._min_quorum,
            round_number,
            None,
            self._base_weight_row(round_number),
            self.config.worker_number,
        )

    def _select_indices(
        self, round_number: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Gather-path selection: :meth:`_base_index_rows` with the fault
        mask folded in.  Dropped ids are masked out of the S_pad row
        (weight 0 — they still occupy a gathered slot but contribute
        exact zeros, like padding); same draw as the dense path, so
        gather/dense parity holds under injection too."""
        from ..util.faults import apply_fault_plan

        idx, weights = self._base_index_rows(round_number)
        weights = apply_fault_plan(
            self._fault_plan,
            self._min_quorum,
            round_number,
            idx,
            weights,
            self.config.worker_number,
        )
        return idx, weights

    # -------------------------------------------- buffered replay rows
    def _fold_buffered_schedule(
        self, round_number: int, ids, weights: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """The buffered twin of :func:`~.faults.apply_fault_plan`: fold
        the arrival schedule into one TRAINING round's host weight row —
        a landing update's weight is pre-discounted by its scheduled
        staleness (the contribution is formed at train time, so the
        discount must ride the training-round row), a never-landing
        update (dropped, or landing past the run's end) is zeroed, and a
        corrupt one is NaN'd at its landing bucket.  Returns ``(weights,
        delays)`` — ``delays[pos]`` routes the slot's contribution into
        the pending ring.  No straggler sleep: the replay runs in logical
        time (the threaded executor is where wall-clock skew is real)."""
        from ..util.buffered import staleness_discount

        schedule = self._arrival_schedule
        delays = np.zeros(len(weights), np.int32)
        plan = self._fault_plan
        corrupt = (
            plan.corrupt_clients(round_number, self.config.worker_number)
            if plan is not None and plan.injection_active
            else frozenset()
        )
        worker_ids = (
            np.asarray(ids) if ids is not None else np.arange(len(weights))
        )
        for pos, wid in enumerate(worker_ids):
            if not weights[pos]:
                continue  # unselected / padding slot
            delay = schedule.delay(int(wid), round_number)
            if delay is None:
                weights[pos] = 0.0  # lost upload, or lands past run end
                continue
            delays[pos] = delay
            if int(wid) in corrupt:
                weights[pos] = np.nan
            else:
                weights[pos] = np.float32(
                    float(weights[pos])
                    * staleness_discount(
                        delay, self._buffered.staleness_alpha
                    )
                )
        return weights, delays

    def _buffered_flush_quorum(self, round_number: int) -> None:
        """Buffered quorum: an EXPLICIT ``min_client_quorum`` is enforced
        against the round's flush cohort (what actually aggregates), not
        the training cohort.  The implicit floor-of-1 the synchronous
        fault machinery applies does NOT hold here — an empty flush is a
        well-defined keep-the-old-params round (every arrival was stale),
        not a degenerate aggregate."""
        if self._min_quorum <= 0:
            return
        plan = self._fault_plan
        cohort = self._arrival_schedule.live_cohort(
            round_number, self._buffered_origin_floor
        )
        survivors = sum(
            1
            for item in cohort
            if plan is None
            or item.worker
            not in plan.corrupt_clients(
                item.origin, self.config.worker_number
            )
        )
        if survivors < self._min_quorum:
            from ..util.faults import QuorumLostError

            message = (
                f"flush {round_number}: {survivors} surviving buffered"
                f" arrivals below min_client_quorum={self._min_quorum} —"
                " aborting the round loudly"
            )
            get_logger().error(message)
            raise QuorumLostError(message)

    def _buffered_select_weights(
        self, round_number: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Dense-path ``(weights, delays)`` rows under buffered replay:
        the SAME base selection row as :meth:`_select_weights`, with the
        arrival-schedule fold instead of the synchronous fault fold."""
        self._buffered_flush_quorum(round_number)
        return self._fold_buffered_schedule(
            round_number, None, self._base_weight_row(round_number)
        )

    def _buffered_select_indices(
        self, round_number: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Gather-path ``(idx, weights, delays)`` rows under buffered
        replay — :meth:`_base_index_rows`' ``s_pad`` padding contract,
        arrival-schedule fold."""
        idx, weights = self._base_index_rows(round_number)
        self._buffered_flush_quorum(round_number)
        weights, delays = self._fold_buffered_schedule(
            round_number, idx, weights
        )
        return idx, weights, delays

    # ---------------------------------------------- streamed populations
    def _cohort_ids(self, round_number: int) -> np.ndarray:
        """The round's ``[S_pad]`` cohort ids WITHOUT the fault/quorum
        fold: the fault machinery zeroes/NaNs WEIGHTS but never changes
        which ids occupy the row, so the prefetcher can compute round
        r+1's cohort ahead of time without tripping r+1's quorum check a
        round early.  (FedOBD overrides — its padding ids are distinct
        unselected workers, not id 0.)"""
        return self._base_index_rows(round_number)[0]

    def _fetch_cohort(self, ids):
        """Host rows → device for one cohort (the ``CohortPrefetcher``
        fetch hook).  Runs on the prefetch thread: jax dispatch is
        thread-safe, and nothing here touches the trace recorder."""
        sharding = NamedSharding(self.mesh, self._slot_spec)
        data = self._population.fetch(ids)
        nbytes = sum(x.nbytes for x in jax.tree.leaves(data))
        placed = put_sharded(data, sharding)
        placed_val = None
        if self._population_val is not None:
            val = self._population_val.fetch(ids)
            nbytes += sum(x.nbytes for x in jax.tree.leaves(val))
            placed_val = put_sharded(val, sharding)
        return (placed, placed_val), nbytes

    def _take_cohort(self, round_number: int, ids: np.ndarray) -> None:
        """Blockingly obtain the cohort placed for this round/chunk (the
        double buffer usually already has it in flight — the wall the
        session actually blocked is the ``exposed`` field of the
        ``prefetch`` span, what the tracedump overlap gate bounds).  The
        host-built id row is broadcast/asserted across processes first so
        a pod never trains diverged cohorts (no-op single-process)."""
        from .mesh import broadcast_selection_rows

        ids = broadcast_selection_rows(np.asarray(ids))
        (self._cohort_data, self._cohort_val), stats = (
            self._cohort_prefetch.take(round_number, ids)
        )
        if self._trace.enabled:
            fields = {
                "round": int(round_number),
                "exposed": round(stats.exposed, 6),
                "bytes": int(stats.nbytes),
            }
            if not stats.prefetched:
                # cold fetch (first round / resume): excluded from the
                # overlap fraction — there was no prior round to hide it
                # under
                fields["warmup"] = True
            self._trace.span_record("prefetch", stats.seconds, **fields)

    def _schedule_next_cohort(self, round_number: int) -> None:
        """Queue the NEXT round's cohort fetch+place so it overlaps the
        current round's dispatched program (the double buffer)."""
        if round_number > self.config.round:
            return
        self._cohort_prefetch.schedule(
            round_number, self._cohort_ids(round_number)
        )

    def _streamed_horizon_ids(self, start_round: int, h: int):
        """The fused chunk's cohort: per-round ``[h, S_pad]`` id rows,
        their union padded to the static ``h * S_pad`` (one program shape
        per horizon length), and the position rows mapping each round's
        slots into the placed union stack."""
        from ..util.population import union_cohort

        id_rows = np.stack(
            [
                self._cohort_ids(r)
                for r in range(start_round, start_round + h)
            ]
        )
        ids_u, pos_rows = union_cohort(id_rows, h * self.s_pad)
        return ids_u, pos_rows, id_rows

    def _schedule_next_horizon_cohort(self, start_round: int) -> None:
        """Queue the next chunk's union cohort behind this chunk's fused
        scan."""
        if start_round > self.config.round:
            return
        h = min(self.round_horizon, self.config.round - start_round + 1)
        ids_u, _pos, _ids = self._streamed_horizon_ids(start_round, h)
        self._cohort_prefetch.schedule(start_round, ids_u)

    def _prepare_round_inputs(self, round_number: int, round_rng):
        """Device inputs for ONE round program invocation:
        ``(host_weights, weights, client_rngs, sel_idx)`` — ``sel_idx`` is
        None on the dense path.  Shared by ``run()`` and bench drivers so
        both exercise the session's actual selection path.  Under
        buffered replay the staleness-delay row rides session state
        (``_round_delays``) so every caller's dispatch surface stays
        unchanged.  Under streamed populations the placed cohort rides
        ``_cohort_data``/``_cohort_val`` the same way, and the round's
        rngs fold by WORKER ID (``_fold_sel_rngs``) — bit-identical to
        the dense fold of the same ids."""
        if self._population_streamed:
            host_idx = self._cohort_ids(round_number)
            if self._buffered_active:
                _idx, host_weights, host_delays = (
                    self._buffered_select_indices(round_number)
                )
            else:
                _idx, host_weights = self._select_indices(round_number)
                host_delays = None
            self._take_cohort(round_number, host_idx)
            self._schedule_next_cohort(round_number + 1)
            sel_idx = put_sharded(host_idx, self._client_sharding)
            weights = put_sharded(host_weights, self._client_sharding)
            client_rngs = self._fold_sel_rngs(round_rng, sel_idx)
            if host_delays is not None:
                self._round_delays = put_sharded(
                    host_delays, self._client_sharding
                )
            # sel_idx None: the dispatch runs the dense-shaped program at
            # cohort shape over the placed rows — there is nothing left
            # to gather
            return host_weights, weights, client_rngs, None
        if self._buffered_active:
            if self._selection_gather:
                host_idx, host_weights, host_delays = (
                    self._buffered_select_indices(round_number)
                )
                sel_idx = put_sharded(host_idx, self._client_sharding)
                weights = put_sharded(host_weights, self._client_sharding)
                client_rngs = self._fold_sel_rngs(round_rng, sel_idx)
            else:
                sel_idx = None
                host_weights, host_delays = self._buffered_select_weights(
                    round_number
                )
                weights = put_sharded(host_weights, self._client_sharding)
                client_rngs = self._fold_rngs(round_rng)
            self._round_delays = put_sharded(
                host_delays, self._client_sharding
            )
            return host_weights, weights, client_rngs, sel_idx
        if self._selection_gather:
            host_idx, host_weights = self._select_indices(round_number)
            sel_idx = put_sharded(host_idx, self._client_sharding)
            weights = put_sharded(host_weights, self._client_sharding)
            client_rngs = self._fold_sel_rngs(round_rng, sel_idx)
        else:
            sel_idx = None
            host_weights = self._select_weights(round_number)
            weights = put_sharded(host_weights, self._client_sharding)
            client_rngs = self._fold_rngs(round_rng)
        return host_weights, weights, client_rngs, sel_idx

    def _horizon_selection_rows(self, start_round: int, h: int):
        """Host-precomputed per-round selection for one fused horizon of
        ``h`` rounds starting at ``start_round``: ``(host [h, S] weight
        matrix, device weight rows, device [h, S_pad] id rows or None)`` —
        the scanned inputs every horizon-fused session (FedAvg family AND
        the FedOBD phase programs) feeds its round scan.  Under buffered
        replay the ``[h, S]`` staleness-delay rows ride session state
        (``_horizon_delay_rows``) next to the weight rows.  Under
        streamed populations the chunk's UNION cohort is taken once here
        (the cohort-union rule) with the position rows riding
        ``_horizon_pos_rows``."""
        if self._population_streamed:
            if self._buffered_active:
                triples = [
                    self._buffered_select_indices(r)
                    for r in range(start_round, start_round + h)
                ]
                host_weights = np.stack([w for _i, w, _d in triples])
                host_delays = np.stack([d for _i, _w, d in triples])
                self._horizon_delay_rows = put_sharded(
                    host_delays, self._horizon_weight_sharding
                )
            else:
                pairs = [
                    self._select_indices(r)
                    for r in range(start_round, start_round + h)
                ]
                host_weights = np.stack([w for _i, w in pairs])
            ids_u, pos_rows, id_rows = self._streamed_horizon_ids(
                start_round, h
            )
            self._take_cohort(start_round, ids_u)
            self._schedule_next_horizon_cohort(start_round + h)
            self._horizon_pos_rows = put_sharded(
                pos_rows, self._horizon_weight_sharding
            )
            idx_rows = put_sharded(id_rows, self._horizon_weight_sharding)
            weight_rows = put_sharded(
                host_weights, self._horizon_weight_sharding
            )
            return host_weights, weight_rows, idx_rows
        if self._buffered_active:
            if self._selection_gather:
                triples = [
                    self._buffered_select_indices(r)
                    for r in range(start_round, start_round + h)
                ]
                host_weights = np.stack([w for _i, w, _d in triples])
                host_delays = np.stack([d for _i, _w, d in triples])
                idx_rows = put_sharded(
                    np.stack([i for i, _w, _d in triples]),
                    self._horizon_weight_sharding,
                )
            else:
                idx_rows = None
                pairs = [
                    self._buffered_select_weights(r)
                    for r in range(start_round, start_round + h)
                ]
                host_weights = np.stack([w for w, _d in pairs])
                host_delays = np.stack([d for _w, d in pairs])
            self._horizon_delay_rows = put_sharded(
                host_delays, self._horizon_weight_sharding
            )
            weight_rows = put_sharded(
                host_weights, self._horizon_weight_sharding
            )
            return host_weights, weight_rows, idx_rows
        if self._selection_gather:
            # host-precomputed [H, s_pad] id + weight matrices — the
            # fused program gathers per scanned round
            pairs = [
                self._select_indices(r)
                for r in range(start_round, start_round + h)
            ]
            host_weights = np.stack([w for _i, w in pairs])
            idx_rows = put_sharded(
                np.stack([i for i, _w in pairs]),
                self._horizon_weight_sharding,
            )
        else:
            idx_rows = None
            host_weights = np.stack(
                [
                    self._select_weights(r)
                    for r in range(start_round, start_round + h)
                ]
            )
        weight_rows = put_sharded(host_weights, self._horizon_weight_sharding)
        return host_weights, weight_rows, idx_rows

    @property
    def wasted_compute_fraction(self) -> float:
        """Fraction of the round program's client-slot compute whose
        aggregation weight is zero (unselected slots + padding): the dense
        path trains ``n_slots`` for ``selected`` useful contributions, the
        gather path trains ``s_pad``, and the streamed path only ever
        PLACES (and trains) ``s_pad``."""
        trained = (
            self.s_pad
            if (self._selection_gather or self._population_streamed)
            else self.n_slots
        )
        return 1.0 - self._selected_per_round / max(trained, 1)

    # ------------------------------------------------- shardcheck hooks
    def shardcheck_shardings(self):
        """Declared sharding vocabulary for ``tools/shardcheck``'s
        mesh-axis-vocabulary rule: every (mesh, PartitionSpec) pair this
        session stores or pins, checked structurally against the mesh's
        axis names before any program is dispatched."""
        from .introspect import DeclaredSpec, named_sharding_decls

        decls = [
            DeclaredSpec("slot_spec", self.mesh, self._slot_spec),
            DeclaredSpec(
                "horizon_weight_rows",
                self.mesh,
                self._horizon_weight_sharding.spec,
            ),
        ]
        decls += [
            DeclaredSpec(f"params[{k}]", self.mesh, spec)
            for k, spec in self._param_specs.items()
        ]
        decls += named_sharding_decls("data", self._data)
        if self._val_data is not None:
            decls += named_sharding_decls("val", self._val_data)
        return decls

    def shardcheck_programs(self):
        """Every jitted program this session's run loop would dispatch,
        as abstract :class:`~.introspect.ProgramSpec` records: arguments
        are ``ShapeDtypeStruct``s (real shardings attached) derived from
        the resident stacks plus the HOST-side selection of rounds 1 and
        2, so the certifier can ``eval_shape``/``lower`` the exact
        programs — never execute them — and prove that consecutive
        rounds share one jit cache entry."""
        from .introspect import (
            ProgramSpec,
            abstract_tree,
            attach_shardings,
            host_abstract,
            key_abstract,
        )

        specs = []
        if getattr(self, "_jitted_round_fn", None) is None:
            return specs  # bespoke round program: nothing registered
        template = jax.eval_shape(
            lambda: self.engine.init_params(self.config.seed)
        )
        params = attach_shardings(template, self._param_shardings)
        data = abstract_tree(self._data)
        val = abstract_tree(self._val_data or {})

        if self._population_streamed:
            # streamed populations dispatch the SAME dense-shaped jitted
            # program at cohort shape: certify it against [s_pad]-leading
            # abstract stacks carrying the slot sharding the prefetcher
            # places them with
            def cohort_abstract(tree, leading):
                return jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(
                        (leading, *np.shape(x)[1:]),
                        np.asarray(x).dtype
                        if not hasattr(x, "dtype")
                        else x.dtype,
                        sharding=self._client_sharding,
                    ),
                    tree,
                )

            cohort_data = cohort_abstract(self._data, self.s_pad)
            cohort_val = (
                cohort_abstract(
                    self._population_val.fetch(np.zeros(1, np.int64)),
                    self.s_pad,
                )
                if self._population_val is not None
                else {}
            )

            def streamed_args(round_number):
                if self._buffered_active:
                    _i, weights, delays = self._buffered_select_indices(
                        round_number
                    )
                    depth = self._buffered_depth
                    pending = (
                        {
                            k: host_abstract(
                                np.zeros((depth, *v.shape), np.float32),
                                self._replicated,
                            )
                            for k, v in template.items()
                        },
                        host_abstract(
                            np.zeros((depth,), np.float32),
                            self._replicated,
                        ),
                    )
                    return (
                        params,
                        pending,
                        host_abstract(weights, self._client_sharding),
                        host_abstract(delays, self._client_sharding),
                        key_abstract(self._client_sharding, (self.s_pad,)),
                        cohort_data,
                        cohort_val,
                    )
                _i, weights = self._select_indices(round_number)
                return (
                    params,
                    host_abstract(weights, self._client_sharding),
                    key_abstract(self._client_sharding, (self.s_pad,)),
                    cohort_data,
                    cohort_val,
                )

            if self._buffered_active:
                specs.append(
                    ProgramSpec(
                        name="round[buffered-streamed]",
                        jitted=self._jitted_buffered_round_fn,
                        args=streamed_args(1),
                        alt_args=(streamed_args(2),),
                        donate_argnums=(0, 1),
                        mesh=self.mesh,
                        out_pin=(
                            (self._param_shardings, self._replicated),
                            None,
                        ),
                        carries=(
                            (0, lambda out: out[0][0]),
                            (1, lambda out: out[0][1]),
                        ),
                        mesh_context=self._round_mesh_context,
                    )
                )
            else:
                specs.append(
                    ProgramSpec(
                        name="round[streamed]",
                        jitted=self._jitted_round_fn,
                        args=streamed_args(1),
                        alt_args=(streamed_args(2),),
                        donate_argnums=(0,),
                        mesh=self.mesh,
                        out_pin=self._round_out_shardings,
                        carries=((0, lambda out: out[0]),),
                        mesh_context=self._round_mesh_context,
                    )
                )
            if self._horizon_capable() and not self._buffered_active:
                h = max(2, min(self.round_horizon, 4))
                fn = self._horizon_fns.get(h)
                if fn is None:
                    fn = self._horizon_fns[h] = self._build_horizon_fn(h)
                eval_batches = abstract_tree(self._ensure_eval_batches())
                union_pad = h * self.s_pad
                union_data = cohort_abstract(self._data, union_pad)
                union_val = (
                    cohort_abstract(
                        self._population_val.fetch(np.zeros(1, np.int64)),
                        union_pad,
                    )
                    if self._population_val is not None
                    else {}
                )

                def streamed_horizon_args(start_round):
                    rows = [
                        self._select_indices(r)
                        for r in range(start_round, start_round + h)
                    ]
                    weight_rows = np.stack([w for _i, w in rows])
                    _u, pos_rows, id_rows = self._streamed_horizon_ids(
                        start_round, h
                    )
                    return (
                        params,
                        key_abstract(self._replicated),
                        host_abstract(
                            weight_rows, self._horizon_weight_sharding
                        ),
                        host_abstract(
                            pos_rows, self._horizon_weight_sharding
                        ),
                        host_abstract(
                            id_rows, self._horizon_weight_sharding
                        ),
                        union_data,
                        union_val,
                        eval_batches,
                    )

                specs.append(
                    ProgramSpec(
                        name=f"horizon[streamed,h={h}]",
                        jitted=fn._jitted,
                        args=streamed_horizon_args(1),
                        alt_args=(streamed_horizon_args(1 + h),),
                        donate_argnums=(0, 1),
                        mesh=self.mesh,
                        out_pin=((self._param_shardings, None), None),
                        carries=(
                            (0, lambda out: out[0][0]),
                            (1, lambda out: out[0][1]),
                        ),
                        scanned_len=h,
                        stacked_out=lambda out: out[1],
                        mesh_context=self._round_mesh_context,
                    )
                )
            return specs

        if self._buffered_active:
            # buffered replay: certify the dispatched per-round buffered
            # program — params AND the pending ring are donated carries
            # whose pinned layouts must survive the round.  The buffered
            # HORIZON program shares these pins (same out_shardings) and
            # is runtime-gated by the tracedump dispatch budget in
            # test.sh / tests, so only the per-round program registers.
            depth = self._buffered_depth
            pending = (
                {
                    k: host_abstract(
                        np.zeros((depth, *v.shape), np.float32),
                        self._replicated,
                    )
                    for k, v in template.items()
                },
                host_abstract(
                    np.zeros((depth,), np.float32), self._replicated
                ),
            )

            def buffered_args(round_number):
                if self._selection_gather:
                    idx, weights, delays = self._buffered_select_indices(
                        round_number
                    )
                    return (
                        params,
                        pending,
                        host_abstract(weights, self._client_sharding),
                        host_abstract(delays, self._client_sharding),
                        key_abstract(self._client_sharding, (self.s_pad,)),
                        host_abstract(idx, self._client_sharding),
                        data,
                        val,
                    )
                weights, delays = self._buffered_select_weights(
                    round_number
                )
                return (
                    params,
                    pending,
                    host_abstract(weights, self._client_sharding),
                    host_abstract(delays, self._client_sharding),
                    key_abstract(self._client_sharding, (self.n_slots,)),
                    data,
                    val,
                )

            specs.append(
                ProgramSpec(
                    name=(
                        "round[buffered-gather]"
                        if self._selection_gather
                        else "round[buffered]"
                    ),
                    jitted=(
                        self._jitted_buffered_gather_fn
                        if self._selection_gather
                        else self._jitted_buffered_round_fn
                    ),
                    args=buffered_args(1),
                    alt_args=(buffered_args(2),),
                    donate_argnums=(0, 1),
                    mesh=self.mesh,
                    out_pin=(
                        (self._param_shardings, self._replicated),
                        None,
                    ),
                    carries=(
                        (0, lambda out: out[0][0]),
                        (1, lambda out: out[0][1]),
                    ),
                    mesh_context=self._round_mesh_context,
                )
            )
            return specs

        def round_args(round_number):
            if self._selection_gather:
                idx, weights = self._select_indices(round_number)
                return (
                    params,
                    host_abstract(weights, self._client_sharding),
                    key_abstract(self._client_sharding, (self.s_pad,)),
                    host_abstract(idx, self._client_sharding),
                    data,
                    val,
                )
            weights = self._select_weights(round_number)
            return (
                params,
                host_abstract(weights, self._client_sharding),
                key_abstract(self._client_sharding, (self.n_slots,)),
                data,
                val,
            )

        specs.append(
            ProgramSpec(
                name=(
                    "round[gather]"
                    if self._selection_gather
                    else "round[dense]"
                ),
                jitted=(
                    self._jitted_gather_round_fn
                    if self._selection_gather
                    else self._jitted_round_fn
                ),
                args=round_args(1),
                alt_args=(round_args(2),),
                donate_argnums=(0,),
                mesh=self.mesh,
                out_pin=self._round_out_shardings,
                carries=((0, lambda out: out[0]),),
                mesh_context=self._round_mesh_context,
            )
        )
        if self._horizon_capable():
            h = max(2, min(self.round_horizon, 4))
            fn = self._horizon_fns.get(h)
            if fn is None:
                fn = self._horizon_fns[h] = self._build_horizon_fn(h)
            eval_batches = abstract_tree(self._ensure_eval_batches())

            def horizon_args(start_round):
                if self._selection_gather:
                    pairs = [
                        self._select_indices(r)
                        for r in range(start_round, start_round + h)
                    ]
                    weight_rows = np.stack([w for _i, w in pairs])
                    idx_rows = host_abstract(
                        np.stack([i for i, _w in pairs]),
                        self._horizon_weight_sharding,
                    )
                else:
                    idx_rows = None
                    weight_rows = np.stack(
                        [
                            self._select_weights(r)
                            for r in range(start_round, start_round + h)
                        ]
                    )
                return (
                    params,
                    key_abstract(self._replicated),
                    host_abstract(
                        weight_rows, self._horizon_weight_sharding
                    ),
                    idx_rows,
                    data,
                    val,
                    eval_batches,
                )

            specs.append(
                ProgramSpec(
                    name=f"horizon[h={h}]",
                    jitted=fn._jitted,
                    args=horizon_args(1),
                    alt_args=(horizon_args(1 + h),),
                    donate_argnums=(0, 1),
                    mesh=self.mesh,
                    out_pin=((self._param_shardings, None), None),
                    carries=(
                        (0, lambda out: out[0][0]),
                        (1, lambda out: out[0][1]),
                    ),
                    scanned_len=h,
                    stacked_out=lambda out: out[1],
                    mesh_context=self._round_mesh_context,
                )
            )
        return specs

    def _init_global_params(self):
        """Initial params + first round: resume from a previous session's
        latest ``aggregated_model/round_N.npz`` (mirrors the threaded
        ``AggregationServer._try_resume``), else ``global_model_path`` warm
        start, else fresh init."""
        config = self.config
        resume_dir = config.algorithm_kwargs.get("resume_dir")
        if resume_dir:
            from ..util.resume import load_resume_state

            params, stats, last = load_resume_state(resume_dir)
            if params is not None:
                self._stat.update(stats)
                self._max_acc = max(
                    s["test_accuracy"] for s in self._stat.values()
                )
                # the restored best_global_model.npz (if any) is at most
                # this good — only a better checkpointed round re-promotes
                self._best_ckpt_acc = self._max_acc
                get_logger().info("resumed from %s round %d", resume_dir, last)
                self._trace.event(
                    "resume", round=last + 1, source=str(resume_dir)
                )
                # buffered resume drains the buffer: the pending ring
                # restarts at zeros, so pre-resume origins can never
                # merge — floor them out of cohort accounting
                self._buffered_origin_floor = last + 1
                return self._place_params(params), last + 1
        init_path = config.algorithm_kwargs.get("global_model_path")
        if init_path:
            with np.load(init_path) as blob:
                params = {k: blob[k] for k in blob.files}
            return self._place_params(params), 1
        return self._place_params(self.engine.init_params(config.seed)), 1

    # wire-cost factor for the stat surface: fraction of full fp32 bytes a
    # client upload costs (fed_paq's 255-level QSGD packs 8 level bits + 1
    # sign bit per element)
    def _upload_cost_factor(self) -> float:
        if self.quantization_level is not None:
            import math

            return (math.ceil(math.log2(self.quantization_level + 1)) + 1) / 32
        return 1.0

    def run(self) -> dict:
        import time as _time

        if self.round_horizon > 1:
            return self._run_horizon()
        config = self.config
        global_params, start_round = self._init_global_params()
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        rng = jax.random.PRNGKey(config.seed)
        for _ in range(start_round - 1):  # resume: keep the rng stream aligned
            rng, _unused = jax.random.split(rng)
        self._last_ckpt_round = start_round - 1
        param_mb = sum(
            int(np.prod(v.shape)) * 4 for v in jax.tree.leaves(global_params)
        ) / 1e6
        model_dir = os.path.join(config.save_dir, "aggregated_model")
        os.makedirs(model_dir, exist_ok=True)
        with self._ckpt:  # flush pending writes at exit, surface errors
            for round_number in range(start_round, config.round + 1):
                start = _time.monotonic()
                rng, round_rng = jax.random.split(rng)
                # per-client streams by fold_in, NOT split(round_rng, n):
                # fold_in is indexed by WORKER ID alone, so the stream is
                # independent of slot padding / device count — the threaded
                # executor derives the identical stream per worker
                # (engine/executor.py::aligned_round_stream) and the
                # cross-executor parity test pins fed_avg trajectories.
                # The chain stays device-resident (no host bounce).  On the
                # selection-gather path the same streams are folded for the
                # selected ids only.
                self._trace.maybe_profile_start(round_number)
                host_weights, weights, client_rngs, sel_idx = (
                    self._prepare_round_inputs(round_number, round_rng)
                )
                self._trace.event(
                    "dispatch", program="fold_rngs", round=round_number
                )
                # old global_params are donated into the round program —
                # any pending background fetch of them must finish first
                self._ckpt.barrier()
                global_params, train_metrics = self._watchdog.call(
                    lambda gp=global_params, w=weights, r=client_rngs, i=sel_idx: (
                        self._round_fn(gp, w, r)
                        if i is None
                        else self._round_fn(gp, w, r, i)
                    ),
                    phase="round",
                    round_number=round_number,
                )
                self._trace.event(
                    "dispatch", program="round", round=round_number
                )
                # queue the round checkpoint NOW so its device→host fetch
                # and disk write overlap the test-set evaluation below
                if self._should_checkpoint(round_number):
                    self._ckpt.save_npz(
                        os.path.join(model_dir, f"round_{round_number}.npz"),
                        self._checkpointable(global_params),
                    )
                    self._ckpt_queued_round = round_number
                    self._last_ckpt_round = round_number
                    self._trace.event("checkpoint", round=round_number)
                with self._trace.span("eval", round=round_number):
                    metric = self._watchdog.call(
                        lambda gp=global_params: self._evaluate(gp),
                        phase="eval",
                        round_number=round_number,
                    )
                self._trace.event(
                    "dispatch", program="eval", round=round_number
                )
                self._trace.event("host_sync", round=round_number)
                self._trace.hbm_watermark(round_number)
                self._trace.count("rounds")
                # same stat surface as the threaded server: analytic wire
                # cost (what the aggregation consumed over ICI, priced at
                # the reference's message sizes) + round wall time
                selected = int((host_weights > 0).sum())
                extra = {
                    "received_mb": selected
                    * param_mb
                    * self._upload_cost_factor(),
                    "sent_mb": selected * param_mb,
                    "round_seconds": _time.monotonic() - start,
                }
                rejected = 0
                if self._update_guard:
                    # the guard's per-round reject count rides the train
                    # metrics; fetched alongside the eval metric (the
                    # round's one host sync point), guard-gated so the
                    # default path's sync budget is untouched
                    rejected = int(
                        np.asarray(train_metrics["rejected_updates"])
                    )
                    extra["rejected_updates"] = rejected
                if self._buffered_active:
                    extra.update(self._buffered_round_extras(round_number))
                self._trace_fault_event(round_number, rejected)
                self._record(
                    round_number, metric, global_params, save_dir, extra=extra
                )
                # post-guard quorum: participating counts NaN-poisoned
                # weights too (NaN != 0), matching the in-program rule
                # (a no-op under buffered replay — the flush-cohort
                # pre-check in _buffered_flush_quorum is the gate there)
                self._post_guard_quorum(
                    round_number, (host_weights != 0).sum(), rejected
                )
                self._maybe_kill(round_number)
                self._trace.maybe_profile_stop(round_number)
        return {"performance": self._stat}

    def _run_horizon(self) -> dict:
        """The fused run loop: ``round_horizon`` rounds per dispatch, one
        host sync per horizon (the stacked metric fetch).  Checkpoints and
        record flushes land on horizon boundaries; the per-round stat
        surface (record rows, log lines, best-model tracking) is identical
        to the H=1 loop — metrics just become visible up to H−1 rounds
        late."""
        import time as _time

        config = self.config
        global_params, start_round = self._init_global_params()
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        model_dir = os.path.join(config.save_dir, "aggregated_model")
        os.makedirs(model_dir, exist_ok=True)
        rng = jax.random.PRNGKey(config.seed)
        for _ in range(start_round - 1):  # resume: keep the rng stream aligned
            rng, _unused = jax.random.split(rng)
        # replicate the chain carry up front: the fused program returns it
        # replicated, and a sharding mismatch on the first chunk would
        # retrace the horizon program once per run
        rng = jax.device_put(rng, self._replicated)
        self._last_ckpt_round = start_round - 1
        param_mb = sum(
            int(np.prod(v.shape)) * 4 for v in jax.tree.leaves(global_params)
        ) / 1e6
        cost_factor = self._upload_cost_factor()
        self._ensure_eval_batches()
        with self._ckpt:
            round_number = start_round
            while round_number <= config.round:
                # the final chunk may be shorter — a tail program of length
                # h compiles once and is cached per length
                h = min(self.round_horizon, config.round - round_number + 1)
                fn = self._horizon_fns.get(h)
                if fn is None:
                    fn = self._horizon_fns[h] = self._build_horizon_fn(h)
                start = _time.monotonic()
                boundary = round_number + h - 1
                self._trace.maybe_profile_start(round_number, boundary)
                host_weights, weight_rows, idx_rows = (
                    self._horizon_selection_rows(round_number, h)
                )
                # old params AND the rng carry are donated into the fused
                # program — pending background fetches must finish first
                self._ckpt.barrier()
                (global_params, rng), outs = self._watchdog.call(
                    lambda gp=global_params, r=rng, w=weight_rows, i=idx_rows: fn(
                        gp, r, w, i
                    ),
                    phase="round",
                    round_number=boundary,
                )
                self._trace.event(
                    "dispatch",
                    program=f"horizon[h={h}]",
                    round=boundary,
                    rounds=h,
                )
                # queue the boundary checkpoint NOW: its device→host fetch
                # overlaps the stacked metric fetch below
                if self._should_checkpoint(boundary):
                    self._ckpt.save_npz(
                        os.path.join(model_dir, f"round_{boundary}.npz"),
                        self._checkpointable(global_params),
                    )
                    self._ckpt_queued_round = boundary
                    self._last_ckpt_round = boundary
                    self._trace.event("checkpoint", round=boundary)
                # ONE host sync per horizon: the stacked eval metrics
                per_round = stacked_round_metrics(outs[1])
                confusion = np.asarray(outs[2]) if len(outs) > 2 else None
                # guard reject counts ride the stacked [H] train metrics —
                # part of the same per-horizon sync, fetched only when the
                # guard is compiled in
                rejected_rows = (
                    np.asarray(outs[0]["rejected_updates"])
                    if self._update_guard
                    else None
                )
                self._trace.event("host_sync", round=boundary)
                self._trace.hbm_watermark(boundary)
                chunk_seconds = _time.monotonic() - start
                self._trace.span_record(
                    "horizon",
                    chunk_seconds,
                    first_round=round_number,
                    last_round=boundary,
                    rounds=h,
                )
                for i in range(h):
                    r = round_number + i
                    metric = per_round[i]
                    if confusion is not None:
                        metric.update(slow_metrics_from_confusion(confusion[i]))
                    selected = int((host_weights[i] > 0).sum())
                    extra = {
                        "received_mb": selected * param_mb * cost_factor,
                        "sent_mb": selected * param_mb,
                        "round_seconds": chunk_seconds / h,
                    }
                    if rejected_rows is not None:
                        extra["rejected_updates"] = int(rejected_rows[i])
                    if self._buffered_active:
                        extra.update(self._buffered_round_extras(r))
                    self._trace_fault_event(
                        r,
                        rejected_rows[i] if rejected_rows is not None else 0,
                    )
                    self._note_round(r, metric, save_dir, extra=extra)
                    if rejected_rows is not None:
                        self._post_guard_quorum(
                            r,
                            (host_weights[i] != 0).sum(),
                            rejected_rows[i],
                        )
                    self._max_acc = max(self._max_acc, metric["accuracy"])
                    # only boundary rounds have a checkpoint to promote —
                    # best_global_model.npz tracks the best CHECKPOINTED
                    # round under fusion, against its own high-water mark
                    # (a better mid-horizon round must not starve it)
                    if (
                        r == boundary
                        and self._ckpt_queued_round == boundary
                        and metric["accuracy"] > self._best_ckpt_acc
                    ):
                        self._best_ckpt_acc = metric["accuracy"]
                        self._ckpt.copy_last_to(
                            os.path.join(save_dir, "best_global_model.npz")
                        )
                self._trace.count("rounds", h)
                # a kill scheduled anywhere in the chunk fires at the
                # horizon boundary (records + the boundary checkpoint are
                # durable; a mid-horizon kill round simply resumes from an
                # earlier boundary and re-trains the tail)
                self._maybe_kill(round_number, boundary)
                self._trace.maybe_profile_stop(boundary)
                round_number += h
        return {"performance": self._stat}

    def _should_checkpoint(self, round_number: int) -> bool:
        """Checkpoint cadence: every ``checkpoint_every`` rounds since the
        last written checkpoint, plus always the run's final round (so the
        exit state is resumable)."""
        if round_number >= self.config.round:
            return True
        return round_number - self._last_ckpt_round >= self._checkpoint_every

    @property
    def dispatches_per_round(self) -> float:
        return self.dispatch_count / max(1, self.rounds_run)

    @property
    def host_sync_points(self) -> float:
        return self.host_sync_count / max(1, self.rounds_run)

    @property
    def _resident_dtype(self):
        """The compute dtype when amp residency is on, else None — the
        switch the whole-mesh round bodies (``scan_weighted_clients``,
        the OBD scan) thread through."""
        if getattr(self, "_amp_resident", False):
            return self.engine.model_ctx.compute_dtype
        return None

    def _hoist_batch_cast(self, batches):
        """amp residency: store the floating INPUT leaves of a batch tree
        in the compute dtype (cast once at placement instead of per step
        in-program).  ``astype`` is deterministic, so storing the cast is
        bit-identical to casting at use; masks/targets are untouched —
        metric counting stays exact f32."""
        if not getattr(self, "_amp_resident", False):
            return batches
        if not isinstance(batches, dict) or "input" not in batches:
            return batches
        cdtype = self.engine.model_ctx.compute_dtype
        batches = dict(batches)
        batches["input"] = jax.tree.map(
            lambda x: x.astype(cdtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            batches["input"],
        )
        return batches

    def _ensure_eval_batches(self):
        # test batches are device-resident and built once — rebuilding host
        # arrays per round re-uploads the whole test set every evaluation
        # (~1.3 s/round over the tunneled chip at the canonical scale)
        if self._eval_batches is None:
            from ..engine.batching import make_epoch_batches

            test = self.dc.get_dataset(Phase.Test)
            # put_sharded, not device_put: on a multi-host pod the replicated
            # sharding spans non-addressable devices (every process passes
            # the full array; JAX keeps the addressable shards), matching
            # _place_params
            self._eval_batches = put_sharded(
                self._hoist_batch_cast(
                    make_epoch_batches(test, self.config.batch_size)
                ),
                self._replicated,
            )
        return self._eval_batches

    def _evaluate(self, global_params) -> dict:
        summed = self.engine.evaluate(global_params, self._ensure_eval_batches())
        metric = summarize_metrics(summed)
        metric.update(
            maybe_slow_metrics(
                self.config, self.engine, global_params, self._eval_batches
            )
        )
        return metric

    def _note_round(self, round_number, metric, save_dir, extra=None) -> None:
        """Record one round's stat row and flush ``round_record.json`` on
        the ``record_flush_every`` cadence — atomically (tmp file + rename),
        so a crash never leaves a torn record for resume to trip on.  The
        final flush rides the checkpoint writer's exit finalizer."""
        round_stat = {f"test_{k}": v for k, v in metric.items()}
        if extra:
            round_stat.update(extra)
        if self._trace.enabled:
            # one `round` span per recorded round on EVERY run path (the
            # single funnel both loops and the OBD driver flow through);
            # the record row cross-links the span's JSONL line offset
            span_fields = {
                "round": round_number,
                "accuracy": metric.get("accuracy"),
                "loss": metric.get("loss"),
            }
            for key in (
                "received_mb",
                "sent_mb",
                "rejected_updates",
                "phase",
            ):
                if extra and key in extra:
                    span_fields[key] = extra[key]
            round_stat["trace_offset"] = self._trace.span_record(
                "round",
                (extra or {}).get("round_seconds", 0.0),
                **span_fields,
            )
        self._stat[round_number] = round_stat
        get_logger().info(
            "round: %d, test accuracy %.4f loss %.4f (spmd)",
            round_number,
            metric["accuracy"],
            metric["loss"],
        )
        self._record_path = os.path.join(save_dir, "round_record.json")
        self._record_dirty = True
        if (
            round_number % self._record_flush_every == 0
            or round_number >= self.config.round
        ):
            self._flush_record()

    def _flush_record(self) -> None:
        if not self._record_dirty or self._record_path is None:
            return
        # rows cross-link trace spans by line offset (trace_offset) and a
        # resumed recorder renumbers from the durable line count — land
        # the referenced lines BEFORE the rows so a hard kill between the
        # two writes can't leave rows pointing at a future session's lines
        self._trace.flush()
        atomic_json_dump(self._record_path, self._stat)
        self._record_dirty = False

    def _record(
        self, round_number, metric, global_params, save_dir, extra=None
    ) -> None:
        self._note_round(round_number, metric, save_dir, extra)
        if (
            self._ckpt_queued_round != round_number
            and self._should_checkpoint(round_number)
        ):
            # the base run loop queues round_N.npz right after the round
            # program returns (overlapping evaluation); sessions that
            # override run() (OBD, Shapley) queue it here instead.  Async is
            # safe for them too: the params they record (OBD's exact
            # aggregate, Shapley's weighted average) are fresh arrays their
            # round programs never donate, and the writer holds a reference
            # until the fetch completes.  Their run() loops flush through
            # the writer's context manager.
            model_dir = os.path.join(self.config.save_dir, "aggregated_model")
            os.makedirs(model_dir, exist_ok=True)
            self._ckpt.save_npz(
                os.path.join(model_dir, f"round_{round_number}.npz"),
                dict(global_params),
            )
            self._ckpt_queued_round = round_number
            self._last_ckpt_round = round_number
        # promoting the round checkpoint to best is a file copy chained on
        # the writer queue, not a second device fetch.  If the background
        # save failed, copy_last_to skips the promotion while _max_acc has
        # already advanced — until the fail-fast error surfaces at the next
        # queue operation, best_global_model.npz may lag _max_acc by one
        # round; a crash inside that window leaves the stale best on disk.
        self._max_acc = max(self._max_acc, metric["accuracy"])
        # with a sparse checkpoint cadence, only rounds that wrote
        # round_N.npz can be promoted — best_global_model.npz tracks the
        # best CHECKPOINTED round against its own high-water mark, so an
        # un-checkpointed better round cannot starve later promotions
        if (
            self._ckpt_queued_round == round_number
            and metric["accuracy"] > self._best_ckpt_acc
        ):
            self._best_ckpt_acc = metric["accuracy"]
            self._ckpt.copy_last_to(
                os.path.join(save_dir, "best_global_model.npz")
            )

    @property
    def performance_stat(self) -> dict:
        return self._stat


class SpmdSignSGDSession(TraceCounterMixin):
    """The whole sign-SGD run as ONE SPMD program.

    The reference's sign-SGD substrate exchanges a gradient through pipes
    on **every optimizer step** (``worker/gradient_worker.py:50-116`` — the
    worst-case transport pattern for the pipe fabric).  Here the per-step
    exchange is a ``psum`` over the ``clients`` mesh axis *inside* the
    scanned step body: sign(local grad) → masked sum across slots → psum →
    sign (majority vote, ``method/sign_sgd``) → momentum SGD update applied
    identically on every client.  No host round-trips at all — epochs ×
    batches × collectives compile into a single XLA program.
    """

    def __init__(
        self,
        config: DistributedTrainingConfig,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        mesh: Mesh | None = None,
    ) -> None:
        self.config = config
        self.dc = dataset_collection
        self.model_ctx = model_ctx
        self.engine = engine
        self.mesh = mesh if mesh is not None else make_mesh()
        from .watchdog import DeadlineWatchdog

        self._watchdog = DeadlineWatchdog.from_config(config, self.mesh)
        self.n_slots = client_slots(config.worker_number, self.mesh)
        self._stat: dict[int, dict] = {}
        # roundtrace telemetry (util/telemetry.py) — same contract as
        # SpmdFedAvgSession: counters always on, span/event records only
        # under config.telemetry.enabled, zero new dispatches/syncs
        from ..util.telemetry import TraceRecorder

        self._trace = TraceRecorder.from_config(config)
        # round-horizon fusion, same contract as SpmdFedAvgSession: scan H
        # rounds (each already a whole-run-of-steps program) per dispatch,
        # evaluating in-program, fetching stacked metrics once per horizon
        self.round_horizon = max(
            1, int(config.algorithm_kwargs.get("round_horizon", 1) or 1)
        )
        # selection-aware gather, sign-SGD flavor: the reference sign-SGD
        # substrate is full-participation, but when
        # ``random_client_number`` caps the per-round cohort the dense
        # program would still train every slot and zero-mask the vote —
        # the gather path trains only the ``s_pad`` gathered slots.  The
        # dense escape hatch (``selection_gather: false``) honors the same
        # per-round selection as 0/1 weight rows, so the two paths train
        # identical trajectories (votes are small-integer sums — exact).
        k = config.algorithm_kwargs.get("random_client_number")
        self._selected_per_round = min(
            int(k) if k is not None else config.worker_number,
            config.worker_number,
        )
        self._selection_active = (
            k is not None and int(k) < config.worker_number
        )
        sg_requested = config.algorithm_kwargs.get("selection_gather")
        self._selection_gather = bool(
            self._selection_active and sg_requested is not False
        )
        if sg_requested and not self._selection_gather:
            get_logger().warning(
                "selection_gather requested but unsupported: full"
                " participation (no random_client_number below"
                " worker_number) — nothing to skip; falling back to the"
                " dense O(population) round path"
            )
        # streamed populations, sign-SGD flavor: same knob and contract
        # as SpmdFedAvgSession.  The per-round rng streams are HOST-built
        # rows indexed by worker id on every path (``host_rngs[idx]``),
        # so cohort-shaped programs are bit-exact by construction.
        store_mode = (
            str(
                config.algorithm_kwargs.get("population_store", "device")
                or "device"
            )
            .strip()
            .lower()
        )
        if store_mode not in ("device", "streamed"):
            raise ValueError(
                "algorithm_kwargs.population_store must be 'device' or"
                f" 'streamed', got {store_mode!r}"
            )
        self._population_streamed = store_mode == "streamed"
        if self._population_streamed:
            # the placed cohort IS the selection — the device-gather twin
            # would gather from stacks that are no longer resident
            self._selection_gather = False
        self.s_pad = (
            client_slots(self._selected_per_round, self.mesh)
            if (self._selection_gather or self._population_streamed)
            else self.n_slots
        )
        # fault tolerance: the availability mask rides the 0/1 vote-weight
        # rows (see SpmdFedAvgSession); the update guard masks non-finite
        # per-step votes (sign-SGD has no round delta to norm-check —
        # votes are ±1 — so the guard here is finiteness + weight hygiene)
        from ..util.faults import FaultPlan

        self._fault_plan = FaultPlan.from_config(config)
        self._min_quorum = int(
            config.algorithm_kwargs.get("min_client_quorum", 0) or 0
        )
        self._update_guard = bool(
            self._fault_plan is not None and self._fault_plan.update_guard
        )
        # buffered aggregation is a round-upload concept; sign-SGD
        # exchanges gradients on every optimizer STEP — reject the knob
        # loudly instead of silently dropping it (config honesty)
        from ..util.buffered import BufferedSettings

        if BufferedSettings.from_config(config) is not None:
            raise ValueError(
                "algorithm_kwargs.aggregation_mode=buffered is unsupported"
                " here: " + str(self._class_buffered_reason())
                + " — drop the knob for this session"
            )
        # per-round weight rows are needed whenever selection OR fault
        # injection varies the cohort round to round; the historical
        # static-weights program (and its unmasked metric sums) is kept
        # bit-exact for the plain full-participation case
        self._per_round_weights = self._selection_active or bool(
            self._fault_plan is not None and self._fault_plan.injection_active
        )

        self._data, self._dataset_sizes, self.n_batches = stack_client_data(
            config, dataset_collection, practitioners, self.n_slots
        )
        self._client_sharding = NamedSharding(self.mesh, P("clients"))
        self._replicated = NamedSharding(self.mesh, P())
        # scan wants batch-major: [n_batches, C, B, ...]

        self._population = None
        self._cohort_data = None
        self._cohort_prefetch = None
        if self._population_streamed:
            # the SLOT-major stacks stay host-resident in the population
            # store; cohort rows are swapped to batch-major at placement
            # (the prefetch thread's fetch hook)
            from ..util.population import CohortPrefetcher, PopulationStore

            self._population = PopulationStore.from_stacked(self._data)
            self._cohort_prefetch = CohortPrefetcher(self._fetch_cohort)
        else:
            self._data = put_sharded(
                {k: np.swapaxes(v, 0, 1) for k, v in self._data.items()},
                NamedSharding(self.mesh, P(None, "clients")),
            )
        self._run_program_fn = None
        self._horizon_fns: dict[int, object] = {}
        self._run_fn = self._build_run_fn()

    def _build_run_fn(self):
        engine = self.engine
        epochs = self.config.epoch
        n_batches = self.n_batches
        hp = engine.hyper_parameter
        momentum = hp.momentum
        schedule = hp.make_schedule(epochs * n_batches)
        # metric masking only when selection is ACTIVE: the
        # full-participation program keeps the historical unmasked sum
        # (padding slots contribute count 0 anyway) so existing
        # trajectories stay bit-identical; under selection, unselected
        # clients must not leak into the recorded train curves (the
        # gather path never trains them at all).  Streamed cohorts mask
        # too: their padding rows DUPLICATE a real client's data (the
        # id-0 padding contract) instead of holding the dense path's
        # zero rows, so only the weight mask keeps the sums identical.
        mask_metrics = self._per_round_weights or self._population_streamed
        guard_active = self._update_guard

        def shard_body(params, data, weights, rngs):
            # data: [n_batches, slots_local, B, ...]; weights/rngs: [slots_local(, 2)]
            velocity = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)

            def batch_body(carry, batch):
                params, velocity, step = carry

                def grad_one(batch_slot, rng):
                    (loss, aux), grads = engine.loss_and_grad(
                        params, batch_slot, jax.random.fold_in(rng, step)
                    )
                    metrics = {
                        "loss_sum": loss * aux["count"],
                        "correct": aux["correct"],
                        "count": aux["count"],
                    }
                    return grads, metrics

                grads, metrics = jax.vmap(grad_one)(batch, rngs)
                vote_weights = weights
                rejected = None
                if guard_active:
                    # update hygiene, sign-SGD flavor: a slot whose step
                    # gradient is non-finite — or whose vote weight
                    # arrived poisoned (corrupt injection) — is masked
                    # out of THIS step's majority vote and counted;
                    # sign(NaN) would otherwise poison the direction for
                    # every client at once
                    finite = jnp.ones(weights.shape, bool)
                    for g in jax.tree.leaves(grads):
                        finite = finite & jnp.all(
                            jnp.isfinite(g).reshape(g.shape[0], -1), axis=1
                        )
                    ok = finite & jnp.isfinite(weights)
                    participating = (weights != 0).astype(jnp.float32)
                    vote_weights = jnp.where(ok, weights, jnp.float32(0.0))
                    rejected = jax.lax.psum(
                        jnp.sum(jnp.where(ok, 0.0, participating)),
                        axis_name="clients",
                    )
                # majority vote: sign of the sum of signs, padding slots
                # masked out (weights ∈ {0, 1})
                total = jax.tree.map(
                    lambda g: jax.lax.psum(
                        jnp.einsum("c,c...->...", vote_weights, jnp.sign(g)),
                        axis_name="clients",
                    ),
                    grads,
                )
                direction = jax.tree.map(jnp.sign, total)
                velocity = jax.tree.map(
                    lambda v, d: momentum * v + d, velocity, direction
                )
                lr = schedule(step)
                params = jax.tree.map(
                    lambda p, v: (p.astype(jnp.float32) - lr * v).astype(p.dtype),
                    params,
                    velocity,
                )
                metrics = jax.tree.map(
                    lambda m: jax.lax.psum(
                        jnp.sum(m * vote_weights, axis=0)
                        if mask_metrics
                        else jnp.sum(m, axis=0),
                        axis_name="clients",
                    ),
                    metrics,
                )
                if rejected is not None:
                    metrics = dict(metrics, rejected_updates=rejected)
                return (params, velocity, step + 1), metrics

            def epoch_body(carry, _):
                carry, metrics = jax.lax.scan(batch_body, carry, data)
                return carry, jax.tree.map(lambda m: jnp.sum(m), metrics)

            (params, _, _), epoch_metrics = jax.lax.scan(
                epoch_body, (params, velocity, jnp.int32(0)), None, length=epochs
            )
            return params, epoch_metrics

        def run_program(params, weights, rngs, data):
            return shard_map_compat(
                shard_body,
                self.mesh,
                in_specs=(P(), P(None, "clients"), P("clients"), P("clients")),
                out_specs=(P(), P()),
            )(params, data, weights, rngs)

        self._run_program_fn = run_program
        # data as an argument, not a closure constant (see _build_round_fn)
        jitted = jax.jit(run_program, donate_argnums=(0,))
        # bench/shardcheck introspection handle (pre-dispatch)
        self._jitted_run_fn = jitted

        self._gather_program_fn = None
        self._jitted_gather_run_fn = None
        # the gather twin also backs the STREAMED horizon: its take() uses
        # a fixed batch-major sharding constant (no trace-time read of the
        # stored stacks' .sharding), so it is safe to build while the
        # population lives on host — the horizon body gathers each round's
        # cohort out of the placed union stack by POSITION rows
        if self._selection_gather or self._population_streamed:
            batch_major_sharding = NamedSharding(self.mesh, P(None, "clients"))

            def gather_run_program(params, weights, rngs, sel_idx, data):
                """The SAME run program over the gathered ``[s_pad]``
                cohort: device-side ``jnp.take`` along the (batch-major)
                slot axis, then the identical shard_map body."""

                def take(x):
                    return jax.lax.with_sharding_constraint(
                        jnp.take(x, sel_idx, axis=1), batch_major_sharding
                    )

                return run_program(
                    params, weights, rngs, jax.tree.map(take, data)
                )

            self._gather_program_fn = gather_run_program
            self._jitted_gather_run_fn = jax.jit(
                gather_run_program, donate_argnums=(0,)
            )

        def fn(params, weights, rngs, sel_idx=None):
            if sel_idx is not None:
                return self._trace.dispatch(
                    "run[gather]",
                    self._jitted_gather_run_fn,
                    (params, weights, rngs, sel_idx, self._data),
                    sig_args=(weights, rngs, sel_idx),
                )
            if self._population_streamed:
                # the SAME dense program, shape-specialized once at the
                # cohort width: slots_local comes off the placed cohort,
                # so every round hits one jit signature (zero retraces)
                return self._trace.dispatch(
                    "run[streamed]",
                    jitted,
                    (params, weights, rngs, self._cohort_data),
                    sig_args=(weights, rngs),
                )
            return self._trace.dispatch(
                "run[dense]",
                jitted,
                (params, weights, rngs, self._data),
                sig_args=(weights, rngs),
            )

        return fn

    def _build_horizon_fn(self, horizon: int):
        """``horizon`` sign-SGD rounds as one jitted, donated scan — the
        per-round rngs ride as ``[H, n_slots, 2]`` scan inputs (each
        round's stream is ``PRNGKey(seed + round)``, no carry chain), and
        each round evaluates in-program on the device-resident test set."""
        engine = self.engine
        run_program = self._run_program_fn
        gather_program = self._gather_program_fn
        # the streamed horizon rides the GATHER program shape: ``data`` is
        # the placed union-of-cohorts stack and ``idx_rows`` are per-round
        # POSITION rows into it (``union_cohort``); rng rows stay
        # host-built by worker id, so trajectories match the dense path
        use_gather = self._selection_gather or self._population_streamed
        per_round_weights = self._per_round_weights
        with_confusion = bool(self.config.use_slow_performance_metrics)

        def horizon_program(params, rng_rows, weights, idx_rows, data, eval_batches):
            # scanned per-round inputs: always the rng rows; under active
            # selection also the 0/1 weight rows; under gather also the
            # [H, s_pad] id rows (the body gathers the round's cohort)
            def body(params, xs):
                if use_gather:
                    rngs, round_weights, sel_idx = xs
                    params, epoch_metrics = gather_program(
                        params, round_weights, rngs, sel_idx, data
                    )
                elif per_round_weights:
                    rngs, round_weights = xs
                    params, epoch_metrics = run_program(
                        params, round_weights, rngs, data
                    )
                else:
                    rngs = xs
                    params, epoch_metrics = run_program(
                        params, weights, rngs, data
                    )
                outs = (epoch_metrics, engine.eval_fn(params, eval_batches))
                if with_confusion:
                    outs = outs + (engine.confusion_fn(params, eval_batches),)
                return params, outs

            if use_gather:
                xs = (rng_rows, weights, idx_rows)
            elif per_round_weights:
                xs = (rng_rows, weights)
            else:
                xs = rng_rows
            return jax.lax.scan(body, params, xs, length=horizon)

        jitted = jax.jit(horizon_program, donate_argnums=(0,))

        def fn(params, rng_rows, weights, eval_batches, idx_rows=None):
            if self._population_streamed:
                return self._trace.dispatch(
                    f"horizon[streamed,h={horizon}]",
                    jitted,
                    (
                        params,
                        rng_rows,
                        weights,
                        idx_rows,
                        self._cohort_data,
                        eval_batches,
                    ),
                    sig_args=(rng_rows, idx_rows),
                )
            return self._trace.dispatch(
                f"horizon[h={horizon}]",
                jitted,
                (params, rng_rows, weights, idx_rows, self._data, eval_batches),
                sig_args=(rng_rows, idx_rows),
            )

        fn._jitted = jitted
        return fn

    def _round_weights(self, round_number: int) -> np.ndarray:
        """[n_slots] 0/1 participation weights for the DENSE program: real
        workers, intersected with the round's selection when
        ``random_client_number`` is active."""
        from ..util.faults import apply_fault_plan

        base = (self._dataset_sizes > 0).astype(np.float32)
        if self._selection_active:
            from ..utils.selection import select_workers

            selected = select_workers(
                self.config.seed,
                round_number,
                self.config.worker_number,
                self.config.algorithm_kwargs.get("random_client_number"),
            )
            mask = np.zeros(self.n_slots, np.float32)
            mask[sorted(selected)] = 1.0
            base = base * mask
        return apply_fault_plan(
            self._fault_plan,
            self._min_quorum,
            round_number,
            None,
            base,
            self.config.worker_number,
        )

    def _select_indices(
        self, round_number: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Gather-path selection: ascending selected worker ids padded to
        ``s_pad`` (id 0 at weight 0), plus their 0/1 vote weights."""
        from ..utils.selection import select_workers

        selected = sorted(
            select_workers(
                self.config.seed,
                round_number,
                self.config.worker_number,
                self.config.algorithm_kwargs.get("random_client_number"),
            )
        )
        idx = np.zeros(self.s_pad, np.int32)
        idx[: len(selected)] = selected
        weights = np.zeros(self.s_pad, np.float32)
        weights[: len(selected)] = (
            self._dataset_sizes[selected] > 0
        ).astype(np.float32)
        from ..util.faults import apply_fault_plan

        weights = apply_fault_plan(
            self._fault_plan,
            self._min_quorum,
            round_number,
            idx,
            weights,
            self.config.worker_number,
        )
        return idx, weights

    # ------------------------------------------- streamed-population path
    def _cohort_ids(self, round_number: int) -> np.ndarray:
        """The round's ``[s_pad]`` cohort ids WITHOUT the fault fold —
        faults zero vote WEIGHTS, never which rows are fetched, so the
        prefetcher can compute round r+1's ids ahead of time (see
        :meth:`SpmdFedAvgSession._cohort_ids`).  ``select_workers``
        returns every worker when selection is inactive, so full
        participation streams too."""
        from ..utils.selection import select_workers

        selected = sorted(
            select_workers(
                self.config.seed,
                round_number,
                self.config.worker_number,
                self.config.algorithm_kwargs.get("random_client_number"),
            )
        )
        idx = np.zeros(self.s_pad, np.int32)
        idx[: len(selected)] = selected
        return idx

    def _fetch_cohort(self, ids):
        """Prefetch-thread hook: host slot-major rows → batch-major device
        placement (the swap the dense path did once at init now happens
        per cohort, on the prefetch thread, off the round's critical
        path)."""
        host = self._population.fetch(ids)
        nbytes = sum(x.nbytes for x in jax.tree.leaves(host))
        placed = put_sharded(
            {k: np.swapaxes(v, 0, 1) for k, v in host.items()},
            NamedSharding(self.mesh, P(None, "clients")),
        )
        return placed, nbytes

    def _take_cohort(self, round_number: int, ids: np.ndarray) -> None:
        """See :meth:`SpmdFedAvgSession._take_cohort` — broadcast/assert
        the host-built ids across processes, block on the double buffer,
        record the ``prefetch`` span with its exposed wall."""
        from .mesh import broadcast_selection_rows

        ids = broadcast_selection_rows(np.asarray(ids))
        self._cohort_data, stats = self._cohort_prefetch.take(
            round_number, ids
        )
        if self._trace.enabled:
            fields = {
                "round": int(round_number),
                "exposed": round(stats.exposed, 6),
                "bytes": int(stats.nbytes),
            }
            if not stats.prefetched:
                fields["warmup"] = True
            self._trace.span_record("prefetch", stats.seconds, **fields)

    def _schedule_next_cohort(self, round_number: int) -> None:
        if round_number > self.config.round:
            return
        self._cohort_prefetch.schedule(
            round_number, self._cohort_ids(round_number)
        )

    def _schedule_next_horizon_cohort(self, start_round: int) -> None:
        """Queue the next fused chunk's union-of-cohorts fetch behind the
        current chunk's scan (same union rule as the take site, so the
        prefetched ids always match)."""
        if start_round > self.config.round:
            return
        from ..util.population import union_cohort

        h = min(self.round_horizon, self.config.round - start_round + 1)
        id_rows = np.stack(
            [
                self._cohort_ids(r)
                for r in range(start_round, start_round + h)
            ]
        )
        ids_u, _pos = union_cohort(id_rows, h * self.s_pad)
        self._cohort_prefetch.schedule(start_round, ids_u)

    @property
    def wasted_compute_fraction(self) -> float:
        """See :meth:`SpmdFedAvgSession.wasted_compute_fraction`."""
        trained = (
            self.s_pad
            if (self._selection_gather or self._population_streamed)
            else self.n_slots
        )
        return 1.0 - self._selected_per_round / max(trained, 1)

    # ------------------------------------------------- shardcheck hooks
    @classmethod
    def capability_gates(cls) -> dict[str, str | None]:
        """Sign-SGD supports all three fused-round knobs (the guard is
        the per-step vote-hygiene flavor) but not buffered aggregation —
        see :meth:`SpmdFedAvgSession.capability_gates`."""
        return {
            "round_horizon": None,
            "selection_gather": None,
            "update_guard": None,
            "aggregation_mode": cls._class_buffered_reason(),
            "population_store": None,
        }

    @classmethod
    def _class_buffered_reason(cls) -> str | None:
        """Sign-SGD's exchange is per optimizer STEP (a psum inside the
        scanned step body) — there is no round-level upload for a buffer
        flush to hold back."""
        return (
            "buffered aggregation (aggregation_mode: buffered) applies to"
            " round-level uploads; sign_SGD exchanges sign votes on every"
            " optimizer step and has no round upload to buffer"
        )

    def shardcheck_shardings(self):
        """See :meth:`SpmdFedAvgSession.shardcheck_shardings`."""
        from .introspect import DeclaredSpec, named_sharding_decls

        decls = [
            DeclaredSpec(
                "client_slots", self.mesh, self._client_sharding.spec
            )
        ]
        decls += named_sharding_decls("data", self._data)
        return decls

    def shardcheck_programs(self):
        """See :meth:`SpmdFedAvgSession.shardcheck_programs` — the
        sign-SGD whole-run program plus its gather twin and the fused
        horizon, described abstractly."""
        from ..engine.batching import make_epoch_batches
        from .introspect import (
            ProgramSpec,
            abstract_tree,
            host_abstract,
            key_abstract,
        )

        template = jax.eval_shape(
            lambda: self.engine.init_params(self.config.seed)
        )
        params = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=self._replicated
            ),
            template,
        )
        if self._population_streamed:
            # streamed: the stored stacks are HOST slot-major
            # [n_slots, n_batches, ...] numpy — the programs see
            # batch-major cohort-shaped placements instead
            batch_major = NamedSharding(self.mesh, P(None, "clients"))

            def cohort_abstract(leading):
                return {
                    k: jax.ShapeDtypeStruct(
                        (v.shape[1], leading) + tuple(v.shape[2:]),
                        v.dtype,
                        sharding=batch_major,
                    )
                    for k, v in self._data.items()
                }

            data = None
        else:
            data = abstract_tree(self._data)
        dense_weights = host_abstract(
            (self._dataset_sizes > 0).astype(np.float32),
            self._client_sharding,
        )

        def run_args(round_number):
            if self._population_streamed:
                _idx, weights = self._select_indices(round_number)
                return (
                    params,
                    host_abstract(weights, self._client_sharding),
                    key_abstract(self._client_sharding, (self.s_pad,)),
                    cohort_abstract(self.s_pad),
                )
            if self._selection_gather:
                idx, weights = self._select_indices(round_number)
                return (
                    params,
                    host_abstract(weights, self._client_sharding),
                    key_abstract(self._client_sharding, (self.s_pad,)),
                    host_abstract(idx, self._client_sharding),
                    data,
                )
            if self._per_round_weights:
                weights = host_abstract(
                    self._round_weights(round_number),
                    self._client_sharding,
                )
            else:
                weights = dense_weights
            return (
                params,
                weights,
                key_abstract(self._client_sharding, (self.n_slots,)),
                data,
            )

        specs = [
            ProgramSpec(
                name=(
                    "run[streamed]"
                    if self._population_streamed
                    else "run[gather]"
                    if self._selection_gather
                    else "run[dense]"
                ),
                jitted=(
                    self._jitted_gather_run_fn
                    if self._selection_gather
                    else self._jitted_run_fn
                ),
                args=run_args(1),
                alt_args=(run_args(2),),
                donate_argnums=(0,),
                mesh=self.mesh,
                carries=((0, lambda out: out[0]),),
            )
        ]
        h = max(2, min(self.round_horizon, 4))
        fn = self._horizon_fns.get(h)
        if fn is None:
            fn = self._horizon_fns[h] = self._build_horizon_fn(h)
        test = self.dc.get_dataset(Phase.Test)
        eval_batches = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                np.asarray(x).shape,
                np.asarray(x).dtype,
                sharding=self._replicated,
            ),
            make_epoch_batches(test, self.config.batch_size),
        )
        rng_sharding = NamedSharding(self.mesh, P(None, "clients"))

        def horizon_args(start_round):
            rounds = range(start_round, start_round + h)
            if self._population_streamed:
                from ..util.population import union_cohort

                pairs = [self._select_indices(r) for r in rounds]
                _ids_u, pos_rows = union_cohort(
                    np.stack([i for i, _w in pairs]), h * self.s_pad
                )
                return (
                    params,
                    key_abstract(rng_sharding, (h, self.s_pad)),
                    host_abstract(
                        np.stack([w for _i, w in pairs]), rng_sharding
                    ),
                    host_abstract(pos_rows, rng_sharding),
                    cohort_abstract(h * self.s_pad),
                    eval_batches,
                )
            if self._selection_gather:
                pairs = [self._select_indices(r) for r in rounds]
                idx_rows = host_abstract(
                    np.stack([i for i, _w in pairs]), rng_sharding
                )
                weight_arg = host_abstract(
                    np.stack([w for _i, w in pairs]), rng_sharding
                )
                slots = self.s_pad
            elif self._per_round_weights:
                idx_rows = None
                weight_arg = host_abstract(
                    np.stack([self._round_weights(r) for r in rounds]),
                    rng_sharding,
                )
                slots = self.n_slots
            else:
                idx_rows = None
                weight_arg = dense_weights
                slots = self.n_slots
            return (
                params,
                key_abstract(rng_sharding, (h, slots)),
                weight_arg,
                idx_rows,
                data,
                eval_batches,
            )

        specs.append(
            ProgramSpec(
                name=(
                    f"horizon[streamed,h={h}]"
                    if self._population_streamed
                    else f"horizon[h={h}]"
                ),
                jitted=fn._jitted,
                args=horizon_args(1),
                alt_args=(horizon_args(1 + h),),
                donate_argnums=(0,),
                mesh=self.mesh,
                carries=((0, lambda out: out[0]),),
                scanned_len=h,
                stacked_out=lambda out: out[1],
            )
        )
        return specs

    def _note_round(
        self, round_number: int, metric, epoch_metrics, round_seconds=0.0
    ) -> None:
        """One round's stat row (identical surface on the per-round and
        horizon-fused paths: test metrics + per-epoch train curves)."""
        count = np.maximum(np.asarray(epoch_metrics["count"]), 1.0)
        row = {
            "test_accuracy": metric["accuracy"],
            "test_loss": metric["loss"],
            "test_count": metric["count"],
            "train_loss_per_epoch": (
                np.asarray(epoch_metrics["loss_sum"]) / count
            ).tolist(),
            "train_accuracy_per_epoch": (
                np.asarray(epoch_metrics["correct"]) / count
            ).tolist(),
        }
        for key, value in metric.items():  # slow-metric extras
            if key not in ("accuracy", "loss", "count"):
                row[f"test_{key}"] = value
        if "rejected_updates" in epoch_metrics:
            # vote-guard rejections (non-finite grads / poisoned weights),
            # summed over the round's steps
            row["rejected_updates"] = float(
                np.asarray(epoch_metrics["rejected_updates"]).sum()
            )
        self._trace_fault_event(round_number, row.get("rejected_updates", 0))
        if self._trace.enabled:
            span_fields = {
                "round": round_number,
                "accuracy": metric["accuracy"],
                "loss": metric["loss"],
            }
            if "rejected_updates" in row:
                span_fields["rejected_updates"] = row["rejected_updates"]
            row["trace_offset"] = self._trace.span_record(
                "round", round_seconds, **span_fields
            )
        self._stat[round_number] = row
        get_logger().info(
            "round: %d, sign_SGD (spmd) %d steps, test accuracy %.4f loss %.4f",
            round_number,
            self.config.epoch * self.n_batches,
            metric["accuracy"],
            metric["loss"],
        )

    def _run_setup(self):
        """(params, weights, eval batches, server dir) shared by both run
        loops — put_sharded throughout: multi-host pods need per-process
        shard placement (see _place_params in SpmdFedAvgSession)."""
        config = self.config
        params = put_sharded(
            self.engine.init_params(config.seed), self._replicated
        )
        weights = put_sharded(
            (self._dataset_sizes > 0).astype(np.float32), self._client_sharding
        )
        save_dir = os.path.join(config.save_dir, "server")
        os.makedirs(save_dir, exist_ok=True)
        from ..engine.batching import make_epoch_batches

        test = self.dc.get_dataset(Phase.Test)
        # device-resident once, not re-uploaded per round
        batches = put_sharded(
            make_epoch_batches(test, config.batch_size), self._replicated
        )
        return params, weights, batches, save_dir

    def run(self) -> dict:
        if self.round_horizon > 1:
            return self._run_horizon()
        import time as _time

        config = self.config
        params, weights, batches, save_dir = self._run_setup()
        best_acc = -1.0
        for round_number in range(1, config.round + 1):
            round_start = _time.monotonic()
            self._trace.maybe_profile_start(round_number)
            # same per-round streams on every path: split(PRNGKey(seed +
            # round), n_slots) indexed by worker id — the gather path takes
            # the selected rows of the identical host split
            host_rngs = np.asarray(
                jax.random.split(
                    jax.random.PRNGKey(config.seed + round_number), self.n_slots
                )
            )
            if self._population_streamed:
                # the placed cohort IS the selection: dense program at the
                # cohort width, rngs/weights the selected rows of the same
                # host-built tables the dense path would use (bit-exact)
                host_idx, host_w = self._select_indices(round_number)
                self._take_cohort(round_number, host_idx)
                self._schedule_next_cohort(round_number + 1)
                sel_idx = None
                round_weights = put_sharded(host_w, self._client_sharding)
                rngs = put_sharded(host_rngs[host_idx], self._client_sharding)
            elif self._selection_gather:
                host_idx, host_w = self._select_indices(round_number)
                sel_idx = put_sharded(host_idx, self._client_sharding)
                round_weights = put_sharded(host_w, self._client_sharding)
                rngs = put_sharded(host_rngs[host_idx], self._client_sharding)
            elif self._per_round_weights:
                sel_idx = None
                round_weights = put_sharded(
                    self._round_weights(round_number), self._client_sharding
                )
                rngs = put_sharded(host_rngs, self._client_sharding)
            else:
                sel_idx = None
                round_weights = weights
                rngs = put_sharded(host_rngs, self._client_sharding)
            params, epoch_metrics = self._watchdog.call(
                lambda p=params, w=round_weights, r=rngs, i=sel_idx: (
                    self._run_fn(p, w, r, i)
                ),
                phase="round",
                round_number=round_number,
            )
            self._trace.event("dispatch", program="run", round=round_number)

            def guarded_eval(p=params):
                metric = summarize_metrics(self.engine.evaluate(p, batches))
                metric.update(
                    maybe_slow_metrics(self.config, self.engine, p, batches)
                )
                return metric

            with self._trace.span("eval", round=round_number):
                metric = self._watchdog.call(
                    guarded_eval, phase="eval", round_number=round_number
                )
            self._trace.event("dispatch", program="eval", round=round_number)
            self._trace.event("host_sync", round=round_number)
            self._trace.hbm_watermark(round_number)
            self._trace.count("rounds")
            self._note_round(
                round_number,
                metric,
                epoch_metrics,
                round_seconds=_time.monotonic() - round_start,
            )
            # this session has no AsyncCheckpointWriter exit finalizer to
            # flush the trace tail on an abort — land each round's
            # records with the (already per-round, synchronous) record
            # write so a mid-run exception loses at most one round, and
            # land them FIRST so durable rows never cross-link
            # trace_offsets a resumed recorder would renumber
            self._trace.flush()
            atomic_json_dump(
                os.path.join(save_dir, "round_record.json"), self._stat
            )
            self._trace.maybe_profile_stop(round_number)
            if metric["accuracy"] > best_acc:
                best_acc = metric["accuracy"]
                np.savez(
                    os.path.join(save_dir, "best_global_model.npz"),
                    **{k: np.asarray(v) for k, v in params.items()},
                )
            # sign_SGD writes no round checkpoints, so a killed run
            # restarts from round 1 under train_with_recovery (documented
            # in docs/migrating.md); the kill still fires after the record
            # lands so the chaos suite can observe completed rounds
            if self._fault_plan is not None:
                self._fault_plan.maybe_kill(round_number)
        if self._cohort_prefetch is not None:
            self._cohort_prefetch.close()
        self._trace.close()
        return {"performance": self._stat}

    def _run_horizon(self) -> dict:
        """The fused run loop: H sign-SGD rounds per dispatch with
        in-program evaluation; the record lands once per horizon (atomic),
        and best_global_model.npz tracks the best HORIZON-BOUNDARY round
        (only boundary params are ever materialized on host)."""
        import time as _time

        config = self.config
        params, weights, batches, save_dir = self._run_setup()
        rng_sharding = NamedSharding(self.mesh, P(None, "clients"))
        record_path = os.path.join(save_dir, "round_record.json")
        # best-boundary high-water mark, independent of mid-horizon rounds
        # (only boundary params materialize, so only they can be saved —
        # a better in-horizon round must not starve later saves)
        best_saved_acc = -1.0
        round_number = 1
        while round_number <= config.round:
            h = min(self.round_horizon, config.round - round_number + 1)
            fn = self._horizon_fns.get(h)
            if fn is None:
                fn = self._horizon_fns[h] = self._build_horizon_fn(h)
            boundary = round_number + h - 1
            self._trace.maybe_profile_start(round_number, boundary)
            # same per-round streams as H=1: PRNGKey(seed + round), split
            # to slots — stacked into [H, n_slots, 2] scan rows (gather:
            # the selected rows of the identical splits, [H, s_pad, 2])
            rounds = range(round_number, round_number + h)
            host_rng_rows = [
                np.asarray(
                    jax.random.split(
                        jax.random.PRNGKey(config.seed + r), self.n_slots
                    )
                )
                for r in rounds
            ]
            idx_rows = None
            weight_arg = weights
            if self._population_streamed:
                # union-of-cohorts chunk: one fetch+place per h rounds,
                # per-round POSITION rows gather each round's slots out
                # of the placed union (the cohort-union rule); rngs are
                # the worker-ID rows of the same host splits as dense
                from ..util.population import union_cohort

                pairs = [self._select_indices(r) for r in rounds]
                id_rows = np.stack([i for i, _w in pairs])
                ids_u, pos_rows = union_cohort(id_rows, h * self.s_pad)
                self._take_cohort(round_number, ids_u)
                self._schedule_next_horizon_cohort(round_number + h)
                host_rng_rows = [
                    row[idx] for row, (idx, _w) in zip(host_rng_rows, pairs)
                ]
                idx_rows = put_sharded(pos_rows, rng_sharding)
                weight_arg = put_sharded(
                    np.stack([w for _i, w in pairs]), rng_sharding
                )
            elif self._selection_gather:
                pairs = [self._select_indices(r) for r in rounds]
                host_rng_rows = [
                    row[idx] for row, (idx, _w) in zip(host_rng_rows, pairs)
                ]
                idx_rows = put_sharded(
                    np.stack([i for i, _w in pairs]), rng_sharding
                )
                weight_arg = put_sharded(
                    np.stack([w for _i, w in pairs]), rng_sharding
                )
            elif self._per_round_weights:
                weight_arg = put_sharded(
                    np.stack([self._round_weights(r) for r in rounds]),
                    rng_sharding,
                )
            rng_rows = put_sharded(np.stack(host_rng_rows), rng_sharding)
            chunk_start = _time.monotonic()
            params, outs = self._watchdog.call(
                lambda p=params, rr=rng_rows, w=weight_arg, i=idx_rows: fn(
                    p, rr, w, batches, i
                ),
                phase="round",
                round_number=boundary,
            )
            self._trace.event(
                "dispatch", program=f"horizon[h={h}]", round=boundary, rounds=h
            )
            epoch_metrics = jax.tree.map(np.asarray, outs[0])  # [h, epochs]
            per_round = stacked_round_metrics(outs[1])
            confusion = np.asarray(outs[2]) if len(outs) > 2 else None
            self._trace.event("host_sync", round=boundary)
            self._trace.hbm_watermark(boundary)
            chunk_seconds = _time.monotonic() - chunk_start
            self._trace.span_record(
                "horizon",
                chunk_seconds,
                first_round=round_number,
                last_round=boundary,
                rounds=h,
            )
            self._trace.count("rounds", h)
            for i in range(h):
                metric = per_round[i]
                if confusion is not None:
                    metric.update(slow_metrics_from_confusion(confusion[i]))
                self._note_round(
                    round_number + i,
                    metric,
                    {k: v[i] for k, v in epoch_metrics.items()},
                    # in-chunk rounds don't materialize individually; the
                    # chunk's amortized share matches the FedAvg fused rows
                    round_seconds=chunk_seconds / h,
                )
            # see run(): no exit finalizer here, and the trace lands
            # before the rows that cross-link it
            self._trace.flush()
            atomic_json_dump(record_path, self._stat)
            if per_round[-1]["accuracy"] > best_saved_acc:
                best_saved_acc = per_round[-1]["accuracy"]
                np.savez(
                    os.path.join(save_dir, "best_global_model.npz"),
                    **{k: np.asarray(v) for k, v in params.items()},
                )
            self._trace.maybe_profile_stop(boundary)
            if self._fault_plan is not None:
                for r in range(round_number, boundary + 1):
                    self._fault_plan.maybe_kill(r)
            round_number += h
        if self._cohort_prefetch is not None:
            self._cohort_prefetch.close()
        self._trace.close()
        return {"performance": self._stat}

    @property
    def performance_stat(self) -> dict:
        return self._stat
