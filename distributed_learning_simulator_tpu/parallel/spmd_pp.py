"""FedAvg rounds with pipeline-parallel clients as one SPMD program.

Round-4's pipeline parallelism ran only on the threaded executor (the
MODEL owned a ``pp`` mesh via its own ``shard_map`` — ``models/text.py``);
this session brings ``model_kwargs.pipeline_stages`` to the TPU-first
SPMD path the way ``spmd_sp.py`` did for sequence parallelism (VERDICT
r4 item 2): the SESSION owns an ``("pp",)`` mesh and the one
``shard_map``; each client's model runs in ``pp_axis`` mode (GPipe
schedule by axis name over its LOCAL stage slice —
``parallel/pipeline.py``), and clients scan through the trunk inside one
round program with on-device weighted aggregation.

Gradient correctness (the part that is genuinely different from SP):
inside the session's shard_map the engine differentiates ONE device's
loss.  Stage-sharded trunk leaves arrive as local slices — their
gradients are local and must NOT be cross-device reduced — while
replicated leaves (embed, head, ...) get PARTIAL per-device
contributions (the reverse-ppermute schedule routes each cotangent to
the stage that produced it).  ``pipeline_body``'s ``symmetric_out``
(``psum_symmetric``, ``parallel/collectives.py``) multiplies every
upstream cotangent by S, after which ONE per-leaf rule is exact:

* replicated leaf:  ``pmean_d(S · partial_d) = sum_d partial_d``  ✓
  (downstream-of-the-psum leaves are full on every device and pmean is
  the identity on them);
* trunk (pp-sharded) leaf: local gradient is ``S · true`` → divide by
  S locally, no collective.

The engine applies this via ``grad_sync_fn`` (``engine/engine.py``).

Inherited unchanged from ``SpmdFedAvgSession``: run loop, selection,
round records, checkpoints, watchdog, resume, and the client-axis rng
contract (equivalence with ``pipeline_stages=1`` on the client-axis
session is pinned by ``tests/test_pipeline_config.py``).
"""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..engine.engine import ComputeEngine
from .spmd import (
    SpmdFedAvgSession,
    scan_weighted_clients,
    shard_map_compat,
    whole_mesh_session_shapes,
)
from .spmd_sp import SingleDeviceEvalMixin


class SpmdPipelineSession(SingleDeviceEvalMixin, SpmdFedAvgSession):
    #: whole-mesh layout routed through the shared fused-round machinery:
    #: selection gather, round-horizon fusion and the update guard all
    #: apply (spmd.py::_wrap_round_programs)
    _whole_mesh_fused = True

    def __init__(
        self,
        config,
        dataset_collection,
        model_ctx,
        engine: ComputeEngine,
        practitioners,
        pipeline_stages: int,
        pipeline_microbatches: int = 0,
    ) -> None:
        devices = jax.devices()
        if pipeline_stages > len(devices):
            raise ValueError(
                f"pipeline_stages={pipeline_stages} exceeds the "
                f"{len(devices)}-device mesh"
            )
        pp_mesh = Mesh(
            np.asarray(devices[:pipeline_stages]), axis_names=("pp",)
        )
        self._pp_stages = pipeline_stages
        # the pp-mode twin: same factory, same parameter structure
        # (stacked trunk), forward written for the session's axis
        from ..models import create_model_context

        kwargs = dict(getattr(config, "model_kwargs", {}) or {})
        kwargs.pop("pp_mesh", None)
        kwargs["pipeline_stages"] = pipeline_stages
        if pipeline_microbatches:
            kwargs["pipeline_microbatches"] = pipeline_microbatches
        kwargs["pp_axis"] = "pp"
        pp_model_ctx = create_model_context(
            config.model_name, dataset_collection, **kwargs
        )
        pp_model_ctx.compute_dtype = model_ctx.compute_dtype

        stages = float(pipeline_stages)

        def grad_sync(grads):
            # sharded-vs-replicated must be decided from the GLOBAL layout
            # (self._param_specs, template shapes) — inside the shard_map
            # the trunk gradients are local slices whose leading dim is
            # lps, which _leaf_spec would misclassify as replicated
            return {
                k: g / stages
                if self._param_specs[k] != P()
                else jax.lax.pmean(g, "pp")
                for k, g in grads.items()
            }

        self._pp_engine = ComputeEngine(
            pp_model_ctx,
            engine.hyper_parameter,
            total_steps=engine.total_steps,
            grad_sync_fn=grad_sync,
        )
        super().__init__(
            config, dataset_collection, model_ctx, engine, practitioners,
            mesh=pp_mesh,
        )

    def _leaf_spec(self, shape, name: str = "") -> P:
        """The stacked trunk's leading layer axis shards over pp (each
        device gets its stage's contiguous layers); everything else
        (embed, positional, head) is replicated."""
        if (
            name.startswith("trunk")
            and shape
            and shape[0] % self._pp_stages == 0
        ):
            return P("pp")
        return P()

    def _build_round_fn(self):
        engine = self._pp_engine
        epochs = self.config.epoch
        mesh = self.mesh
        _, metrics_shape = whole_mesh_session_shapes(self)
        param_specs = self._param_specs
        # update-guard support (the last cell of the guard matrix): inside
        # this shard_map the trunk params are per-STAGE local slices, so
        # the per-client hygiene check guards each stage's OWN slice and
        # all-reduces the verdict along ``pp`` (psum of slice non-finite
        # counts + slice norm contributions; replicated leaves counted
        # once) — every stage derives the identical effective weight, the
        # consistency the old carve-out lacked (guard_client_update's
        # cross-stage flavor).
        guard_sharded = {
            k: spec != P() for k, spec in param_specs.items()
        }

        def round_program(global_params, weights, rngs, data, val):
            def shard_body(global_params, data, val, weights, rngs):
                # trunk leaves here are LOCAL stage slices; data/weights/
                # rngs replicated (every stage sees the full batch — the
                # schedule's stage-0 select feeds it into the pipe)
                return scan_weighted_clients(
                    engine, epochs, global_params, data, weights, rngs,
                    metrics_shape, val_data=val if val else None,
                    guard_active=self._update_guard,
                    max_update_norm=self._max_update_norm,
                    guard_sharded=guard_sharded,
                    guard_reduce_axis="pp",
                    compute_dtype=self._resident_dtype,
                )

            return shard_map_compat(
                shard_body,
                mesh,
                in_specs=(param_specs, P(), P(), P(), P()),
                out_specs=(param_specs, P()),
            )(global_params, data, val, weights, rngs)

        # gather twin + horizon fusion + dispatch come from the shared
        # machinery; the trunk's stored P("pp") layout rides the horizon
        # carry's out_shardings pin
        return self._wrap_round_programs(round_program)


def build_pipeline_session(ctx, session_args, session_kwargs):
    config = ctx.config
    model_kwargs = dict(config.model_kwargs)
    return SpmdPipelineSession(
        *session_args,
        pipeline_stages=int(model_kwargs.get("pipeline_stages", 0)),
        pipeline_microbatches=int(
            model_kwargs.get("pipeline_microbatches", 0)
        ),
    )
