from .base import (
    DatasetCollectionSampler,
    IIDSampler,
    RandomLabelIIDSplit,
    get_dataset_collection_sampler,
    global_sampler_factory,
)

__all__ = [
    "DatasetCollectionSampler",
    "IIDSampler",
    "RandomLabelIIDSplit",
    "get_dataset_collection_sampler",
    "global_sampler_factory",
]
