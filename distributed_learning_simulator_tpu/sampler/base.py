"""Dataset partitioning over federated participants.

TPU-native equivalent of the reference's sampler layer
(``simulation_lib/sampler/base.py:9-46`` + the toolbox
``get_dataset_collection_sampler``/``global_sampler_factory`` surface).
A sampler assigns each of ``part_number`` participants an index set per
phase; partitions are deterministic in the config seed.
"""

from collections.abc import Callable

import numpy as np

from ..data.collection import DatasetCollection
from ..ml_type import MachineLearningPhase as Phase

global_sampler_factory: dict[str, Callable[..., "DatasetCollectionSampler"]] = {}


def register_sampler(name: str):
    def deco(cls):
        global_sampler_factory[name.lower()] = cls
        return cls

    return deco


class DatasetCollectionSampler:
    """Base: computes per-part index arrays for every phase once."""

    def __init__(
        self,
        dataset_collection: DatasetCollection,
        part_number: int,
        seed: int = 0,
        **kwargs,
    ) -> None:
        self.dataset_collection = dataset_collection
        self.part_number = part_number
        self.seed = seed
        self._parts: dict[int, dict[Phase, np.ndarray]] = {
            i: {} for i in range(part_number)
        }
        if dataset_collection.dataset_type == "graph":
            # one label-stratified NODE partition shared by every phase, so a
            # worker owns a consistent subgraph (per-phase masks intersect at
            # subset time)
            dataset = next(iter(dataset_collection.datasets.values()))
            split = self._split_indices(
                np.arange(len(dataset.targets)), dataset.targets, Phase.Training
            )
            for i, idx in enumerate(split):
                for phase in dataset_collection.datasets:
                    self._parts[i][phase] = np.sort(idx)
            return
        for phase in list(dataset_collection.datasets):
            dataset = dataset_collection.get_dataset(phase)
            split = self._split_indices(
                np.arange(len(dataset)), dataset.targets, phase
            )
            for i, idx in enumerate(split):
                self._parts[i][phase] = np.sort(idx)

    # subclass hook
    def _split_indices(
        self, indices: np.ndarray, targets: np.ndarray, phase: Phase
    ) -> list[np.ndarray]:
        raise NotImplementedError

    def sample(self, part_id: int) -> dict[Phase, np.ndarray]:
        return self._parts[part_id]

    def sample_dataset(self, part_id: int) -> DatasetCollection:
        return self.dataset_collection.subset(self._parts[part_id])


def _phase_salt(phase: Phase) -> int:
    """Stable per-phase RNG salt (``hash()`` of an enum is PYTHONHASHSEED-
    randomized per process and would break cross-run determinism)."""
    return list(Phase).index(phase) + 1


@register_sampler("iid")
class IIDSampler(DatasetCollectionSampler):
    """Per-class proportional split: each part receives an equal IID share of
    every class (reference default ``dataset_sampling: iid``)."""

    def _split_indices(self, indices, targets, phase):
        # native xorshift permutation: deterministic across platforms AND
        # numpy versions (Generator streams carry no such guarantee)
        from ..native import permute_indices

        parts: list[list[np.ndarray]] = [[] for _ in range(self.part_number)]
        for label in np.unique(targets):
            label_idx = indices[targets == label]
            perm = permute_indices(
                len(label_idx),
                seed=self.seed * 1009 + _phase_salt(phase) * 131 + int(label),
            )
            label_idx = label_idx[perm]
            for i, chunk in enumerate(np.array_split(label_idx, self.part_number)):
                parts[i].append(chunk)
        return [np.concatenate(p) if p else np.array([], dtype=np.int64) for p in parts]


@register_sampler("random_label_iid")
class RandomLabelIIDSplit(DatasetCollectionSampler):
    """Non-IID: each part draws ``sampled_class_number`` random classes (all
    classes covered overall), then per-class IID sharding among the parts that
    hold the class (reference ``simulation_lib/sampler/base.py:9-46``)."""

    def __init__(self, dataset_collection, part_number, sampled_class_number=None, **kwargs):
        num_classes = dataset_collection.num_classes
        if sampled_class_number is None:
            sampled_class_number = max(1, num_classes // 2)
        assert sampled_class_number <= num_classes
        rng = np.random.default_rng(kwargs.get("seed", 0) + 17)
        while True:
            assignment = [
                set(rng.choice(num_classes, size=sampled_class_number, replace=False))
                for _ in range(part_number)
            ]
            covered = set().union(*assignment)
            if len(covered) == num_classes or part_number * sampled_class_number < num_classes:
                break
        self._assignment = assignment
        super().__init__(dataset_collection, part_number, **kwargs)

    def _split_indices(self, indices, targets, phase):
        if phase is not Phase.Training:
            # evaluation phases stay IID so every worker can validate
            rng = np.random.default_rng(self.seed + 23)
            return list(np.array_split(rng.permutation(indices), self.part_number))
        rng = np.random.default_rng(self.seed * 1009 + _phase_salt(phase))
        parts: list[list[np.ndarray]] = [[] for _ in range(self.part_number)]
        for label in np.unique(targets):
            holders = [i for i, classes in enumerate(self._assignment) if label in classes]
            if not holders:
                holders = list(range(self.part_number))
            label_idx = rng.permutation(indices[targets == label])
            for holder, chunk in zip(holders, np.array_split(label_idx, len(holders))):
                parts[holder].append(chunk)
        return [np.concatenate(p) if p else np.array([], dtype=np.int64) for p in parts]


def get_dataset_collection_sampler(
    name: str, dataset_collection: DatasetCollection, part_number: int, **kwargs
) -> DatasetCollectionSampler:
    cls = global_sampler_factory.get(name.lower())
    if cls is None:
        raise KeyError(f"unknown sampler {name!r}; known: {sorted(global_sampler_factory)}")
    return cls(dataset_collection, part_number, **kwargs)
