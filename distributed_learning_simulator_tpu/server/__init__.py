from .server import Server
from .aggregation_server import AggregationServer

__all__ = ["Server", "AggregationServer"]
