"""Round state machine over a pluggable aggregation algorithm.

TPU-native equivalent of
``simulation_lib/server/aggregation_server.py:15-184``: distribute the init
model, gather all workers each round, aggregate, compute the round test
metric, append to ``round_record.json``, keep ``best_global_model``, early
stop on a 5-round plateau, and cache the global model per round.
"""

import os
import time as _time
from typing import Any

import numpy as np

from ..algorithm.aggregation_algorithm import AggregationAlgorithm
from ..message import (
    DeltaParameterMessage,
    Message,
    ParameterMessage,
    ParameterMessageBase,
)
from ..ops.pytree import Params
from ..util.model_cache import ModelCache
from ..utils.logging import get_logger
from .server import Server


class AggregationServer(Server):
    #: whether this server class can run ``aggregation_mode: buffered``
    #: (staleness-weighted buffer flushes) — subclasses that own their own
    #: round/phase progression (FedOBD's driver, Shapley's sampling,
    #: graph servers) opt out and the knob is rejected loudly
    _buffered_capable = True

    def __init__(self, algorithm: AggregationAlgorithm, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._model_cache = ModelCache()
        self._round_number = 1
        self._worker_flag: set[int] = set()
        self.__algorithm = algorithm
        self.__algorithm.set_server(self)
        self.__algorithm.set_config(self.config)
        self.__stat: dict[int, dict] = {}
        self._compute_stat: bool = True
        self.__plateau = 0
        self.__best_acc = 0.0  # best-model bookkeeping
        self.__max_acc = 0.0  # plateau bookkeeping (owned by _convergent)
        self.need_init_performance = False
        self.__early_stop = self.config.algorithm_kwargs.get("early_stop", False)
        # fault tolerance (util/faults.py): quorum floor, per-round fault
        # stat columns, and the scheduled process kills all key off the
        # same plan the SPMD sessions consume
        from ..util.faults import FaultPlan

        self._fault_plan = FaultPlan.from_config(self.config)
        self._min_quorum = int(
            self.config.algorithm_kwargs.get("min_client_quorum", 0) or 0
        )
        # kill deferral bookkeeping: a scheduled kill fires only once the
        # killed round has a SAVED checkpoint, so a resumed run starts
        # past it and the stateless plan never re-fires the same kill
        self._kill_armed_round: int | None = None
        self._last_saved_key = 0
        self.__round_start = _time.monotonic()
        self.__round_start_bytes = (0, 0)
        # roundtrace telemetry (util/telemetry.py): the threaded executor
        # shares the SPMD sessions' trace schema — worker `upload` events,
        # a `round_barrier` span (first upload → all workers in), one
        # `round` span per record row (its JSONL offset cross-linked as
        # the row's trace_offset), and `fault` events.  Everything runs
        # on the server sweep thread over host state it already owns.
        from ..util.telemetry import TraceRecorder

        self._trace = TraceRecorder.from_config(
            self.config, default_dir=self.save_dir
        )
        if not (getattr(self.config, "telemetry", None) or {}).get("flush_every"):
            # the server event loop has no try/finally around its sweep
            # (Server.start runs _server_exit only on the clean path), so
            # an abort mid-round (QuorumLostError, worker crash) would
            # drop a buffered trace entirely.  This executor already
            # writes its record synchronously every round — flush each
            # trace record the same way unless the user chose a cadence
            # (an explicit `flush_every: 0` means "auto" and gets the
            # same eager default, not the recorder's 256-record buffer).
            self._trace.flush_every = 1
        self._upload_window_start: float | None = None
        # ---- buffered-asynchronous aggregation (util/buffered.py) ----
        # ``aggregation_mode: buffered`` removes the round barrier on THIS
        # executor for real: the event loop (greedy sweep, server.py)
        # consumes uploads as they arrive, holds each one keyed by its
        # (worker, origin round), and aggregates a flush as soon as its
        # scheduled cohort is in — a straggler's upload lands in a LATER
        # flush with the staleness discount instead of stalling everyone.
        # Flush membership follows the seeded arrival schedule (not
        # wall-clock races), so runs are deterministic and the SPMD
        # executor can replay the identical schedule bit-for-bit.
        from ..util.buffered import BufferedSettings

        self._buffered = BufferedSettings.from_config(self.config)
        self._buffered_round_stats: dict | None = None
        self._flush_window_start: float | None = None
        if self._buffered is not None:
            from ..util.buffered import threaded_buffered_reason

            reason = None
            if not self._buffered_capable:
                reason = (
                    f"{type(self).__name__} owns its own round/phase"
                    " progression"
                )
            else:
                reason = threaded_buffered_reason(
                    self.config.distributed_algorithm
                )
            if reason is not None:
                raise ValueError(
                    "algorithm_kwargs.aggregation_mode=buffered is"
                    f" unsupported here: {reason} — drop the knob for this"
                    " server"
                )
            from ..util.buffered import (
                compute_arrival_schedule,
                threaded_uploaders,
            )

            self._bsched = compute_arrival_schedule(
                self._buffered,
                self._fault_plan,
                self.worker_number,
                self.config.round,
                threaded_uploaders(self.config),
            )
            self._greedy_sweep = True
            #: (worker, origin) -> (normalized ParameterMessage, its
            #: origin base) — uploads held until their landing flush
            self._held: dict[tuple[int, int], tuple] = {}
            #: items whose upload will never arrive (injected dropout
            #: Nones, demoted/dead workers, unselected-round acks)
            self._cancelled: set[tuple[int, int]] = set()
            #: per-worker next collection round (every message — upload
            #: or None — advances it; endpoint queues are FIFO).  A
            #: resume rebases it (_try_resume): workers jump straight to
            #: the resumed round, so their first upload's origin is the
            #: resume round, not 1.
            self._origin_counter = {
                w: 1 for w in range(self.worker_number)
            }
            #: origins below this are pre-resume: their scheduled flush
            #: items can never arrive and are treated as cancelled
            #: ("resume drains the buffer" — docs/migrating.md)
            self._buffered_origin_floor = 1
            #: round -> host copy of that flush's global params: the
            #: restore base for stale deltas (a round-o upload diffs
            #: against v_{o-1}, NOT the newest global).  Trimmed to the
            #: schedule's staleness window.
            self._param_history: dict[int, Params] = {}

    @property
    def early_stop(self) -> bool:
        return self.__early_stop

    @property
    def algorithm(self) -> AggregationAlgorithm:
        return self.__algorithm

    @property
    def round_number(self) -> int:
        return self._round_number

    def _get_init_model(self) -> Params:
        resumed = self._try_resume()
        if resumed is not None:
            return resumed
        init_path = self.config.algorithm_kwargs.get("global_model_path")
        if init_path:
            with np.load(init_path) as blob:
                return {k: blob[k] for k in blob.files}
        return self.tester.get_parameter_dict()

    def _try_resume(self) -> Params | None:
        """True round resume the reference lacks (SURVEY.md §5: "a killed run
        restarts from round 1"): if ``algorithm_kwargs.resume_dir`` points at
        a previous session, load its latest ``aggregated_model/round_N.npz``
        and continue from round N+1, restoring the round records."""
        resume_dir = self.config.algorithm_kwargs.get("resume_dir")
        if not resume_dir:
            return None
        from ..util.resume import load_resume_state

        resumed_params, stats, last_round = load_resume_state(resume_dir)
        if resumed_params is None:
            get_logger().warning("nothing resumable under %s", resume_dir)
            return None
        self.__stat.update(stats)
        if self.__stat:
            restored_max = max(t["test_accuracy"] for t in self.__stat.values())
            self.__best_acc = restored_max
            self.__max_acc = restored_max
        self._round_number = last_round + 1
        self._last_saved_key = last_round  # kill deferral: already durable
        if self._buffered is not None:
            # buffered resume drains the buffer: workers restart at the
            # resumed round (their init broadcast carries it), so origin
            # counters rebase there and every pre-resume scheduled item
            # is cancelled — a flush must never wait on an upload from
            # before the kill (it can never arrive)
            self._origin_counter = {
                w: self._round_number for w in range(self.worker_number)
            }
            self._buffered_origin_floor = self._round_number
        get_logger().info("resumed from %s at round %d", resume_dir, self._round_number)
        return resumed_params

    def _before_start(self) -> None:
        if self.config.distribute_init_parameters:
            init_model = self._get_init_model()
            other_data: dict = {"init": True}
            if self._round_number > 1:  # resumed: tell workers where we are
                other_data["round"] = self._round_number
            other_data.update(self._init_annotations())
            self._send_result(
                ParameterMessage(
                    in_round=True,
                    parameter=init_model,
                    other_data=other_data,
                    is_initial=True,
                    # a resume of an already-complete schedule has nothing
                    # to run: the init itself tells workers to stop
                    end_training=self._stopped(),
                )
            )

    def _server_exit(self) -> None:
        self.__algorithm.exit()
        self._trace.close()

    def _process_worker_data(self, worker_id: int, data: Message | None) -> None:
        if self._buffered is not None:
            self._process_buffered(worker_id, data)
            return
        assert 0 <= worker_id < self.worker_number
        # telemetry.profile_rounds on this executor is server-observed:
        # the window opens at the first upload the server sees for its
        # first round and closes after the last round's record
        self._trace.maybe_profile_start(self._round_number)
        if self._trace.enabled:
            if not self._worker_flag:
                # the round barrier opens at its first upload; the span
                # below measures how long the stragglers kept it open
                self._upload_window_start = _time.monotonic()
            self._trace.event(
                "upload",
                worker=worker_id,
                round=self._round_number,
                dropped=data is None,
            )
        self.__algorithm.process_worker_data(
            worker_id=worker_id,
            worker_data=data,
            save_dir=self.config.save_dir,
            old_parameter_dict=self._model_cache.parameter_dict,
        )
        self._worker_flag.add(worker_id)
        if len(self._worker_flag) == self.worker_number:
            if self._trace.enabled and self._upload_window_start is not None:
                self._trace.span_record(
                    "round_barrier",
                    _time.monotonic() - self._upload_window_start,
                    round=self._round_number,
                    workers=self.worker_number,
                )
                self._upload_window_start = None
            result = self._aggregate_worker_data()
            self._send_result(result)
            self._worker_flag.clear()

    # ------------------------------------------ buffered flush machinery
    def _process_buffered(self, worker_id: int, data: Message | None) -> None:
        """Buffered-mode message intake: every message (upload or None)
        advances the worker's origin counter; real uploads are normalized
        against their ORIGIN's base immediately and held until their
        scheduled landing flush; every flush whose cohort is complete
        fires at once (several can cascade after a demotion)."""
        assert 0 <= worker_id < self.worker_number
        self._trace.maybe_profile_start(self._round_number)
        origin = self._origin_counter[worker_id]
        self._origin_counter[worker_id] = origin + 1
        landing = self._bsched.landing.get((worker_id, origin))
        if self._trace.enabled:
            self._trace.event(
                "upload",
                worker=worker_id,
                round=origin,
                dropped=data is None,
                landing=landing,
            )
        if data is None or not isinstance(data, ParameterMessageBase):
            # unselected-round ack, injected dropout, or a demoted
            # worker's synthesized None: the item (if any was scheduled)
            # is cancelled — its flush stops waiting for it
            self._cancelled.add((worker_id, origin))
            if data is None:
                self.algorithm.skipped_workers.add(worker_id)
        elif landing is None:
            get_logger().debug(
                "buffered: worker %s round %s upload lands past the run"
                " end — dropped",
                worker_id,
                origin,
            )
        else:
            base = self._param_history.get(origin - 1)
            message: Message = data
            match message:
                case DeltaParameterMessage():
                    assert base is not None, (
                        "buffered: stale delta restore needs the origin"
                        f" base v_{origin - 1} (history window too small?)"
                    )
                    message = message.restore(base)
                case ParameterMessage():
                    if base is not None:
                        message.complete(base)
            self._held[(worker_id, origin)] = (message, base)
            if self._flush_window_start is None:
                self._flush_window_start = _time.monotonic()
        while not self._stopped() and self._buffered_flush_ready():
            self._buffered_flush()

    def _buffered_flush_ready(self) -> bool:
        """Whether the CURRENT round's flush can fire: every item the
        arrival schedule lands here has either arrived or been cancelled.
        Messages the cohort does not contain (stragglers' in-flight
        uploads, trailing Nones) never block — that is the whole point."""
        flush_round = self._round_number
        if flush_round > self.config.round:
            return False
        for item in self._bsched.live_cohort(
            flush_round, self._buffered_origin_floor
        ):
            key = (item.worker, item.origin)
            if key not in self._held and key not in self._cancelled:
                return False
        return True

    def _buffered_flush(self) -> None:
        """Aggregate one buffer flush: the scheduled cohort's held
        uploads, each guarded against its ORIGIN base, merged with
        ``dataset_size × 1/(1+staleness)^alpha`` weights (normalized over
        the survivors).  An empty flush keeps the old global — a
        well-defined no-op round, not a degenerate aggregate."""
        from ..algorithm.aggregation_algorithm import (
            check_finite,
            update_passes_guard,
        )
        from ..ops import pytree

        flush_round = self._round_number
        cohort = self._bsched.live_cohort(
            flush_round, self._buffered_origin_floor
        )
        algo = self.algorithm
        uploads: list[ParameterMessage] = []
        weights: list[float] = []
        stale_updates = 0
        for item in cohort:
            key = (item.worker, item.origin)
            if key in self._cancelled:
                algo.skipped_workers.add(item.worker)
                continue
            message, base = self._held.pop(key)
            if not update_passes_guard(
                self._fault_plan, item.worker, message.parameter, base
            ):
                algo.rejected_workers.add(item.worker)
                algo.skipped_workers.add(item.worker)
                continue
            if item.staleness:
                stale_updates += 1
                if self._trace.enabled:
                    self._trace.event(
                        "staleness",
                        round=flush_round,
                        worker=item.worker,
                        origin=item.origin,
                        staleness=item.staleness,
                        discount=round(item.discount, 6),
                    )
            uploads.append(message)
            weights.append(float(message.dataset_size) * item.discount)
        # buffered quorum: EXPLICIT min_client_quorum only — an empty
        # flush keeps the old params (see the SPMD twin's rationale)
        if self._min_quorum and len(uploads) < self._min_quorum:
            from ..util.faults import QuorumLostError

            message_text = (
                f"flush {flush_round}: {len(uploads)} surviving buffered"
                f" arrivals below min_client_quorum={self._min_quorum}"
                f" (cohort {len(cohort)}, rejected"
                f" {sorted(algo.rejected_workers)}) — aborting loudly"
            )
            get_logger().error(message_text)
            raise QuorumLostError(message_text)
        if uploads:
            total = sum(weights)
            layout = pytree.ParamVecLayout.of(uploads[0].parameter)
            parameter = pytree.flat_weighted_avg_params(
                [u.parameter for u in uploads],
                [w / total for w in weights],
                layout,
            )
            check_finite(parameter)
            end_training = any(u.end_training for u in uploads)
        else:
            get_logger().info(
                "buffered: flush %s has no landed uploads — keeping the"
                " previous global params",
                flush_round,
            )
            parameter = dict(self._model_cache.parameter_dict)
            end_training = False
        if self._trace.enabled and self._flush_window_start is not None:
            # the buffered twin of the synchronous round_barrier span:
            # first buffered arrival → flush
            self._trace.span_record(
                "buffer_flush",
                _time.monotonic() - self._flush_window_start,
                round=flush_round,
                cohort=len(cohort),
                stale_updates=stale_updates,
                buffer_depth=self._bsched.buffer_depth_after(
                    flush_round, self._buffered_origin_floor
                ),
            )
            self._flush_window_start = None
        self._buffered_round_stats = {
            "flush_cohort": len(cohort),
            "stale_updates": stale_updates,
            "buffer_depth": self._bsched.buffer_depth_after(
                flush_round, self._buffered_origin_floor
            ),
        }
        self._send_result(
            ParameterMessage(parameter=parameter, end_training=end_training)
        )

    def pending_workers(self) -> set[int]:
        """Workers the current round is still waiting on — the stall
        watchdog demotes these to permanent dropouts instead of aborting
        the task when ``fault_tolerance.client_faults_nonfatal`` is set.
        Buffered mode waits only on the next flush's missing cohort
        items, never on stragglers scheduled for later flushes."""
        if self._buffered is not None:
            if self._round_number > self.config.round:
                return set()
            return {
                item.worker
                for item in self._bsched.live_cohort(
                    self._round_number, self._buffered_origin_floor
                )
                if (item.worker, item.origin) not in self._held
                and (item.worker, item.origin) not in self._cancelled
            }
        return set(range(self.worker_number)) - set(self._worker_flag)

    def _quorum_floor(self) -> int:
        """``algorithm_kwargs.min_client_quorum``, with a floor of 1 under
        any active fault machinery (injection, nonfatal client faults, OR
        the update guard — a guard-only plan can still reject every
        upload) — an all-dropped/all-rejected round must abort loudly,
        never "aggregate" an empty upload set."""
        plan = self._fault_plan
        active = plan is not None and (
            plan.injection_active
            or plan.client_faults_nonfatal
            or plan.update_guard
        )
        return max(self._min_quorum, 1 if active else 0)

    def _aggregate_worker_data(self) -> Message:
        quorum = self._quorum_floor()
        if quorum:
            survivors = len(self.__algorithm.all_worker_data)
            if survivors < quorum:
                from ..util.faults import QuorumLostError

                message = (
                    f"round {self._round_number}: {survivors} surviving "
                    f"uploads below min_client_quorum={quorum} "
                    f"(skipped: {sorted(self.__algorithm.skipped_workers)}, "
                    f"rejected: {sorted(self.__algorithm.rejected_workers)})"
                    " — aborting the round loudly"
                )
                get_logger().error(message)
                raise QuorumLostError(message)
        return self.__algorithm.aggregate_worker_data()

    def _before_send_result(self, result: Message) -> None:
        if not isinstance(result, ParameterMessageBase):
            return
        assert isinstance(result, ParameterMessage)
        if self.need_init_performance:
            assert self.config.distribute_init_parameters
        if self.need_init_performance and "init" in result.other_data:
            # keyed 0 directly (not rekeyed after the fact) so its trace
            # span carries the row's real key and the distinct kind keeps
            # tracedump's rounds_total an actual round count
            self.__record_compute_stat(
                result.parameter, keep_performance_logger=False, stat_key=0
            )
        elif self._compute_stat and "init" not in result.other_data:
            self.__record_compute_stat(result.parameter)
            self._maybe_early_stop(result)
        elif result.end_training and "init" not in result.other_data:
            # (a resumed-complete run's init carries end_training — that is
            # not a round and must not append a phantom record row)
            self.__record_compute_stat(result.parameter)
        # key the checkpoint by the stat row just recorded, NOT the round
        # counter: in_round aggregates (FedOBD phase 2) freeze the counter
        # while stat keys keep appending — counter-keyed files would
        # overwrite each other and desync checkpoint↔record pairing on
        # resume (stat key == round_N.npz name is the resume contract)
        recorded_key = max(
            (k for k in self.__stat if k > 0), default=self._round_number
        )
        model_path = os.path.join(
            self.config.save_dir, "aggregated_model", f"round_{recorded_key}.npz"
        )
        self._model_cache.cache_parameter_dict(result.parameter, model_path)
        if self._buffered is not None:
            # stale-delta restore bases: v_r keyed by the flush that
            # produced it (the init broadcast keys the round BEFORE the
            # first flush — 0 fresh, the resumed round on resume); real
            # host copies, trimmed to the schedule's staleness window
            key = (
                self._round_number - 1
                if "init" in result.other_data
                else self._round_number
            )
            self._param_history[key] = {
                k: np.array(v, copy=True)
                for k, v in result.parameter.items()
            }
            window = self._bsched.max_staleness + 1
            for stale_key in [
                k for k in self._param_history if k < key - window
            ]:
                del self._param_history[stale_key]
        if self.config.checkpoint_every_round:
            # config.checkpoint_every thins the cadence (0/1 = legacy
            # every-round); the final round and an end_training aggregate
            # always land so the exit state stays resumable
            every = max(1, int(getattr(self.config, "checkpoint_every", 0) or 1))
            if (
                every == 1
                or recorded_key % every == 0
                or recorded_key >= self.config.round
                or result.end_training
            ):
                self._model_cache.save()
                self._last_saved_key = recorded_key

    def _after_send_result(self, result: Message) -> None:
        if isinstance(result, ParameterMessageBase) and not result.in_round:
            self._trace.maybe_profile_stop(self._round_number)
            self._round_number += 1
            # FaultPlan process kills arm at their scheduled round but
            # fire only once a checkpoint ≥ that round is SAVED (record
            # rows are written synchronously every round) — a sparse
            # checkpoint_every cadence defers the kill to the next saved
            # round, so resume always starts past it and the stateless
            # plan never re-fires the same kill
            if self._fault_plan is not None:
                completed = self._round_number - 1
                self._kill_armed_round = self._fault_plan.arm_kill(
                    completed, completed, self._kill_armed_round
                )
                # record rows are written synchronously every round here,
                # so durability reduces to the last SAVED checkpoint key
                self._fault_plan.fire_armed_kill(
                    self._kill_armed_round, self._last_saved_key
                )
        self.__algorithm.clear_worker_data()

    def _stopped(self) -> bool:
        return self._round_number > self.config.round

    @property
    def performance_stat(self) -> dict[int, dict]:
        return self.__stat

    def _get_stat_key(self) -> int:
        return self._round_number

    def _annotate_stat(self, round_stat: dict) -> None:
        """Subclass hook: extra fields on each round record (FedOBD tags
        the producing phase so a resume can replay its driver)."""

    def _init_annotations(self) -> dict:
        """Subclass hook: extra ``other_data`` on the init broadcast (FedOBD
        announces a resumed phase-2 state to freshly started workers)."""
        return {}

    def __record_compute_stat(
        self,
        parameter_dict: Params,
        keep_performance_logger: bool = True,
        stat_key: int | None = None,
    ) -> None:
        self.tester.set_visualizer_prefix(f"round: {self._round_number},")
        metric = self.get_metric(
            parameter_dict, keep_performance_logger=keep_performance_logger
        )
        round_stat = {f"test_{k}": v for k, v in metric.items()}
        # first-class per-round profiling counters (SURVEY.md §5 TPU plan):
        # wall-clock + transport bytes since the previous round record
        now = _time.monotonic()
        round_stat["round_seconds"] = now - self.__round_start
        round_stat["received_mb"] = (
            self.received_bytes - self.__round_start_bytes[0]
        ) / 1e6
        round_stat["sent_mb"] = (self.sent_bytes - self.__round_start_bytes[1]) / 1e6
        self.__round_start = now
        self.__round_start_bytes = (self.received_bytes, self.sent_bytes)
        plan = self._fault_plan
        if plan is not None and (
            plan.injection_active
            or plan.client_faults_nonfatal
            or plan.update_guard
        ):
            # fault observability: how many uploads the guard rejected and
            # how many selected clients dropped (injected, crashed, or
            # watchdog-demoted) this round
            algo = self.__algorithm
            round_stat["rejected_updates"] = len(algo.rejected_workers)
            dead = set(
                getattr(self._task_context, "dropped_workers", None) or ()
            )
            injected = plan.dropped_clients(
                self._round_number, self.worker_number
            )
            round_stat["dropped_clients"] = len(
                algo.skipped_workers & (dead | set(injected))
            )
        if self._buffered is not None and self._buffered_round_stats:
            # buffered observability: what this flush actually merged
            # (cohort size, late arrivals, in-flight backlog)
            round_stat.update(self._buffered_round_stats)
        self._annotate_stat(round_stat)
        key = self._get_stat_key() if stat_key is None else stat_key
        assert key not in self.__stat
        if self._trace.enabled:
            if "rejected_updates" in round_stat:
                self._trace.event(
                    "fault",
                    round=key,
                    rejected_updates=round_stat["rejected_updates"],
                    dropped_clients=round_stat.get("dropped_clients", 0),
                )
            span_fields = {
                "round": key,
                "accuracy": metric.get("accuracy"),
                "loss": metric.get("loss"),
                "received_mb": round_stat["received_mb"],
                "sent_mb": round_stat["sent_mb"],
            }
            if "rejected_updates" in round_stat:
                span_fields["rejected_updates"] = round_stat[
                    "rejected_updates"
                ]
            # the init-performance row (stat_key=0) is not a round: its
            # own span kind keeps tracedump's rounds_total honest
            round_stat["trace_offset"] = self._trace.span_record(
                "round" if key else "init_eval",
                round_stat["round_seconds"],
                **span_fields,
            )
        self.__stat[key] = round_stat
        # the shared atomic-write helper (util/checkpoint.py): the record
        # is the resume source of record rows on this executor too — a
        # crash mid-write must never leave a torn file (the SPMD flusher
        # has used this contract since PR 2; the threaded path's plain
        # open() rewrite was the last non-atomic copy).  The trace lands
        # first so durable rows never cross-link trace_offsets a resumed
        # recorder would renumber (a no-op at the default eager cadence)
        from ..util.checkpoint import atomic_json_dump

        self._trace.flush()
        atomic_json_dump(
            os.path.join(self.save_dir, "round_record.json"), self.__stat
        )

        max_acc = max(t["test_accuracy"] for t in self.__stat.values())
        if max_acc > self.__best_acc:
            self.__best_acc = max_acc
            np.savez(
                os.path.join(self.save_dir, "best_global_model.npz"),
                **{k: np.asarray(v) for k, v in parameter_dict.items()},
            )

    def _maybe_early_stop(self, result: Message) -> None:
        """Default plateau stop after each recorded round metric.  Methods
        owning their own phase progression (FedOBD's driver) override this
        to a no-op so ``_convergent``'s plateau counter has exactly one
        caller."""
        if not result.end_training and self.early_stop and self._convergent():
            result.end_training = True

    def _convergent(self) -> bool:
        """5-round accuracy plateau (reference ``aggregation_server.py:166-184``;
        its version raises the watermark during stat recording so the
        improvement test can never pass — here ``__max_acc`` is owned solely
        by this method)."""
        max_acc = max(t["test_accuracy"] for t in self.performance_stat.values())
        diff = 0.001
        if max_acc > self.__max_acc + diff:
            self.__max_acc = max_acc
            self.__plateau = 0
            return False
        self.__plateau += 1
        get_logger().info(
            "plateau %s (max acc %.4f)", self.__plateau, self.__max_acc
        )
        return self.__plateau >= 5
