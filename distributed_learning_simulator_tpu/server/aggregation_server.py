"""Round state machine over a pluggable aggregation algorithm.

TPU-native equivalent of
``simulation_lib/server/aggregation_server.py:15-184``: distribute the init
model, gather all workers each round, aggregate, compute the round test
metric, append to ``round_record.json``, keep ``best_global_model``, early
stop on a 5-round plateau, and cache the global model per round.
"""

import os
import time as _time
from typing import Any

import numpy as np

from ..algorithm.aggregation_algorithm import AggregationAlgorithm
from ..message import Message, ParameterMessage, ParameterMessageBase
from ..ops.pytree import Params
from ..util.model_cache import ModelCache
from ..utils.logging import get_logger
from .server import Server


class AggregationServer(Server):
    def __init__(self, algorithm: AggregationAlgorithm, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._model_cache = ModelCache()
        self._round_number = 1
        self._worker_flag: set[int] = set()
        self.__algorithm = algorithm
        self.__algorithm.set_server(self)
        self.__algorithm.set_config(self.config)
        self.__stat: dict[int, dict] = {}
        self._compute_stat: bool = True
        self.__plateau = 0
        self.__best_acc = 0.0  # best-model bookkeeping
        self.__max_acc = 0.0  # plateau bookkeeping (owned by _convergent)
        self.need_init_performance = False
        self.__early_stop = self.config.algorithm_kwargs.get("early_stop", False)
        # fault tolerance (util/faults.py): quorum floor, per-round fault
        # stat columns, and the scheduled process kills all key off the
        # same plan the SPMD sessions consume
        from ..util.faults import FaultPlan

        self._fault_plan = FaultPlan.from_config(self.config)
        self._min_quorum = int(
            self.config.algorithm_kwargs.get("min_client_quorum", 0) or 0
        )
        # kill deferral bookkeeping: a scheduled kill fires only once the
        # killed round has a SAVED checkpoint, so a resumed run starts
        # past it and the stateless plan never re-fires the same kill
        self._kill_armed_round: int | None = None
        self._last_saved_key = 0
        self.__round_start = _time.monotonic()
        self.__round_start_bytes = (0, 0)
        # roundtrace telemetry (util/telemetry.py): the threaded executor
        # shares the SPMD sessions' trace schema — worker `upload` events,
        # a `round_barrier` span (first upload → all workers in), one
        # `round` span per record row (its JSONL offset cross-linked as
        # the row's trace_offset), and `fault` events.  Everything runs
        # on the server sweep thread over host state it already owns.
        from ..util.telemetry import TraceRecorder

        self._trace = TraceRecorder.from_config(
            self.config, default_dir=self.save_dir
        )
        if not (getattr(self.config, "telemetry", None) or {}).get("flush_every"):
            # the server event loop has no try/finally around its sweep
            # (Server.start runs _server_exit only on the clean path), so
            # an abort mid-round (QuorumLostError, worker crash) would
            # drop a buffered trace entirely.  This executor already
            # writes its record synchronously every round — flush each
            # trace record the same way unless the user chose a cadence
            # (an explicit `flush_every: 0` means "auto" and gets the
            # same eager default, not the recorder's 256-record buffer).
            self._trace.flush_every = 1
        self._upload_window_start: float | None = None

    @property
    def early_stop(self) -> bool:
        return self.__early_stop

    @property
    def algorithm(self) -> AggregationAlgorithm:
        return self.__algorithm

    @property
    def round_number(self) -> int:
        return self._round_number

    def _get_init_model(self) -> Params:
        resumed = self._try_resume()
        if resumed is not None:
            return resumed
        init_path = self.config.algorithm_kwargs.get("global_model_path")
        if init_path:
            with np.load(init_path) as blob:
                return {k: blob[k] for k in blob.files}
        return self.tester.get_parameter_dict()

    def _try_resume(self) -> Params | None:
        """True round resume the reference lacks (SURVEY.md §5: "a killed run
        restarts from round 1"): if ``algorithm_kwargs.resume_dir`` points at
        a previous session, load its latest ``aggregated_model/round_N.npz``
        and continue from round N+1, restoring the round records."""
        resume_dir = self.config.algorithm_kwargs.get("resume_dir")
        if not resume_dir:
            return None
        from ..util.resume import load_resume_state

        resumed_params, stats, last_round = load_resume_state(resume_dir)
        if resumed_params is None:
            get_logger().warning("nothing resumable under %s", resume_dir)
            return None
        self.__stat.update(stats)
        if self.__stat:
            restored_max = max(t["test_accuracy"] for t in self.__stat.values())
            self.__best_acc = restored_max
            self.__max_acc = restored_max
        self._round_number = last_round + 1
        self._last_saved_key = last_round  # kill deferral: already durable
        get_logger().info("resumed from %s at round %d", resume_dir, self._round_number)
        return resumed_params

    def _before_start(self) -> None:
        if self.config.distribute_init_parameters:
            init_model = self._get_init_model()
            other_data: dict = {"init": True}
            if self._round_number > 1:  # resumed: tell workers where we are
                other_data["round"] = self._round_number
            other_data.update(self._init_annotations())
            self._send_result(
                ParameterMessage(
                    in_round=True,
                    parameter=init_model,
                    other_data=other_data,
                    is_initial=True,
                    # a resume of an already-complete schedule has nothing
                    # to run: the init itself tells workers to stop
                    end_training=self._stopped(),
                )
            )

    def _server_exit(self) -> None:
        self.__algorithm.exit()
        self._trace.close()

    def _process_worker_data(self, worker_id: int, data: Message | None) -> None:
        assert 0 <= worker_id < self.worker_number
        # telemetry.profile_rounds on this executor is server-observed:
        # the window opens at the first upload the server sees for its
        # first round and closes after the last round's record
        self._trace.maybe_profile_start(self._round_number)
        if self._trace.enabled:
            if not self._worker_flag:
                # the round barrier opens at its first upload; the span
                # below measures how long the stragglers kept it open
                self._upload_window_start = _time.monotonic()
            self._trace.event(
                "upload",
                worker=worker_id,
                round=self._round_number,
                dropped=data is None,
            )
        self.__algorithm.process_worker_data(
            worker_id=worker_id,
            worker_data=data,
            save_dir=self.config.save_dir,
            old_parameter_dict=self._model_cache.parameter_dict,
        )
        self._worker_flag.add(worker_id)
        if len(self._worker_flag) == self.worker_number:
            if self._trace.enabled and self._upload_window_start is not None:
                self._trace.span_record(
                    "round_barrier",
                    _time.monotonic() - self._upload_window_start,
                    round=self._round_number,
                    workers=self.worker_number,
                )
                self._upload_window_start = None
            result = self._aggregate_worker_data()
            self._send_result(result)
            self._worker_flag.clear()

    def pending_workers(self) -> set[int]:
        """Workers the current round is still waiting on — the stall
        watchdog demotes these to permanent dropouts instead of aborting
        the task when ``fault_tolerance.client_faults_nonfatal`` is set."""
        return set(range(self.worker_number)) - set(self._worker_flag)

    def _quorum_floor(self) -> int:
        """``algorithm_kwargs.min_client_quorum``, with a floor of 1 under
        any active fault machinery (injection, nonfatal client faults, OR
        the update guard — a guard-only plan can still reject every
        upload) — an all-dropped/all-rejected round must abort loudly,
        never "aggregate" an empty upload set."""
        plan = self._fault_plan
        active = plan is not None and (
            plan.injection_active
            or plan.client_faults_nonfatal
            or plan.update_guard
        )
        return max(self._min_quorum, 1 if active else 0)

    def _aggregate_worker_data(self) -> Message:
        quorum = self._quorum_floor()
        if quorum:
            survivors = len(self.__algorithm.all_worker_data)
            if survivors < quorum:
                from ..util.faults import QuorumLostError

                message = (
                    f"round {self._round_number}: {survivors} surviving "
                    f"uploads below min_client_quorum={quorum} "
                    f"(skipped: {sorted(self.__algorithm.skipped_workers)}, "
                    f"rejected: {sorted(self.__algorithm.rejected_workers)})"
                    " — aborting the round loudly"
                )
                get_logger().error(message)
                raise QuorumLostError(message)
        return self.__algorithm.aggregate_worker_data()

    def _before_send_result(self, result: Message) -> None:
        if not isinstance(result, ParameterMessageBase):
            return
        assert isinstance(result, ParameterMessage)
        if self.need_init_performance:
            assert self.config.distribute_init_parameters
        if self.need_init_performance and "init" in result.other_data:
            # keyed 0 directly (not rekeyed after the fact) so its trace
            # span carries the row's real key and the distinct kind keeps
            # tracedump's rounds_total an actual round count
            self.__record_compute_stat(
                result.parameter, keep_performance_logger=False, stat_key=0
            )
        elif self._compute_stat and "init" not in result.other_data:
            self.__record_compute_stat(result.parameter)
            self._maybe_early_stop(result)
        elif result.end_training and "init" not in result.other_data:
            # (a resumed-complete run's init carries end_training — that is
            # not a round and must not append a phantom record row)
            self.__record_compute_stat(result.parameter)
        # key the checkpoint by the stat row just recorded, NOT the round
        # counter: in_round aggregates (FedOBD phase 2) freeze the counter
        # while stat keys keep appending — counter-keyed files would
        # overwrite each other and desync checkpoint↔record pairing on
        # resume (stat key == round_N.npz name is the resume contract)
        recorded_key = max(
            (k for k in self.__stat if k > 0), default=self._round_number
        )
        model_path = os.path.join(
            self.config.save_dir, "aggregated_model", f"round_{recorded_key}.npz"
        )
        self._model_cache.cache_parameter_dict(result.parameter, model_path)
        if self.config.checkpoint_every_round:
            # config.checkpoint_every thins the cadence (0/1 = legacy
            # every-round); the final round and an end_training aggregate
            # always land so the exit state stays resumable
            every = max(1, int(getattr(self.config, "checkpoint_every", 0) or 1))
            if (
                every == 1
                or recorded_key % every == 0
                or recorded_key >= self.config.round
                or result.end_training
            ):
                self._model_cache.save()
                self._last_saved_key = recorded_key

    def _after_send_result(self, result: Message) -> None:
        if isinstance(result, ParameterMessageBase) and not result.in_round:
            self._trace.maybe_profile_stop(self._round_number)
            self._round_number += 1
            # FaultPlan process kills arm at their scheduled round but
            # fire only once a checkpoint ≥ that round is SAVED (record
            # rows are written synchronously every round) — a sparse
            # checkpoint_every cadence defers the kill to the next saved
            # round, so resume always starts past it and the stateless
            # plan never re-fires the same kill
            if self._fault_plan is not None:
                completed = self._round_number - 1
                self._kill_armed_round = self._fault_plan.arm_kill(
                    completed, completed, self._kill_armed_round
                )
                # record rows are written synchronously every round here,
                # so durability reduces to the last SAVED checkpoint key
                self._fault_plan.fire_armed_kill(
                    self._kill_armed_round, self._last_saved_key
                )
        self.__algorithm.clear_worker_data()

    def _stopped(self) -> bool:
        return self._round_number > self.config.round

    @property
    def performance_stat(self) -> dict[int, dict]:
        return self.__stat

    def _get_stat_key(self) -> int:
        return self._round_number

    def _annotate_stat(self, round_stat: dict) -> None:
        """Subclass hook: extra fields on each round record (FedOBD tags
        the producing phase so a resume can replay its driver)."""

    def _init_annotations(self) -> dict:
        """Subclass hook: extra ``other_data`` on the init broadcast (FedOBD
        announces a resumed phase-2 state to freshly started workers)."""
        return {}

    def __record_compute_stat(
        self,
        parameter_dict: Params,
        keep_performance_logger: bool = True,
        stat_key: int | None = None,
    ) -> None:
        self.tester.set_visualizer_prefix(f"round: {self._round_number},")
        metric = self.get_metric(
            parameter_dict, keep_performance_logger=keep_performance_logger
        )
        round_stat = {f"test_{k}": v for k, v in metric.items()}
        # first-class per-round profiling counters (SURVEY.md §5 TPU plan):
        # wall-clock + transport bytes since the previous round record
        now = _time.monotonic()
        round_stat["round_seconds"] = now - self.__round_start
        round_stat["received_mb"] = (
            self.received_bytes - self.__round_start_bytes[0]
        ) / 1e6
        round_stat["sent_mb"] = (self.sent_bytes - self.__round_start_bytes[1]) / 1e6
        self.__round_start = now
        self.__round_start_bytes = (self.received_bytes, self.sent_bytes)
        plan = self._fault_plan
        if plan is not None and (
            plan.injection_active
            or plan.client_faults_nonfatal
            or plan.update_guard
        ):
            # fault observability: how many uploads the guard rejected and
            # how many selected clients dropped (injected, crashed, or
            # watchdog-demoted) this round
            algo = self.__algorithm
            round_stat["rejected_updates"] = len(algo.rejected_workers)
            dead = set(
                getattr(self._task_context, "dropped_workers", None) or ()
            )
            injected = plan.dropped_clients(
                self._round_number, self.worker_number
            )
            round_stat["dropped_clients"] = len(
                algo.skipped_workers & (dead | set(injected))
            )
        self._annotate_stat(round_stat)
        key = self._get_stat_key() if stat_key is None else stat_key
        assert key not in self.__stat
        if self._trace.enabled:
            if "rejected_updates" in round_stat:
                self._trace.event(
                    "fault",
                    round=key,
                    rejected_updates=round_stat["rejected_updates"],
                    dropped_clients=round_stat.get("dropped_clients", 0),
                )
            span_fields = {
                "round": key,
                "accuracy": metric.get("accuracy"),
                "loss": metric.get("loss"),
                "received_mb": round_stat["received_mb"],
                "sent_mb": round_stat["sent_mb"],
            }
            if "rejected_updates" in round_stat:
                span_fields["rejected_updates"] = round_stat[
                    "rejected_updates"
                ]
            # the init-performance row (stat_key=0) is not a round: its
            # own span kind keeps tracedump's rounds_total honest
            round_stat["trace_offset"] = self._trace.span_record(
                "round" if key else "init_eval",
                round_stat["round_seconds"],
                **span_fields,
            )
        self.__stat[key] = round_stat
        # the shared atomic-write helper (util/checkpoint.py): the record
        # is the resume source of record rows on this executor too — a
        # crash mid-write must never leave a torn file (the SPMD flusher
        # has used this contract since PR 2; the threaded path's plain
        # open() rewrite was the last non-atomic copy).  The trace lands
        # first so durable rows never cross-link trace_offsets a resumed
        # recorder would renumber (a no-op at the default eager cadence)
        from ..util.checkpoint import atomic_json_dump

        self._trace.flush()
        atomic_json_dump(
            os.path.join(self.save_dir, "round_record.json"), self.__stat
        )

        max_acc = max(t["test_accuracy"] for t in self.__stat.values())
        if max_acc > self.__best_acc:
            self.__best_acc = max_acc
            np.savez(
                os.path.join(self.save_dir, "best_global_model.npz"),
                **{k: np.asarray(v) for k, v in parameter_dict.items()},
            )

    def _maybe_early_stop(self, result: Message) -> None:
        """Default plateau stop after each recorded round metric.  Methods
        owning their own phase progression (FedOBD's driver) override this
        to a no-op so ``_convergent``'s plateau counter has exactly one
        caller."""
        if not result.end_training and self.early_stop and self._convergent():
            result.end_training = True

    def _convergent(self) -> bool:
        """5-round accuracy plateau (reference ``aggregation_server.py:166-184``;
        its version raises the watermark during stat recording so the
        improvement test can never pass — here ``__max_acc`` is owned solely
        by this method)."""
        max_acc = max(t["test_accuracy"] for t in self.performance_stat.values())
        diff = 0.001
        if max_acc > self.__max_acc + diff:
            self.__max_acc = max_acc
            self.__plateau = 0
            return False
        self.__plateau += 1
        get_logger().info(
            "plateau %s (max acc %.4f)", self.__plateau, self.__max_acc
        )
        return self.__plateau >= 5
