"""Graph-FL server (reference ``simulation_lib/server/graph_server.py:5-7``)."""

from typing import Any

from ..algorithm.graph_algorithm import GraphNodeEmbeddingPassingAlgorithm
from .aggregation_server import AggregationServer


class GraphNodeServer(AggregationServer):
    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("algorithm", GraphNodeEmbeddingPassingAlgorithm())
        super().__init__(**kwargs)
