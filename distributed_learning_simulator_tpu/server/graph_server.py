"""Graph-FL server (reference ``simulation_lib/server/graph_server.py:5-7``)."""

from typing import Any

from ..algorithm.graph_algorithm import GraphNodeEmbeddingPassingAlgorithm
from .aggregation_server import AggregationServer


class GraphNodeServer(AggregationServer):
    #: the embedding-passing rounds interleave non-parameter messages the
    #: buffer-flush bookkeeping cannot hold back (aggregation_mode gate)
    _buffered_capable = False

    def __init__(self, **kwargs: Any) -> None:
        kwargs.setdefault("algorithm", GraphNodeEmbeddingPassingAlgorithm())
        super().__init__(**kwargs)
