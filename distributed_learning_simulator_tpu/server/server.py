"""Server base: the central event loop.

TPU-native equivalent of ``simulation_lib/server/server.py:20-134``: sweep
workers for pending data, feed ``_process_worker_data``, send results with
per-worker payloads or selected-subset broadcast (``None`` to unselected),
own the central test ``Inferencer``.  The gevent 1 s sweep becomes a blocking
multi-queue wait; evaluation is a jitted XLA program.
"""

import json
import os
import time
from functools import cached_property
from typing import Any

from ..engine.executor import Inferencer
from ..executor import Executor
from ..message import Message, ParameterMessage
from ..ml_type import MachineLearningPhase
from ..ops.pytree import Params
from ..utils.logging import get_logger


class Server(Executor):
    #: buffered-aggregation event-loop mode (set by AggregationServer when
    #: ``aggregation_mode: buffered``): drain EVERY queued message each
    #: sweep instead of one per worker per cycle.  The one-per-cycle
    #: cadence implements the synchronous round barrier — under buffered
    #: flushes it would serialize consumption behind the slowest worker
    #: and silently reinstate the barrier the mode exists to remove.
    _greedy_sweep = False

    def __init__(self, task_id: int | None, endpoint, config=None, task_context=None, **kwargs: Any) -> None:
        name = "server"
        if task_id is not None:
            name = f"server of {task_id}"
        super().__init__(config=config, name=name, task_context=task_context)
        self._endpoint = endpoint

    # first-class communication counters (SURVEY.md §5: byte accounting via
    # get_message_size becomes a built-in metric, not a log scrape).  The
    # endpoint counts at the wire boundary, so quantized transports report
    # compressed sizes.
    @property
    def received_bytes(self) -> int:
        return getattr(self._endpoint, "received_bytes", 0)

    @property
    def sent_bytes(self) -> int:
        return getattr(self._endpoint, "sent_bytes", 0)

    @property
    def worker_number(self) -> int:
        return self.config.worker_number

    @cached_property
    def tester(self) -> Inferencer:
        return Inferencer(
            self.config,
            self._task_context.dataset_collection,
            self._task_context.model_ctx,
            self._task_context.engine,
            phase=MachineLearningPhase.Test,
            seed=self.config.seed,
            name="tester",
        )

    def get_metric(
        self, parameter_dict: Params | ParameterMessage, keep_performance_logger: bool = True
    ) -> dict:
        """Load params into the tester and run central inference (reference
        ``server.py:40-55``)."""
        if isinstance(parameter_dict, ParameterMessage):
            parameter_dict = parameter_dict.parameter
        self.tester.load_parameter_dict(parameter_dict)
        metric = self.tester.inference()
        if keep_performance_logger:
            get_logger().info(
                "%s test accuracy %.4f loss %.4f",
                self.tester.visualizer_prefix,
                metric["accuracy"],
                metric["loss"],
            )
        return metric

    def start(self) -> None:
        with self._get_execution_context():
            os.makedirs(self.save_dir, exist_ok=True)
            with open(
                os.path.join(self.save_dir, "config.json"), "wt", encoding="utf8"
            ) as f:
                json.dump(
                    {k: v for k, v in vars(self.config).items() if _is_jsonable(v)},
                    f,
                    default=str,
                )
            self._before_start()

            worker_set: set[int] = set()
            while not self._stopped():
                if not worker_set:
                    worker_set = self._active_workers()
                progressed = False
                # fault tolerance (util/faults.py): a worker whose thread
                # died (or was demoted by the stall watchdog) under
                # ``fault_tolerance.client_faults_nonfatal`` can never
                # upload again — synthesize its per-round ``None`` (the
                # existing skipped-worker path) so every round completes
                # over the survivors instead of waiting forever.  A last
                # upload still queued from before the death is consumed
                # first.
                dropped = self._dropped_workers() & worker_set
                if self._greedy_sweep:
                    # buffered mode: only synthesize a dead worker's None
                    # when the next flush actually waits on it — the
                    # greedy drain consumes real messages as fast as they
                    # arrive, so an every-sweep synthesis would run away
                    pending_fn = getattr(self, "pending_workers", None)
                    dropped &= (
                        set(pending_fn()) if pending_fn is not None else set()
                    )
                for worker_id in sorted(dropped):
                    if self._endpoint.has_data(worker_id):
                        continue
                    self._process_worker_data(worker_id, None)
                    if not self._greedy_sweep:
                        worker_set.remove(worker_id)
                    progressed = True
                for worker_id in sorted(worker_set):
                    if self._greedy_sweep:
                        while not self._stopped() and self._endpoint.has_data(
                            worker_id
                        ):
                            self._process_worker_data(
                                worker_id, self._endpoint.get(worker_id)
                            )
                            progressed = True
                    elif self._endpoint.has_data(worker_id):
                        data = self._endpoint.get(worker_id)
                        self._process_worker_data(worker_id, data)
                        worker_set.remove(worker_id)
                        progressed = True
                if self._task_context is not None and self._task_context.aborted():
                    break
                if not progressed and worker_set and not self._stopped():
                    _wait_any(self._endpoint, worker_set)
            self._endpoint.close()
            self._server_exit()
            get_logger().debug("end server")

    def _before_start(self) -> None:
        pass

    def _server_exit(self) -> None:
        pass

    def _process_worker_data(self, worker_id: int, data: Message | None) -> None:
        raise NotImplementedError

    def _before_send_result(self, result: Message) -> None:
        pass

    def _after_send_result(self, result: Message) -> None:
        pass

    def _send_result(self, result: Message) -> None:
        self._before_send_result(result=result)
        if "worker_result" in result.other_data:
            for worker_id, data in result.other_data["worker_result"].items():
                self._endpoint.send(worker_id=worker_id, data=data)
        else:
            selected_workers = self._select_workers()
            get_logger().debug("choose workers %s", selected_workers)
            if selected_workers:
                self._endpoint.broadcast(data=result, worker_ids=selected_workers)
            unselected = set(range(self.worker_number)) - selected_workers
            if unselected:
                self._endpoint.broadcast(data=None, worker_ids=unselected)
        self._after_send_result(result=result)

    def _active_workers(self) -> set[int]:
        """Workers the event loop still expects messages from (subclasses
        shrink this as workers finish — per-step gradient methods)."""
        return set(range(self._endpoint.worker_num))

    def _dropped_workers(self) -> set[int]:
        """Workers permanently demoted to dropouts (crashed threads /
        watchdog-demoted stragglers) under
        ``fault_tolerance.client_faults_nonfatal``."""
        ctx = self._task_context
        return set(getattr(ctx, "dropped_workers", None) or ())

    def _select_workers(self) -> set[int]:
        """Random client selection (reference ``server.py:123-131``),
        deterministic in (seed, round)."""
        from ..utils.selection import select_workers

        return select_workers(
            self.config.seed,
            getattr(self, "_round_number", 0),
            self.worker_number,
            self.config.algorithm_kwargs.get("random_client_number"),
        )

    def _stopped(self) -> bool:
        raise NotImplementedError


def _wait_any(endpoint, worker_set: set[int], timeout: float = 0.5) -> None:
    """Block until some worker has data (replaces the reference's 1 s gevent
    sleep-poll, ``server.py:85``) via the topology's wakeup event."""
    wakeup = getattr(getattr(endpoint, "_topology", None), "server_wakeup", None)
    if wakeup is None:
        time.sleep(0.05)
        return
    wakeup.wait(timeout=timeout)
    wakeup.clear()


def _is_jsonable(value: Any) -> bool:
    try:
        json.dumps(value)
        return True
    except (TypeError, ValueError):
        return False
