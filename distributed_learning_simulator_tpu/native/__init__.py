"""ctypes bindings for the native host runtime (``native/fastops.cc``).

The library is built on demand with ``g++`` (the image has no pybind11;
plain C ABI + ctypes keeps the binding dependency-free).  Every entry point
has a numpy fallback so the framework still runs where no compiler exists —
``available()`` tells which path is active.

Surface:
* :class:`Float64Accumulator` — streaming float64 parameter aggregation,
  the reference server's accumulation semantics
  (``simulation_lib/algorithm/fed_avg_algorithm.py:44``) for bit-parity runs;
* :func:`sparsify` — exact top-k error-feedback sparsification
  (``single_model_afd`` with ``topk_ratio``);
* :func:`gather_rows` — fused index-select batch assembly for the host
  input pipeline;
* :func:`permute_indices` — version-stable deterministic shuffling (the
  IID sampler's per-class permutation).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_SRC_DIR, "libfastops.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    src = os.path.join(_SRC_DIR, "fastops.cc")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["make", "-C", _SRC_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        # make is a fast no-op when the .so is current, and rebuilds when
        # fastops.cc changed; a pre-existing .so is used only if make fails
        if not _build() and not os.path.exists(_LIB_PATH):
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            i64, f32p, f64p, i64p, i32p = (
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
            )
            lib.accumulate_f64.argtypes = [f64p, f32p, ctypes.c_double, i64]
            lib.finalize_f64.argtypes = [f64p, ctypes.c_double, f32p, i64]
            lib.sparsify_topk.restype = i64
            lib.sparsify_topk.argtypes = [f32p, i64, i64, i64p, f32p, ctypes.c_int]
            lib.gather_rows_f32.argtypes = [f32p, i64, i64p, i64, f32p]
            lib.gather_rows_i32.argtypes = [i32p, i64, i64p, i64, i32p]
            lib.permute_indices.argtypes = [i64p, i64, ctypes.c_uint64]
            lib.fastops_abi_version.restype = ctypes.c_int
            if lib.fastops_abi_version() != 1:
                raise OSError("fastops ABI mismatch")
        except (OSError, AttributeError):
            # stale/incompatible binary: fall back to numpy everywhere
            _build_failed = True
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


class Float64Accumulator:
    """Streaming ``acc += x * w`` in float64, finalized to float32 — the
    reference's server-side accumulation semantics, natively."""

    def __init__(self, n: int) -> None:
        self.acc = np.zeros(n, np.float64)
        self.total_weight = 0.0
        self.n = n

    def add(self, x: np.ndarray, weight: float) -> None:
        x = np.ascontiguousarray(x, np.float32).reshape(-1)
        assert x.size == self.n
        lib = _load()
        if lib is not None:
            lib.accumulate_f64(
                _ptr(self.acc, ctypes.c_double),
                _ptr(x, ctypes.c_float),
                float(weight),
                self.n,
            )
        else:
            self.acc += x.astype(np.float64) * weight
        self.total_weight += float(weight)

    def finalize(self) -> np.ndarray:
        assert self.total_weight > 0
        out = np.empty(self.n, np.float32)
        lib = _load()
        if lib is not None:
            lib.finalize_f64(
                _ptr(self.acc, ctypes.c_double),
                self.total_weight,
                _ptr(out, ctypes.c_float),
                self.n,
            )
        else:
            out[:] = (self.acc / self.total_weight).astype(np.float32)
        return out


def sparsify(x: np.ndarray, k: int, zero_rest: bool = False):
    """Keep the exact k largest-|x| entries (ties toward lower index);
    returns (indices, values) in ascending index order.  With ``zero_rest``
    the kept entries are zeroed **in x** (error-feedback: what is sent
    leaves the residual) — ``x`` must then be contiguous float32, or the
    mutation would land on a temporary copy."""
    if zero_rest:
        assert (
            isinstance(x, np.ndarray)
            and x.dtype == np.float32
            and x.flags.c_contiguous
        ), "zero_rest requires a contiguous float32 array (mutated in place)"
    x = np.ascontiguousarray(x, np.float32).reshape(-1)
    k = min(int(k), x.size)
    if k <= 0:
        return np.empty(0, np.int64), np.empty(0, np.float32)
    lib = _load()
    if lib is not None:
        indices = np.empty(k, np.int64)
        values = np.empty(k, np.float32)
        count = lib.sparsify_topk(
            _ptr(x, ctypes.c_float),
            x.size,
            k,
            _ptr(indices, ctypes.c_int64),
            _ptr(values, ctypes.c_float),
            1 if zero_rest else 0,
        )
        return indices[:count], values[:count]
    # numpy fallback: argpartition on (-|x|, index) — same tie rule
    order = np.lexsort((np.arange(x.size), -np.abs(x)))[:k]
    indices = np.sort(order)
    values = x[indices].copy()
    if zero_rest:
        x[indices] = 0.0
    return indices, values


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``src[idx]`` for 2D+ row-major arrays via one native memcpy pass."""
    idx = np.ascontiguousarray(idx, np.int64)
    lib = _load()
    row_shape = src.shape[1:]
    row_elems = int(np.prod(row_shape)) if row_shape else 1
    if lib is None:
        return src[idx]
    if src.dtype == np.float32:
        src_c = np.ascontiguousarray(src)
        out = np.empty((idx.size, *row_shape), np.float32)
        lib.gather_rows_f32(
            _ptr(src_c, ctypes.c_float), row_elems,
            _ptr(idx, ctypes.c_int64), idx.size,
            _ptr(out, ctypes.c_float),
        )
        return out
    if src.dtype == np.int32:
        src_c = np.ascontiguousarray(src)
        out = np.empty((idx.size, *row_shape), np.int32)
        lib.gather_rows_i32(
            _ptr(src_c, ctypes.c_int32), row_elems,
            _ptr(idx, ctypes.c_int64), idx.size,
            _ptr(out, ctypes.c_int32),
        )
        return out
    return src[idx]


def permute_indices(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of ``arange(n)`` — identical across
    platforms and library versions (xorshift64 Fisher-Yates)."""
    idx = np.arange(n, dtype=np.int64)
    lib = _load()
    if lib is not None:
        lib.permute_indices(_ptr(idx, ctypes.c_int64), n, seed & 0xFFFFFFFFFFFFFFFF)
        return idx
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    rng.shuffle(idx)
    return idx
