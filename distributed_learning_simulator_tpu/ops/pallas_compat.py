"""Pallas interpret-mode compat across jax versions.

Newer jax spells interpreter mode ``interpret=pltpu.InterpretParams()``
(a config object carrying TPU-interpreter options); the 0.4 line (this
container ships 0.4.37) has no ``InterpretParams`` and takes the older
``interpret=True`` boolean.  Every kernel call site routes through
:func:`interpret_param` so the whole kernel layer follows whichever API
the installed jax exposes.
"""

from jax.experimental.pallas import tpu as pltpu


def interpret_param(interpret: bool):
    """Value for ``pl.pallas_call(..., interpret=...)``: the TPU
    interpreter params object where the API has one, the legacy boolean
    otherwise; ``False`` always means compiled."""
    if not interpret:
        return False
    if hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return True
