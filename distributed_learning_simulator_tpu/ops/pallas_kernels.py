"""Pallas TPU kernels for the transport-codec and aggregation hot ops.

The reference's native-performance layer is upstream torch's CUDA core
(SURVEY.md §2); ours is XLA — and, for the ops XLA can't fuse the way we
want, these hand-written TPU kernels:

* ``qsgd_encode`` / ``qsgd_decode`` — the whole QSGD codec as ONE VMEM
  pass: abs-max scale, stochastic rounding (on-core PRNG via
  ``pltpu.prng_random_bits`` — no Threefry key streams materialized in
  HBM), sign extraction, and bit-packing into uint32 words.  The XLA
  version in ``ops/quantization.py`` needs separate reduce / uniform /
  pack programs with HBM round-trips between them.
* ``weighted_accum`` — the FedAvg reduction ``sum_c w[c] * X[c]`` without
  materializing the ``[C, N]`` weighted intermediate: a grid over feature
  blocks, scanning clients inside the kernel with a float32 VMEM
  accumulator.

Kernels run compiled on TPU and in interpreter mode elsewhere (CPU test
mesh), selected automatically.  Packed layout is row-grouped (values
``r*lanes..r*lanes+lanes-1`` of a 128-lane column share one word) — it is
self-consistent between encode/decode but deliberately *not* the byte
layout of the XLA packer; codecs never mix the two in one payload.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import interpret_param

LANE = 128


def use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _rows_for(n: int, bits: int) -> int:
    """Pad element count to whole uint32 words per 128-lane column: rows must
    be a multiple of the level-packing group (32/bits), the sign-packing
    group (32), and the f32 sublane (8) — i.e. of 32."""
    group = int(np.lcm(32 // bits, 32))
    rows = max(1, math.ceil(n / LANE))
    return ((rows + group - 1) // group) * group


# ------------------------------------------------------------------ encode
def _pack(values, width, out_ref):
    lanes = 32 // width
    rows = values.shape[0]
    grouped = values.reshape(rows // lanes, lanes, LANE)
    shifts = jax.lax.broadcasted_iota(jnp.uint32, grouped.shape, 1) * width
    # disjoint bit ranges: signed sum == bitwise-or (Mosaic lacks unsigned
    # reductions, so sum as int32 and bitcast back)
    shifted = pltpu.bitcast(grouped << shifts, jnp.int32)
    out_ref[:] = pltpu.bitcast(
        jnp.sum(shifted, axis=1, dtype=jnp.int32), jnp.uint32
    )


def _qsgd_quantize_and_pack(
    x, rand_bits, packed_ref, signs_ref, scale_ref, level, bits
):
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12)
    scale_ref[0] = scale
    normalized = jnp.abs(x) / scale * level
    floor = jnp.floor(normalized)
    # uniform in [0, 1) from the high 24 bits (via int32: Mosaic has no
    # direct uint32->f32 cast; values < 2^24 so the reinterpret is exact)
    u = pltpu.bitcast(rand_bits >> 8, jnp.int32).astype(jnp.float32) * (
        1.0 / (1 << 24)
    )
    q = pltpu.bitcast(
        (floor + (u < (normalized - floor)).astype(jnp.float32)).astype(jnp.int32),
        jnp.uint32,
    )
    _pack(q, bits, packed_ref)
    _pack(pltpu.bitcast((x < 0).astype(jnp.int32), jnp.uint32), 1, signs_ref)


def _qsgd_encode_kernel_tpu(
    x_ref, seed_ref, packed_ref, signs_ref, scale_ref, *, level: int, bits: int
):
    """On-core PRNG: no random-bit stream materialized in HBM."""
    pltpu.prng_seed(seed_ref[0])
    rand = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
    _qsgd_quantize_and_pack(
        x_ref[:], rand, packed_ref, signs_ref, scale_ref, level, bits
    )


def _qsgd_encode_kernel_hostrand(
    x_ref, rand_ref, packed_ref, signs_ref, scale_ref, *, level: int, bits: int
):
    """Interpreter fallback: the TPU interpreter stubs ``prng_random_bits``
    to zeros, so random bits come in as an input."""
    _qsgd_quantize_and_pack(
        x_ref[:], rand_ref[:], packed_ref, signs_ref, scale_ref, level, bits
    )


@functools.partial(jax.jit, static_argnames=("level", "bits"))
def qsgd_encode(x: jnp.ndarray, seed, level: int, bits: int):
    """Encode a flat float32 array.  Returns (packed_levels [R/lanes, 128]
    uint32, packed_signs, scale[1])."""
    n = x.size
    rows = _rows_for(n, bits)
    padded = jnp.zeros((rows * LANE,), jnp.float32).at[:n].set(
        x.astype(jnp.float32).reshape(-1)
    )
    x2d = padded.reshape(rows, LANE)
    lanes = 32 // bits
    interpret = use_interpret()
    if interpret:
        kernel = _qsgd_encode_kernel_hostrand
        aux = jax.random.bits(
            jax.random.PRNGKey(seed), (rows, LANE), jnp.uint32
        )
        aux_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    else:
        kernel = _qsgd_encode_kernel_tpu
        aux = jnp.asarray([seed], jnp.int32)
        aux_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.pallas_call(
        functools.partial(kernel, level=level, bits=bits),
        out_shape=(
            jax.ShapeDtypeStruct((rows // lanes, LANE), jnp.uint32),
            jax.ShapeDtypeStruct((rows // 32, LANE), jnp.uint32),
            jax.ShapeDtypeStruct((1,), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM), aux_spec],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        interpret=interpret_param(interpret),
    )(x2d, aux)


# ------------------------------------------------------------------ decode
def _qsgd_decode_kernel(
    packed_ref, signs_ref, scale_ref, out_ref, *, level: int, bits: int
):
    def unpack(out_rows, width, ref):
        lanes = 32 // width
        words = ref[:]
        grouped = jnp.broadcast_to(
            words[:, None, :], (words.shape[0], lanes, LANE)
        )
        shifts = jax.lax.broadcasted_iota(jnp.uint32, grouped.shape, 1) * width
        mask = jnp.uint32((1 << width) - 1)
        return ((grouped >> shifts) & mask).reshape(out_rows, LANE)

    rows = out_ref.shape[0]
    q = pltpu.bitcast(unpack(rows, bits, packed_ref), jnp.int32).astype(jnp.float32)
    signs = pltpu.bitcast(unpack(rows, 1, signs_ref), jnp.int32).astype(jnp.float32)
    out_ref[:] = q / level * scale_ref[0] * (1.0 - 2.0 * signs)


@functools.partial(jax.jit, static_argnames=("level", "bits", "n"))
def qsgd_decode(packed, signs, scale, level: int, bits: int, n: int):
    lanes = 32 // bits
    rows = packed.shape[0] * lanes
    out = pl.pallas_call(
        functools.partial(_qsgd_decode_kernel, level=level, bits=bits),
        out_shape=jax.ShapeDtypeStruct((rows, LANE), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret_param(use_interpret()),
    )(packed, signs, scale)
    return out.reshape(-1)[:n]


# --------------------------------------------------------- weighted accum
def _weighted_accum_kernel(x_ref, w_ref, out_ref):
    # x_ref block: [C, rows_blk, 128]; w in SMEM [C]
    clients = x_ref.shape[0]

    def body(c, acc):
        return acc + x_ref[c] * w_ref[c]

    out_ref[:] = jax.lax.fori_loop(
        0, clients, body, jnp.zeros(out_ref.shape, jnp.float32)
    )


@jax.jit
def weighted_accum(stacked: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """``sum_c weights[c] * stacked[c]`` for ``stacked: [C, N]`` without the
    ``[C, N]`` weighted temporary.  Returns float32 ``[N]``."""
    c, n = stacked.shape
    rows = max(8, ((math.ceil(n / LANE) + 7) // 8) * 8)
    if n == rows * LANE:
        padded = stacked.astype(jnp.float32)
    else:
        padded = jnp.zeros((c, rows * LANE), jnp.float32)
        padded = padded.at[:, :n].set(stacked.astype(jnp.float32))
    x3d = padded.reshape(c, rows, LANE)
    blk = min(rows, 512)
    grid = (math.ceil(rows / blk),)
    out = pl.pallas_call(
        _weighted_accum_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANE), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((c, blk, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((blk, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=interpret_param(use_interpret()),
    )(x3d, weights.astype(jnp.float32))
    return out.reshape(-1)[:n]
