"""Transport compression codecs, jit-compiled.

TPU-native equivalents of the reference's external codecs (SURVEY.md §2.13):

* ``stochastic_quantization(level)`` — QSGD-style stochastic uniform
  quantization (``cyy_torch_algorithm.quantization.stochastic``, used by the
  ``StochasticQuant*Endpoint``s with ``quantization_level=255``).
* ``NNADQ(weight)`` — adaptive deterministic quantization
  (``cyy_torch_algorithm.quantization.deterministic``): per-tensor bit-width
  chosen from tensor statistics under a norm-vs-size tradeoff ``weight``,
  deterministic nearest-level rounding, compression-ratio reporting
  (reference logs it via ``check_compression_ratio``,
  ``topology/quantized_endpoint.py:92-95``).

Both operate on pytrees whose leaves are jax arrays; encode/decode are jitted
per-leaf (static shapes), with bit-level packing so the encoded payload's
``nbytes`` reflects the real compressed size.
"""

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .pytree import ParamVecLayout, flatten_params, param_nbytes, split_flat_params

#: reserved leaf name carrying a whole model as one ParamVec in a flat blob
_FLAT_KEY = "__param_vec__"


def _flat_encode_tree(params: dict) -> tuple[dict, ParamVecLayout]:
    """ParamVec entry point shared by the codecs: collapse a flat param
    dict to a single-leaf tree (ONE encode dispatch instead of one per
    tensor); the layout rides in the blob so decode can split back."""
    layout = ParamVecLayout.of(params)
    return {_FLAT_KEY: flatten_params(params)}, layout


def _flat_decode_tree(tree: dict, layout: ParamVecLayout) -> dict:
    return split_flat_params(tree[_FLAT_KEY], layout)


def _flat_encodable(tree: Any) -> bool:
    return (
        isinstance(tree, dict)
        and len(tree) > 1
        and _FLAT_KEY not in tree
        and all(hasattr(v, "shape") and hasattr(v, "dtype") for v in tree.values())
    )


@functools.lru_cache(maxsize=32)
def _segment_ids(layout: ParamVecLayout) -> jnp.ndarray:
    """Per-element tensor index ``[D]`` for a layout, DEVICE-resident and
    cached (flat QSGD keeps per-tensor scales via one segment reduction;
    re-uploading ~4·D bytes per message would tax the very hot path the
    flat payload exists to thin out)."""
    sizes = [
        int(np.prod(shape)) if shape else 1 for shape in layout.shapes
    ]
    return jnp.asarray(
        np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
    )


# ---------------------------------------------------------------- bit packing
def _pack_uint(levels: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Pack unsigned integer levels (< 2**bits) into a uint32 word stream.

    ``32 // bits`` values per word (x64-safe: no uint64 needed on TPU)."""
    lanes = 32 // bits
    flat = levels.astype(jnp.uint32).reshape(-1)
    pad = (-flat.shape[0]) % lanes
    flat = jnp.pad(flat, (0, pad))
    group = flat.reshape(-1, lanes)
    shifts = (jnp.arange(lanes, dtype=jnp.uint32) * bits).astype(jnp.uint32)
    # disjoint bit ranges ⇒ sum == bitwise-or
    return jnp.sum(group << shifts[None, :], axis=1, dtype=jnp.uint32)


def _unpack_uint(packed: jnp.ndarray, bits: int, n: int) -> jnp.ndarray:
    lanes = 32 // bits
    shifts = (jnp.arange(lanes, dtype=jnp.uint32) * bits).astype(jnp.uint32)
    mask = jnp.uint32((1 << bits) - 1)
    values = (packed[:, None] >> shifts[None, :]) & mask
    return values.reshape(-1)[:n].astype(jnp.uint32)


# ------------------------------------------------------- stochastic (QSGD)
def _sq_round(flat: jnp.ndarray, scale, key: jax.Array, level: int):
    """THE QSGD stochastic-rounding step: ``|x| / scale`` snapped to
    ``level`` magnitude levels, round direction drawn from ``key``.
    ``scale`` may be a scalar (per-tensor path) or a per-element vector
    (flat ParamVec path) — one definition, one distortion profile."""
    normalized = jnp.abs(flat) / scale * level
    floor = jnp.floor(normalized)
    prob = normalized - floor
    rnd = jax.random.uniform(key, flat.shape)
    return floor + (rnd < prob).astype(jnp.float32)


def _sq_levels(flat: jnp.ndarray, key: jax.Array, level: int):
    """The QSGD numerics shared by every executor path: abs-max scale +
    stochastic rounding to ``level`` magnitude levels."""
    scale = jnp.maximum(jnp.max(jnp.abs(flat)), 1e-12)
    return _sq_round(flat, scale, key, level), scale


def qsgd_quantize_dequantize(x: jnp.ndarray, key: jax.Array, level: int) -> jnp.ndarray:
    """Quantize→dequantize in one step — the transport numerics without the
    byte packing.  Used by the SPMD fed_paq round program, where 'transport'
    is an ICI collective and only the value distortion matters."""
    flat = x.astype(jnp.float32).reshape(-1)
    q, scale = _sq_levels(flat, key, level)
    out = jnp.sign(flat) * q / level * scale
    return out.reshape(x.shape).astype(x.dtype)


def nnadq_quantize_dequantize(x: jnp.ndarray, weight: float):
    """NNADQ transport numerics without byte packing (see :class:`NNADQ`):
    per-tensor adaptive bit-width from tensor stats, deterministic rounding,
    immediate dequantize.  Returns ``(x_dequantized, bits)`` with ``bits`` a
    traced scalar — used by the SPMD fed_obd round program where 'transport'
    is an ICI collective and only the distortion + the analytic payload size
    matter."""
    flat = x.astype(jnp.float32).reshape(-1)
    std = jnp.std(flat)
    # closed-form bit choice (NNADQ._choose_bits), traced: 2^b = 32 ln2 std/w
    b = jnp.log2(jnp.maximum(32.0 * math.log(2.0) * std / weight, 1.0) + 1.0)
    # ceiling 16, not 8: value-quantizing whole parameter tensors (FedOBD
    # uploads/broadcasts) needs a step finer than one round's parameter
    # delta, or deterministic rounding snaps the update away and training
    # stalls — at weight=1e-3 the closed form asks for ~10 bits
    bits = jnp.clip(jnp.round(b), 2, 16)
    levels = 2.0**bits - 1.0
    lo = jnp.min(flat)
    span = jnp.maximum(jnp.max(flat) - lo, 1e-12)
    q = jnp.round((flat - lo) / span * levels)
    out = (q / levels * span + lo).reshape(x.shape).astype(x.dtype)
    return out, bits


@functools.partial(jax.jit, static_argnums=(2, 3))
def _sq_encode_leaf(x: jnp.ndarray, key: jax.Array, level: int, bits: int):
    flat = x.astype(jnp.float32).reshape(-1)
    q, scale = _sq_levels(flat, key, level)
    sign_bits = (flat < 0).astype(jnp.uint32)
    packed = _pack_uint(q.astype(jnp.uint32), bits)
    packed_signs = _pack_uint(sign_bits, 1)
    return packed, packed_signs, scale


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _sq_decode_leaf(packed, packed_signs, scale, level: int, bits: int, n: int):
    q = _unpack_uint(packed, bits, n).astype(jnp.float32)
    signs = _unpack_uint(packed_signs, 1, n).astype(jnp.float32)
    magnitude = q / level * scale
    return magnitude * (1.0 - 2.0 * signs)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _sq_encode_flat(vec, seg_ids, key, level: int, bits: int, num_segments: int):
    """Whole-model QSGD as ONE program with PER-TENSOR scales: the abs-max
    scale is a segment reduction over the layout, so a layernorm bias is
    never quantized against an embedding's magnitude (a single global
    scale would bury small tensors in rounding noise)."""
    flat = vec.astype(jnp.float32)
    seg_scales = jax.ops.segment_max(
        jnp.abs(flat), seg_ids, num_segments=num_segments
    )
    seg_scales = jnp.maximum(seg_scales, 1e-12)
    q = _sq_round(flat, seg_scales[seg_ids], key, level)
    packed = _pack_uint(q.astype(jnp.uint32), bits)
    packed_signs = _pack_uint((flat < 0).astype(jnp.uint32), 1)
    return packed, packed_signs, seg_scales


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _sq_decode_flat(packed, packed_signs, seg_scales, seg_ids, level: int, bits: int, n: int):
    q = _unpack_uint(packed, bits, n).astype(jnp.float32)
    signs = _unpack_uint(packed_signs, 1, n).astype(jnp.float32)
    magnitude = q / level * seg_scales[seg_ids]
    return magnitude * (1.0 - 2.0 * signs)


def stochastic_quantization(quantization_level: int = 255, use_pallas: bool | None = None):
    """Return ``(quant, dequant)`` closures over pytrees (reference surface:
    ``stochastic_quantization(quantization_level=255)``).

    ``use_pallas=None`` auto-selects: the fused single-pass Pallas kernel
    (``ops/pallas_kernels.py``) on TPU, the multi-program XLA path
    elsewhere.  Both produce QSGD payloads with the same compression
    ratio; their packed byte layouts differ, so each encoded leaf records
    which packer produced it (``"pallas"`` per-leaf flag) and decode
    follows that."""
    bits = max(1, math.ceil(math.log2(quantization_level + 1)))
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    def quant(
        tree: Any, seed: int = 0, key=None, fold_indices=None, flat: bool = False
    ) -> dict:
        """``flat=True`` is the ParamVec entry point: the whole param dict
        is encoded as ONE flat vector leaf — one packed stream, one
        dispatch instead of one per tensor — while the abs-max scales
        stay PER TENSOR (a segment reduction over the layout), so flat
        encoding does not change the codec's distortion profile.  The
        layout rides in the blob for decode.  Ignored when an aligned
        ``key`` is supplied: the cross-executor parity rules (fed_paq
        split-per-leaf, fed_obd_sq fold-by-position) are defined per
        tensor.

        ``key`` (a jax PRNGKey) overrides the integer seed: per-leaf
        keys come from ``split(key, n_leaves)`` — EXACTLY the stream the
        SPMD in-program codec draws (``parallel/spmd.py`` local_train),
        which is what cross-executor fed_paq codec parity needs.  With
        ``fold_indices`` (a name → position map over the FULL parameter
        dict), per-leaf keys come from ``fold_in(key, position)`` instead
        — the FedOBD in-program rule, where a kept-block subset must
        still draw each leaf's key by its global position
        (``parallel/spmd_obd.py`` local_train).  The pallas packer has
        its own integer-seed rng, so the key paths pin the XLA leaf
        encoder."""
        from . import pallas_kernels as pk

        if flat and key is None and _flat_encodable(tree):
            vec_tree, layout = _flat_encode_tree(tree)
            seg_ids = _segment_ids(layout)
            packed, packed_signs, seg_scales = _sq_encode_flat(
                vec_tree[_FLAT_KEY],
                seg_ids,
                jax.random.PRNGKey(seed),
                quantization_level,
                bits,
                len(layout.keys),
            )
            _, treedef = jax.tree.flatten(vec_tree)
            return {
                "treedef": treedef,
                "leaves": [
                    {
                        "packed": packed,
                        "signs": packed_signs,
                        "scales": seg_scales,  # [T] per-tensor abs-max
                        "shape": (layout.size,),
                        "dtype": "float32",
                        "pallas": False,
                    }
                ],
                "level": quantization_level,
                "flat_layout": layout,
            }
        leaves, treedef = jax.tree.flatten(tree)
        if key is not None and fold_indices is not None:
            names = sorted(tree) if isinstance(tree, dict) else []
            assert len(names) == len(leaves), "fold_indices needs a flat dict"
            keys = [
                jax.random.fold_in(key, fold_indices[name])
                for name in names
            ]
        elif key is not None:
            keys = jax.random.split(key, max(1, len(leaves)))
        else:
            keys = jax.random.split(
                jax.random.PRNGKey(seed), max(1, len(leaves))
            )
        encoded = []
        for i, (leaf, key_i) in enumerate(zip(leaves, keys)):
            leaf = jnp.asarray(leaf)
            # the pallas packer pads each leaf to whole (32, 128) tiles
            # (worst case 4096 elements) — only worth it for leaves where
            # that padding is noise (<~6%)
            leaf_pallas = (
                key is None and use_pallas and leaf.size >= 16 * 32 * 128
            )
            if leaf_pallas:
                packed, packed_signs, scale = pk.qsgd_encode(
                    leaf,
                    seed=(seed * 100003 + i) % 0x7FFFFFFF,  # keep int32-safe
                    level=quantization_level,
                    bits=bits,
                )
            else:
                packed, packed_signs, scale = _sq_encode_leaf(
                    leaf, key_i, quantization_level, bits
                )
            encoded.append(
                {
                    "packed": packed,
                    "signs": packed_signs,
                    "scale": scale,
                    "shape": leaf.shape,
                    "dtype": str(leaf.dtype),
                    "pallas": leaf_pallas,
                }
            )
        return {"treedef": treedef, "leaves": encoded, "level": quantization_level}

    def dequant(blob: dict) -> Any:
        from . import pallas_kernels as pk

        decoded = []
        flat_layout = blob.get("flat_layout")
        for enc in blob["leaves"]:
            n = int(np.prod(enc["shape"])) if enc["shape"] else 1
            if "scales" in enc:
                flat = _sq_decode_flat(
                    enc["packed"], enc["signs"], enc["scales"],
                    _segment_ids(flat_layout),
                    blob["level"], bits, n,
                )
            elif enc.get("pallas"):
                flat = pk.qsgd_decode(
                    enc["packed"], enc["signs"], enc["scale"],
                    level=blob["level"], bits=bits, n=n,
                )
            else:
                flat = _sq_decode_leaf(
                    enc["packed"], enc["signs"], enc["scale"], blob["level"], bits, n
                )
            decoded.append(flat.reshape(enc["shape"]).astype(enc["dtype"]))
        tree = jax.tree.unflatten(blob["treedef"], decoded)
        layout = blob.get("flat_layout")
        if layout is not None:
            return _flat_decode_tree(tree, layout)
        return tree

    return quant, dequant


# ------------------------------------------- adaptive deterministic (NNADQ)
@functools.partial(jax.jit, static_argnums=(1,))
def _adq_encode_leaf(x: jnp.ndarray, bits: int):
    flat = x.astype(jnp.float32).reshape(-1)
    lo = jnp.min(flat)
    hi = jnp.max(flat)
    span = jnp.maximum(hi - lo, 1e-12)
    levels = (1 << bits) - 1
    q = jnp.round((flat - lo) / span * levels)  # deterministic rounding
    packed = _pack_uint(q.astype(jnp.uint32), bits)
    return packed, lo, span


@functools.partial(jax.jit, static_argnums=(3, 4))
def _adq_decode_leaf(packed, lo, span, bits: int, n: int):
    levels = (1 << bits) - 1
    q = _unpack_uint(packed, bits, n).astype(jnp.float32)
    return q / levels * span + lo


class NNADQ:
    """Neural-Network Adaptive Deterministic Quantization.

    The tradeoff ``weight`` balances payload size against quantization
    error: per tensor, bit-width ``b`` minimizes
    ``E_q(b) + weight * b/32`` where ``E_q(b) ≈ std(x) / 2^b`` is the
    expected rounding error — larger ``weight`` penalizes size harder and
    yields fewer bits.  Solved in closed form (``2^b = 32 ln2 · std /
    weight``) and clamped to [2, 16].
    """

    def __init__(self, weight: float = 0.01) -> None:
        self.weight = float(weight)
        self.last_compression_ratio: float | None = None

    def _choose_bits(self, std: float) -> int:
        if std <= 0:
            return 2
        b = math.log2(max(32.0 * math.log(2.0) * std / self.weight, 1.0) + 1.0)
        # see nnadq_quantize_dequantize: 8-bit ceiling coarser than a round's
        # parameter delta stalls FedOBD value uploads
        return int(min(16, max(2, round(b))))

    def quant(self, tree: Any, flat: bool = False) -> dict:
        """``flat=True``: ParamVec entry point — one bit-width chosen from
        the whole vector's stats, one packed stream (collapses the
        per-tensor dispatch count; trades away per-tensor bit adaptivity,
        which is why the NNADQ endpoints keep per-tensor by default)."""
        if flat and _flat_encodable(tree):
            vec_tree, layout = _flat_encode_tree(tree)
            blob = self.quant(vec_tree)
            blob["flat_layout"] = layout
            return blob
        leaves, treedef = jax.tree.flatten(tree)
        stds = [float(jnp.std(jnp.asarray(leaf))) for leaf in leaves]
        encoded = []
        for leaf, std in zip(leaves, stds):
            leaf = jnp.asarray(leaf)
            bits = self._choose_bits(std)
            packed, lo, span = _adq_encode_leaf(leaf, bits)
            encoded.append(
                {
                    "packed": packed,
                    "lo": lo,
                    "span": span,
                    "bits": bits,
                    "shape": leaf.shape,
                    "dtype": str(leaf.dtype),
                }
            )
        return {"treedef": treedef, "leaves": encoded}

    def dequant(self, blob: dict) -> Any:
        decoded = []
        for enc in blob["leaves"]:
            n = int(np.prod(enc["shape"])) if enc["shape"] else 1
            flat = _adq_decode_leaf(enc["packed"], enc["lo"], enc["span"], enc["bits"], n)
            decoded.append(flat.reshape(enc["shape"]).astype(enc["dtype"]))
        tree = jax.tree.unflatten(blob["treedef"], decoded)
        layout = blob.get("flat_layout")
        if layout is not None:
            return _flat_decode_tree(tree, layout)
        return tree

    def __call__(self, tree: Any) -> dict:
        return self.quant(tree)


def check_compression_ratio(original: Any, encoded: dict) -> float:
    """Compressed bytes / original bytes (reference
    ``NeuralNetworkAdaptiveDeterministicQuant.check_compression_ratio``)."""
    original_bytes = max(1, param_nbytes(original))
    encoded_bytes = 0
    for enc in encoded["leaves"]:
        for key in ("packed", "signs"):
            if key in enc:
                encoded_bytes += int(enc[key].nbytes)
        if "scales" in enc:  # flat ParamVec leaf: [T] per-tensor scales
            encoded_bytes += int(enc["scales"].nbytes)
        else:
            encoded_bytes += 8  # scalar scale/offset
    return encoded_bytes / original_bytes
