"""Fused multi-head attention as a first-party Pallas TPU kernel.

The reference's transformer classifiers run attention through torch's
softmax(QK^T)V with the [B, H, Tq, Tk] score matrix materialized in HBM
(reference models come from ``cyy_torch_text``, SURVEY.md §2.13).  This
kernel computes the whole attention — scores, masking, softmax, and the
value contraction — in one VMEM pass per query block, so the [Tq, Tk]
scores never touch HBM.  It is the LONG-SEQUENCE hot op: measured on the
v5e (BASELINE.md), XLA's batched-matmul attention is faster below
T≈1024 (the kernel's many small grid steps lose to one fat batched
matmul), at parity around 1–2k, and behind by 1.4×+ at 8k where score
materialization saturates HBM — so ``attention_fn`` gates the kernel to
``MIN_FUSED_T ≤ T ≤ MAX_FUSED_T`` and the zoo's short-sequence encoders
(ViT seq 64, IMDB seq 300) keep the XLA path.

Design (deliberately simpler than a streaming/online-softmax kernel): one
level of blocking.  The grid is ``(batch*heads, q_blocks)``; each step
loads one [blk, D] query block plus the FULL [T, D] key/value rows for
that (batch, head) into VMEM and runs an exact softmax over the complete
key axis — no streaming recurrence needed.  The query block height adapts
to the sequence (``_pick_blk``: fat blocks at short T for fewer grid
steps, 128-row blocks at the long end).  Full K/V rows in VMEM bound the
fusable sequence (``MAX_FUSED_T``); beyond that the sequence-parallel
path (``parallel/ring_attention.py``) shards T over the mesh and each
device's local block lands back inside this bound.

The backward pass is two Pallas kernels (recompute-style, the standard
flash-attention adjoint): ``dq`` re-forms each query block's probabilities
from the saved log-sum-exp and contracts against K/V; ``dkv`` walks key
blocks against the full query axis.  ``delta = rowsum(dO * O)`` is a cheap
elementwise XLA op outside the kernels.

Integration: ``attention_fn`` is a drop-in for
``flax.linen.MultiHeadDotProductAttention(attention_fn=...)`` — same
parameter tree, kwargs filtered by signature.  It falls back to flax's
``dot_product_attention`` whenever the kernel doesn't apply (attention-
probability dropout active, a mask that isn't a pure key-padding mask,
head_dim > 128, T > MAX_FUSED_T, or a non-TPU backend — the interpreter
is far too slow for the CPU test mesh, where the XLA path is used
instead; set ``DLS_TPU_FUSED_ATTN=interpret`` to force the kernel under
the Pallas interpreter for kernel tests).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
MIN_FUSED_T = 1024  # below this XLA's batched-matmul attention is faster
MAX_FUSED_T = 8192  # full K/V rows per (batch, head) must fit VMEM
_S_VMEM_BYTES = 2 * 1024 * 1024  # budget for one [blk, T] f32 score block
_NEG_INF = -1e30


def _pick_blk(t_pad: int) -> int:
    """Largest 128-multiple row block that DIVIDES ``t_pad`` (the grid is
    ``t_pad // blk`` steps — a non-divisor would silently drop trailing
    query rows) and whose [blk, T] f32 score tile fits the VMEM budget —
    fewer, fatter grid steps at short T; 128-row steps at the long end."""
    cap = max(128, (_S_VMEM_BYTES // (t_pad * 4)) // 128 * 128)
    blk = min(t_pad, cap)
    while t_pad % blk:
        blk -= 128
    return blk


def _mode() -> str:
    """'tpu' (compiled), 'interpret' (forced for kernel tests), or 'off'."""
    if jax.default_backend() == "tpu":
        return "tpu"
    if os.environ.get("DLS_TPU_FUSED_ATTN") == "interpret":
        return "interpret"
    return "off"


def _interp(interpret: bool):
    return pltpu.InterpretParams() if interpret else False


# ----------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *, scale, causal):
    blk = q_ref.shape[1]
    q = q_ref[0]  # [blk, D]
    k = k_ref[0]  # [T, D]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [BLK, T]
    valid = (mask_ref[0] != 0.0)  # [1, T] -> broadcasts over rows
    if causal:
        q_pos = pl.program_id(1) * blk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0
        )
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = valid & (q_pos >= k_pos)
    s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [blk, 1]
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / jnp.maximum(l, 1e-30)
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).reshape(1, -1)


def _fwd(q3, k3, v3, mask2, heads, scale, causal, interpret):
    bh, t, d = q3.shape
    blk = _pick_blk(t)
    grid = (bh, t // blk)
    kv_spec = pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0)),
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, 1, t), lambda b, i: (b // heads, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, blk), lambda b, i: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ),
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2)
    return out, lse


# ---------------------------------------------------------------- backward
def _dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, causal,
):
    blk = q_ref.shape[1]
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [blk, T]
    valid = (mask_ref[0] != 0.0)
    if causal:
        q_pos = pl.program_id(1) * blk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0
        )
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = valid & (q_pos >= k_pos)
    lse = lse_ref[0].reshape(-1, 1)  # [blk, 1]
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [blk, T]
    delta = delta_ref[0].reshape(-1, 1)
    ds = p * (dp - delta)
    dq = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, kmask_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal,
):
    j = pl.program_id(1)
    blk = k_ref.shape[1]
    q = q_ref[0]  # [T, D] full query rows
    k = k_ref[0]  # [blk, D] one key block
    v = v_ref[0]
    do = do_ref[0]  # [T, D]
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [blk, T] = scores transposed (keys x queries)
    # kmask_ref is blocked per KEY block: [1, BLK] validity of these keys
    # (reshape the f32 mask, not the i1 compare result — Mosaic only
    # supports minor-dim-inserting reshapes for 32-bit types)
    valid = kmask_ref[0].reshape(-1, 1) != 0.0
    if causal:
        k_pos = j * blk + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 0)
        q_pos = jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 1)
        valid = valid & (q_pos >= k_pos)
    lse = lse_ref[0]  # [1, T] per-query normalizers
    p_t = jnp.where(valid, jnp.exp(s_t - lse), 0.0)  # [blk, T]
    dv = jax.lax.dot_general(
        p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [blk, D]
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [blk, T]
    delta = delta_ref[0]  # [1, T]
    ds_t = p_t * (dp_t - delta)
    dk = jax.lax.dot_general(
        ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q3, k3, v3, mask2, out3, lse, do3, heads, scale, causal, interpret):
    bh, t, d = q3.shape
    delta = jnp.sum(
        do3.astype(jnp.float32) * out3.astype(jnp.float32), axis=-1
    )[:, None, :]
    blk = _pick_blk(t)
    q_spec = pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0))
    full_spec = pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0))
    mask_spec = pl.BlockSpec((1, 1, t), lambda b, i: (b // heads, 0, 0))
    row_blk_spec = pl.BlockSpec((1, 1, blk), lambda b, i: (b, 0, i))
    row_full_spec = pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal),
        grid=(bh, t // blk),
        in_specs=[q_spec, full_spec, full_spec, mask_spec, q_spec,
                  row_blk_spec, row_blk_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2, do3, lse, delta)
    kmask_spec = pl.BlockSpec((1, 1, blk), lambda b, j: (b // heads, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal),
        grid=(bh, t // blk),
        in_specs=[full_spec,
                  pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
                  kmask_spec, full_spec, row_full_spec, row_full_spec],
        out_specs=(
            pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ),
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2, do3, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _attend(q3, k3, v3, mask2, heads, scale, causal, interpret):
    out, _ = _fwd(q3, k3, v3, mask2, heads, scale, causal, interpret)
    return out


def _attend_fwd(q3, k3, v3, mask2, heads, scale, causal, interpret):
    out, lse = _fwd(q3, k3, v3, mask2, heads, scale, causal, interpret)
    return out, (q3, k3, v3, mask2, out, lse)


def _attend_bwd(heads, scale, causal, interpret, res, do3):
    q3, k3, v3, mask2, out, lse = res
    dq, dk, dv = _bwd(
        q3, k3, v3, mask2, out, lse, do3, heads, scale, causal, interpret
    )
    return dq, dk, dv, None


_attend.defvjp(_attend_fwd, _attend_bwd)


def fused_attention(q, k, v, kv_mask=None, causal: bool = False):
    """Exact fused attention.  ``q/k/v: [B, T, H, D]`` (flax head layout),
    ``kv_mask: [B, T]`` key-padding mask (True = attend) or None.  The
    caller is responsible for eligibility (see :func:`kernel_eligible`);
    callers wanting automatic gating + fallback use :func:`attention_fn`."""
    mode = _mode()
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    t_pad = max(128, ((t + 127) // 128) * 128)
    # K/V loads and dq/dk/dv writes pay for padded D bytes: pad only to the
    # MXU's minimum useful contraction width, not always to a full lane
    d_pad = 64 if d <= 64 else LANE if d <= LANE else d

    def to3(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)
        return jnp.pad(x, ((0, 0), (0, t_pad - t), (0, d_pad - d)))

    q3, k3, v3 = to3(q), to3(k), to3(v)
    mask = jnp.ones((b, t), jnp.float32) if kv_mask is None else kv_mask.astype(
        jnp.float32
    )
    mask2 = jnp.pad(mask, ((0, 0), (0, t_pad - t)))[:, None, :]
    out = _attend(q3, k3, v3, mask2, h, scale, causal, mode == "interpret")
    out = out[:, :t, :d].reshape(b, h, t, d)
    return jnp.transpose(out, (0, 2, 1, 3))


_VMEM_BUDGET = 15 * 1024 * 1024  # leave headroom under the 16 MB scoped limit


def kernel_eligible(t: int, d: int, itemsize: int = 2) -> bool:
    """Shape/backend eligibility for the kernel itself.  The MIN_FUSED_T
    gate is a measured perf crossover (BASELINE.md: below ~1024 XLA's
    batched-matmul attention wins on step-overhead; at/above it the fused
    kernel is at parity and pulls ahead with T) and applies only to the
    compiled TPU path — the interpreter mode exists for correctness tests
    at small shapes.  The VMEM model mirrors what Mosaic stack-allocates
    per grid step (measured on the v5e): full K/V rows plus ~4 [blk, T]
    f32 score-sized temporaries — f32 inputs at seq 8k exceed the 16 MB
    scoped limit where bf16 fits, so eligibility is dtype-aware.  The
    coefficients are anchored on measured compiles: bf16 T=8192 d=64
    fits (14.7 MB est.), f32 T=8192 OOMs (16.8 MB est. vs the observed
    16.5 MB allocation), bf16 T=16384 d_pad=128 OOMs."""
    mode = _mode()
    if mode == "off":
        return False
    if d > LANE or t > MAX_FUSED_T:
        return False
    if mode == "tpu" and t < MIN_FUSED_T:
        return False
    t_pad = max(128, ((t + 127) // 128) * 128)
    d_pad = 64 if d <= 64 else LANE
    kv_bytes = 2 * t_pad * d_pad * itemsize
    temp_bytes = 3 * _pick_blk(t_pad) * t_pad * 4
    return kv_bytes + temp_bytes <= _VMEM_BUDGET


def eligible(q, mask, dropout_rate: float, deterministic: bool, k=None) -> bool:
    """Can the Pallas kernel serve this ``attention_fn`` call?
    (Attention-probability dropout, cross-attention, and q- or
    head-dependent masks fall back.)"""
    if dropout_rate > 0.0 and not deterministic:
        return False  # in-kernel prob-dropout not implemented; XLA path
    if q.ndim != 4 or not kernel_eligible(
        q.shape[1], q.shape[3], q.dtype.itemsize
    ):
        return False
    if k is not None and k.shape[1] != q.shape[1]:
        return False  # cross-attention (T_kv != T_q): XLA path
    if mask is not None and (
        mask.ndim != 4 or mask.shape[-2] != 1 or mask.shape[-3] != 1
    ):
        return False  # not a pure key-padding mask (q- or head-dependent)
    return True


def attention_fn(
    query,
    key,
    value,
    mask=None,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    broadcast_dropout: bool = True,
    deterministic: bool = True,
    dtype=None,
    precision=None,
):
    """Drop-in ``attention_fn`` for ``nn.MultiHeadDotProductAttention``:
    routes to the fused Pallas kernel when eligible, otherwise to flax's
    reference ``dot_product_attention`` (bit-for-bit the default path)."""
    if eligible(query, mask, dropout_rate, deterministic, k=key):
        kv_mask = None
        if mask is not None:
            # [B, 1, 1, T] (or broadcastable) key-padding mask -> [B, T]
            kv_mask = jnp.broadcast_to(
                mask, (query.shape[0], 1, 1, key.shape[1])
            )[:, 0, 0, :]
        return fused_attention(query, key, value, kv_mask=kv_mask)
    import flax.linen as nn

    return nn.dot_product_attention(
        query,
        key,
        value,
        mask=mask,
        dropout_rng=dropout_rng,
        dropout_rate=dropout_rate,
        broadcast_dropout=broadcast_dropout,
        deterministic=deterministic,
        dtype=dtype,
        precision=precision,
    )
