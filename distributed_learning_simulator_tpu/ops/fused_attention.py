"""Fused multi-head attention as a first-party Pallas TPU kernel.

The reference's transformer classifiers run attention through torch's
softmax(QK^T)V with the [B, H, Tq, Tk] score matrix materialized in HBM
(reference models come from ``cyy_torch_text``, SURVEY.md §2.13).  This
kernel computes the whole attention — scores, masking, softmax, and the
value contraction — in one VMEM pass per query block, so the [Tq, Tk]
scores never touch HBM.  It is the LONG-SEQUENCE hot op: measured on the
v5e (BASELINE.md), XLA's batched-matmul attention is faster below
T≈1024 (the kernel's many small grid steps lose to one fat batched
matmul), at parity around 1–2k, and behind by 1.4×+ at 8k where score
materialization saturates HBM — so ``attention_fn`` gates the kernel to
``MIN_FUSED_T ≤ T ≤ MAX_FUSED_T`` and the zoo's short-sequence encoders
(ViT seq 64, IMDB seq 300) keep the XLA path.

Two kernel tiers (``kernel_tier`` picks per shape/dtype):

* **one-level** (``"fused"``): the grid is ``(batch*heads, q_blocks)``;
  each step loads one [blk, D] query block plus the FULL [T, D] key/value
  rows for that (batch, head) into VMEM and runs an exact softmax over
  the complete key axis — no streaming recurrence.  The query block
  height adapts to the sequence (``_pick_blk``: fat blocks at short T for
  fewer grid steps, 128-row blocks at the long end).  Fastest tier, but
  full K/V rows in VMEM bound it (``MAX_FUSED_T``, dtype-aware model).
* **streaming** (``"stream"``): the classic online-softmax walk — grid
  ``(batch*heads, q_blocks, kv_blocks)`` with running (acc, m, l) VMEM
  scratch, so VMEM is O(blk²) regardless of T.  Extends the single-chip
  fusable sequence to ``MAX_STREAM_T`` (measured on the v5e: seq 16384
  trains end-to-end at 165 ms/step, seq 32768 fwd+bwd 163 ms raw, where
  both XLA attention and the one-level tier OOM).

Beyond ``MAX_STREAM_T`` the sequence-parallel path
(``parallel/ring_attention.py``) shards T over the mesh and each
device's local block lands back inside these bounds.

The backward pass is two Pallas kernels (recompute-style, the standard
flash-attention adjoint): ``dq`` re-forms each query block's probabilities
from the saved log-sum-exp and contracts against K/V; ``dkv`` walks key
blocks against the full query axis.  ``delta = rowsum(dO * O)`` is a cheap
elementwise XLA op outside the kernels.

Integration: ``attention_fn`` is a drop-in for
``flax.linen.MultiHeadDotProductAttention(attention_fn=...)`` — same
parameter tree, kwargs filtered by signature.  It falls back to flax's
``dot_product_attention`` whenever the kernel doesn't apply (attention-
probability dropout active, a mask that isn't a pure key-padding mask,
head_dim > 128, T > MAX_STREAM_T, or a non-TPU backend — the interpreter
is far too slow for the CPU test mesh, where the XLA path is used
instead; set ``DLS_TPU_FUSED_ATTN=interpret`` to force the kernel under
the Pallas interpreter for kernel tests, or ``=off`` to kill the kernel
everywhere).

Sharded-context note: inside ``shard_map`` (the ring/Ulysses path) the
kernels see per-device blocks and compose cleanly.  Inside a
GSPMD-partitioned ``jit`` (``model_parallel`` TP), XLA treats a Pallas
call as opaque and will all-gather sharded operands to run it replicated
— correct but unprofitable, and the *interpreter* variant (an
``io_callback``) cannot be partitioned at all; prefer shard_map contexts
for sharded attention, or ``DLS_TPU_FUSED_ATTN=off`` under TP.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_compat import interpret_param

LANE = 128
MIN_FUSED_T = 1024  # below this XLA's batched-matmul attention is faster
MAX_FUSED_T = 8192  # full K/V rows per (batch, head) must fit VMEM
MAX_STREAM_T = 32768  # streaming tier: K/V walked block-by-block from HBM
_S_VMEM_BYTES = 2 * 1024 * 1024  # budget for one [blk, T] f32 score block
_STREAM_BLK = 512  # q/kv block edge for the streaming tier
_NEG_INF = -1e30


def _divisor_blk(t_pad: int, cap: int) -> int:
    """Largest 128-multiple row block ≤ cap that DIVIDES ``t_pad`` (the
    grid is ``t_pad // blk`` steps — a non-divisor would silently drop
    trailing rows)."""
    blk = min(t_pad, max(128, cap))
    while t_pad % blk:
        blk -= 128
    return blk


def _pick_blk(t_pad: int) -> int:
    """One-level tier: fattest block whose [blk, T] f32 score tile fits the
    VMEM budget — fewer grid steps at short T; 128-row steps at the long
    end."""
    return _divisor_blk(t_pad, (_S_VMEM_BYTES // (t_pad * 4)) // 128 * 128)


def _mode() -> str:
    """'tpu' (compiled), 'interpret' (forced for kernel tests), or 'off'.

    ``DLS_TPU_FUSED_ATTN=off`` is the operator kill switch — every caller
    gates through :func:`kernel_tier`, so setting it routes ALL attention
    back to the XLA paths (flax / dense / jnp ring)."""
    env = os.environ.get("DLS_TPU_FUSED_ATTN", "")
    if env == "off":
        return "off"
    if env == "interpret":
        # explicit override wins even on a TPU backend (kernel debugging)
        return "interpret"
    if jax.default_backend() == "tpu":
        return "tpu"
    return "off"


def _interp(interpret: bool):
    return interpret_param(interpret)


# ----------------------------------------------------------------- forward
def _masked_scores(
    rows, cols, kmask_row, scale, causal, row_off, col_off, keys_on_rows
):
    """Scores + validity for one tile — THE single definition of the
    masking semantics shared by all six kernels (forward/dq/dkv in both
    tiers).  ``rows @ cols^T * scale``; ``kmask_row`` is the [1, N_keys]
    f32 key-padding row for the tile's KEY side (compared against 0.0
    AFTER any reshape — Mosaic only supports minor-dim-inserting reshapes
    for 32-bit types, not i1); causal masking reconstructs global
    positions from the tile offsets, with q/k roles swapped when the tile
    is key-major (``keys_on_rows``)."""
    s = jax.lax.dot_general(
        rows, cols, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if keys_on_rows:
        valid = kmask_row.reshape(-1, 1) != 0.0  # [BK, 1] over rows
    else:
        valid = kmask_row != 0.0  # [1, BK] broadcasts over rows
    if causal:
        r_pos = row_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        c_pos = col_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        q_pos, k_pos = (c_pos, r_pos) if keys_on_rows else (r_pos, c_pos)
        valid = valid & (q_pos >= k_pos)
    return s, valid


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *, scale, causal):
    blk = q_ref.shape[1]
    q = q_ref[0]  # [blk, D]
    k = k_ref[0]  # [T, D]
    v = v_ref[0]
    s, valid = _masked_scores(
        q, k, mask_ref[0], scale, causal, pl.program_id(1) * blk, 0, False
    )  # [blk, T]
    s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [blk, 1]
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / jnp.maximum(l, 1e-30)
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).reshape(1, -1)


def _fwd(q3, k3, v3, mask2, heads, scale, causal, interpret):
    bh, t, d = q3.shape
    blk = _pick_blk(t)
    grid = (bh, t // blk)
    kv_spec = pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0)),
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, 1, t), lambda b, i: (b // heads, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, blk), lambda b, i: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ),
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2)
    return out, lse


# ---------------------------------------------------------------- backward
def _dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, causal,
):
    blk = q_ref.shape[1]
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s, valid = _masked_scores(
        q, k, mask_ref[0], scale, causal, pl.program_id(1) * blk, 0, False
    )  # [blk, T]
    lse = lse_ref[0].reshape(-1, 1)  # [blk, 1]
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [blk, T]
    delta = delta_ref[0].reshape(-1, 1)
    ds = p * (dp - delta)
    dq = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, kmask_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal,
):
    j = pl.program_id(1)
    blk = k_ref.shape[1]
    q = q_ref[0]  # [T, D] full query rows
    k = k_ref[0]  # [blk, D] one key block
    v = v_ref[0]
    do = do_ref[0]  # [T, D]
    # kmask_ref is blocked per KEY block: [1, blk] validity of these keys
    s_t, valid = _masked_scores(
        k, q, kmask_ref[0], scale, causal, j * blk, 0, True
    )  # [blk, T] = scores transposed (keys x queries)
    lse = lse_ref[0]  # [1, T] per-query normalizers
    p_t = jnp.where(valid, jnp.exp(s_t - lse), 0.0)  # [blk, T]
    dv = jax.lax.dot_general(
        p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [blk, D]
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [blk, T]
    delta = delta_ref[0]  # [1, T]
    ds_t = p_t * (dp_t - delta)
    dk = jax.lax.dot_general(
        ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q3, k3, v3, mask2, out3, lse, do3, heads, scale, causal, interpret,
         dlse=None):
    bh, t, d = q3.shape
    delta = jnp.sum(
        do3.astype(jnp.float32) * out3.astype(jnp.float32), axis=-1
    )[:, None, :]
    if dlse is not None:
        # lse-output cotangent: d lse_i/d s_ij = p_ij, so it folds into the
        # SAME ds = p*(dp - delta') recurrence with delta' = delta - dlse
        delta = delta - dlse
    blk = _pick_blk(t)
    q_spec = pl.BlockSpec((1, blk, d), lambda b, i: (b, i, 0))
    full_spec = pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0))
    mask_spec = pl.BlockSpec((1, 1, t), lambda b, i: (b // heads, 0, 0))
    row_blk_spec = pl.BlockSpec((1, 1, blk), lambda b, i: (b, 0, i))
    row_full_spec = pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal),
        grid=(bh, t // blk),
        in_specs=[q_spec, full_spec, full_spec, mask_spec, q_spec,
                  row_blk_spec, row_blk_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2, do3, lse, delta)
    kmask_spec = pl.BlockSpec((1, 1, blk), lambda b, j: (b // heads, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal),
        grid=(bh, t // blk),
        in_specs=[full_spec,
                  pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
                  pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
                  kmask_spec, full_spec, row_full_spec, row_full_spec],
        out_specs=(
            pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, blk, d), lambda b, j: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ),
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2, do3, lse, delta)
    return dq, dk, dv


# ------------------------------------------------- streaming tier (long T)
# Beyond the one-level tier's VMEM bound the kernels walk K/V block-by-block
# from HBM with the online-softmax recurrence — VMEM is O(blk^2) regardless
# of T, extending the single-chip fusable sequence to MAX_STREAM_T.


def _fwd_stream_kernel(
    q_ref, k_ref, v_ref, kmask_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale, causal, nk,
):
    kidx = pl.program_id(2)

    @pl.when(kidx == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0]  # [BQ, D]
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]
    s, valid = _masked_scores(
        q, k, kmask_ref[0], scale, causal,
        pl.program_id(1) * q.shape[0], kidx * k.shape[0], False,
    )  # [BQ, BK]
    s = jnp.where(valid, s, _NEG_INF)
    # m/l scratch is [BQ, 128] with every lane holding the row value (the
    # 128-lane layout Mosaic wants for narrow per-row state)
    m_old = m_ref[:, :1]  # [BQ, 1]
    m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_old - m_new)
    l_ref[...] = l_ref[...] * alpha + jnp.broadcast_to(
        jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(kidx == nk - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:, :1] + jnp.log(l)).reshape(1, -1)


def _dq_stream_kernel(
    q_ref, k_ref, v_ref, kmask_ref, do_ref, lse_ref, delta_ref, dq_ref,
    dq_acc_ref, *, scale, causal, nk,
):
    kidx = pl.program_id(2)

    @pl.when(kidx == 0)
    def _():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s, valid = _masked_scores(
        q, k, kmask_ref[0], scale, causal,
        pl.program_id(1) * q.shape[0], kidx * k.shape[0], False,
    )  # [BQ, BK]
    lse = lse_ref[0].reshape(-1, 1)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    delta = delta_ref[0].reshape(-1, 1)
    ds = p * (dp - delta)
    dq_acc_ref[...] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(kidx == nk - 1)
    def _():
        dq_ref[0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_stream_kernel(
    q_ref, k_ref, v_ref, kmask_ref, do_ref, lse_ref, delta_ref, dk_ref,
    dv_ref, dk_acc_ref, dv_acc_ref, *, scale, causal, nq,
):
    qidx = pl.program_id(2)

    @pl.when(qidx == 0)
    def _():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    q = q_ref[0]  # [BQ, D]
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]
    do = do_ref[0]  # [BQ, D]
    s_t, valid = _masked_scores(
        k, q, kmask_ref[0], scale, causal,
        pl.program_id(1) * k.shape[0], qidx * q.shape[0], True,
    )  # [BK, BQ]
    lse = lse_ref[0]  # [1, BQ]
    p_t = jnp.where(valid, jnp.exp(s_t - lse), 0.0)  # [BK, BQ]
    dv_acc_ref[...] += jax.lax.dot_general(
        p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [BK, BQ]
    delta = delta_ref[0]  # [1, BQ]
    ds_t = p_t * (dp_t - delta)
    dk_acc_ref[...] += jax.lax.dot_general(
        ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(qidx == nq - 1)
    def _():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _fwd_stream(q3, k3, v3, mask2, heads, scale, causal, interpret):
    bh, t, d = q3.shape
    blk = _divisor_blk(t, _STREAM_BLK)
    nq, nk = t // blk, t // blk
    q_spec = pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0))
    kmask_spec = pl.BlockSpec((1, 1, blk), lambda b, i, j: (b // heads, 0, j))
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_stream_kernel, scale=scale, causal=causal, nk=nk
        ),
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, kmask_spec],
        out_specs=(
            q_spec,
            pl.BlockSpec((1, 1, blk), lambda b, i, j: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((blk, d), jnp.float32),
            pltpu.VMEM((blk, LANE), jnp.float32),
            pltpu.VMEM((blk, LANE), jnp.float32),
        ],
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2)
    return out, lse


def _bwd_stream(q3, k3, v3, mask2, out3, lse, do3, heads, scale, causal,
                interpret, dlse=None):
    bh, t, d = q3.shape
    delta = jnp.sum(
        do3.astype(jnp.float32) * out3.astype(jnp.float32), axis=-1
    )[:, None, :]
    if dlse is not None:
        delta = delta - dlse  # see _bwd: lse cotangent folds into delta
    blk = _divisor_blk(t, _STREAM_BLK)
    nq, nk = t // blk, t // blk
    q_spec = pl.BlockSpec((1, blk, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, blk, d), lambda b, i, j: (b, j, 0))
    kmask_spec = pl.BlockSpec((1, 1, blk), lambda b, i, j: (b // heads, 0, j))
    row_q_spec = pl.BlockSpec((1, 1, blk), lambda b, i, j: (b, 0, i))
    dq = pl.pallas_call(
        functools.partial(
            _dq_stream_kernel, scale=scale, causal=causal, nk=nk
        ),
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, kmask_spec, q_spec,
                  row_q_spec, row_q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((blk, d), jnp.float32)],
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2, do3, lse, delta)
    # dkv walks the QUERY axis innermost; k/v blocks are pinned per middle
    # grid index
    kv_pin_spec = pl.BlockSpec((1, blk, d), lambda b, j, i: (b, j, 0))
    q_walk_spec = pl.BlockSpec((1, blk, d), lambda b, j, i: (b, i, 0))
    kmask_pin_spec = pl.BlockSpec(
        (1, 1, blk), lambda b, j, i: (b // heads, 0, j)
    )
    row_walk_spec = pl.BlockSpec((1, 1, blk), lambda b, j, i: (b, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_stream_kernel, scale=scale, causal=causal, nq=nq
        ),
        grid=(bh, nk, nq),
        in_specs=[q_walk_spec, kv_pin_spec, kv_pin_spec, kmask_pin_spec,
                  q_walk_spec, row_walk_spec, row_walk_spec],
        out_specs=(kv_pin_spec, kv_pin_spec),
        out_shape=(
            jax.ShapeDtypeStruct(k3.shape, k3.dtype),
            jax.ShapeDtypeStruct(v3.shape, v3.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((blk, d), jnp.float32),
            pltpu.VMEM((blk, d), jnp.float32),
        ],
        interpret=_interp(interpret),
    )(q3, k3, v3, mask2, do3, lse, delta)
    return dq, dk, dv


def _fwd_tier(tier, *args):
    return (_fwd if tier == "fused" else _fwd_stream)(*args)


def _bwd_tier(tier, *args):
    return (_bwd if tier == "fused" else _bwd_stream)(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _attend(q3, k3, v3, mask2, heads, scale, causal, interpret, tier):
    out, _ = _fwd_tier(tier, q3, k3, v3, mask2, heads, scale, causal, interpret)
    return out


def _attend_fwd(q3, k3, v3, mask2, heads, scale, causal, interpret, tier):
    out, lse = _fwd_tier(
        tier, q3, k3, v3, mask2, heads, scale, causal, interpret
    )
    return out, (q3, k3, v3, mask2, out, lse)


def _attend_bwd(heads, scale, causal, interpret, tier, res, do3):
    q3, k3, v3, mask2, out, lse = res
    dq, dk, dv = _bwd_tier(
        tier, q3, k3, v3, mask2, out, lse, do3, heads, scale, causal, interpret
    )
    return dq, dk, dv, None


_attend.defvjp(_attend_fwd, _attend_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _attend_lse(q3, k3, v3, mask2, heads, scale, causal, interpret, tier):
    return _fwd_tier(tier, q3, k3, v3, mask2, heads, scale, causal, interpret)


def _attend_lse_fwd(q3, k3, v3, mask2, heads, scale, causal, interpret, tier):
    out, lse = _fwd_tier(
        tier, q3, k3, v3, mask2, heads, scale, causal, interpret
    )
    return (out, lse), (q3, k3, v3, mask2, out, lse)


def _attend_lse_bwd(heads, scale, causal, interpret, tier, res, cts):
    q3, k3, v3, mask2, out, lse = res
    do3, dlse = cts
    dq, dk, dv = _bwd_tier(
        tier, q3, k3, v3, mask2, out, lse, do3, heads, scale, causal,
        interpret, dlse.astype(jnp.float32),
    )
    return dq, dk, dv, None


_attend_lse.defvjp(_attend_lse_fwd, _attend_lse_bwd)


def _prepare(q, k, v, kv_mask, tier):
    """Shared wrapper preamble for both public entry points: tier
    resolution, [B,T,H,D] -> padded [B*H, T_pad, D_pad] relayout, and the
    f32 key-padding row.  ONE definition so the plain path
    (``attention_fn``) and the lse path (ring merge) can never drift."""
    b, t, h, d = q.shape
    if tier is None:
        tier = kernel_tier(t, d, q.dtype.itemsize, _perf_gate=False)
    assert tier in ("fused", "stream"), f"ineligible shape T={t} D={d}"
    t_pad = max(128, ((t + 127) // 128) * 128)
    # K/V loads and dq/dk/dv writes pay for padded D bytes: pad only to the
    # MXU's minimum useful contraction width, not always to a full lane
    d_pad = 64 if d <= 64 else LANE if d <= LANE else d

    def to3(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)
        return jnp.pad(x, ((0, 0), (0, t_pad - t), (0, d_pad - d)))

    mask = jnp.ones((b, t), jnp.float32) if kv_mask is None else kv_mask.astype(
        jnp.float32
    )
    mask2 = jnp.pad(mask, ((0, 0), (0, t_pad - t)))[:, None, :]
    scale = 1.0 / math.sqrt(d)
    return tier, to3(q), to3(k), to3(v), mask2, scale


def fused_attention(q, k, v, kv_mask=None, causal: bool = False, tier=None):
    """Exact fused attention.  ``q/k/v: [B, T, H, D]`` (flax head layout),
    ``kv_mask: [B, T]`` key-padding mask (True = attend) or None.  The
    caller is responsible for eligibility (see :func:`kernel_tier`);
    callers wanting automatic gating + fallback use :func:`attention_fn`.
    ``tier`` overrides the automatic one-level/streaming choice (tests)."""
    b, t, h, d = q.shape
    tier, q3, k3, v3, mask2, scale = _prepare(q, k, v, kv_mask, tier)
    out = _attend(
        q3, k3, v3, mask2, h, scale, causal, _mode() == "interpret", tier
    )
    out = out[:, :t, :d].reshape(b, h, t, d)
    return jnp.transpose(out, (0, 2, 1, 3))


def fused_attention_lse(q, k, v, kv_mask=None, causal: bool = False,
                        tier=None):
    """Like :func:`fused_attention` but ALSO returns the per-row
    log-sum-exp ``[B, H, T]`` — the merge currency for composing partial
    attention over key shards (``parallel/ring_attention.py`` combines
    per-hop (out, lse) pairs).  Fully differentiable: the lse cotangent
    folds into the shared backward kernels as ``delta - dlse``."""
    b, t, h, d = q.shape
    tier, q3, k3, v3, mask2, scale = _prepare(q, k, v, kv_mask, tier)
    out, lse = _attend_lse(
        q3, k3, v3, mask2, h, scale, causal, _mode() == "interpret", tier
    )
    out = out[:, :t, :d].reshape(b, h, t, d)
    return jnp.transpose(out, (0, 2, 1, 3)), lse[:, 0, :t].reshape(b, h, t)


_VMEM_BUDGET = 15 * 1024 * 1024  # leave headroom under the 16 MB scoped limit


def kernel_tier(
    t: int, d: int, itemsize: int = 2, _perf_gate: bool = True
) -> str | None:
    """Which kernel tier serves shape (T, D): ``"fused"`` (one-level, full
    K/V rows in VMEM), ``"stream"`` (online-softmax walk over K/V blocks,
    VMEM O(blk^2) — up to MAX_STREAM_T), or None (XLA fallback).

    The MIN_FUSED_T floor is a measured perf crossover (BASELINE.md: below
    ~1024 XLA's batched-matmul attention wins on step-overhead) and applies
    only to the compiled TPU path — the interpreter mode exists for
    correctness tests at small shapes.  The one-level VMEM model mirrors
    what Mosaic stack-allocates per grid step: full K/V rows plus ~3
    [blk, T] f32 score-sized temporaries, anchored on measured compiles
    (bf16 T=8192 d=64 fits at 14.7 MB est.; f32 T=8192 OOMs at 16.8 MB
    est. vs the observed 16.5 MB allocation; bf16 T=16384 d_pad=128 OOMs).
    Shapes past the one-level bound take the streaming tier instead."""
    mode = _mode()
    if mode == "off" or d > LANE:
        return None
    if _perf_gate and mode == "tpu" and t < MIN_FUSED_T:
        return None
    t_pad = max(128, ((t + 127) // 128) * 128)
    d_pad = 64 if d <= 64 else LANE
    kv_bytes = 2 * t_pad * d_pad * itemsize
    temp_bytes = 3 * _pick_blk(t_pad) * t_pad * 4
    if t <= MAX_FUSED_T and kv_bytes + temp_bytes <= _VMEM_BUDGET:
        return "fused"
    if t <= MAX_STREAM_T:
        return "stream"
    return None


def kernel_eligible(t: int, d: int, itemsize: int = 2) -> bool:
    """True when any kernel tier serves this shape on this backend."""
    return kernel_tier(t, d, itemsize) is not None


def eligible(q, mask, dropout_rate: float, deterministic: bool, k=None) -> bool:
    """Can the Pallas kernel serve this ``attention_fn`` call?
    (Attention-probability dropout, cross-attention, and q- or
    head-dependent masks fall back.)"""
    if dropout_rate > 0.0 and not deterministic:
        return False  # in-kernel prob-dropout not implemented; XLA path
    if q.ndim != 4 or not kernel_eligible(
        q.shape[1], q.shape[3], q.dtype.itemsize
    ):
        return False
    if k is not None and k.shape[1] != q.shape[1]:
        return False  # cross-attention (T_kv != T_q): XLA path
    if mask is not None and (
        mask.ndim != 4 or mask.shape[-2] != 1 or mask.shape[-3] != 1
    ):
        return False  # not a pure key-padding mask (q- or head-dependent)
    return True


def attention_fn(
    query,
    key,
    value,
    mask=None,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    broadcast_dropout: bool = True,
    deterministic: bool = True,
    dtype=None,
    precision=None,
):
    """Drop-in ``attention_fn`` for ``nn.MultiHeadDotProductAttention``:
    routes to the fused Pallas kernel when eligible, otherwise to flax's
    reference ``dot_product_attention`` (bit-for-bit the default path)."""
    if eligible(query, mask, dropout_rate, deterministic, k=key):
        kv_mask = None
        if mask is not None:
            # [B, 1, 1, T] (or broadcastable) key-padding mask -> [B, T]
            kv_mask = jnp.broadcast_to(
                mask, (query.shape[0], 1, 1, key.shape[1])
            )[:, 0, 0, :]
        return fused_attention(query, key, value, kv_mask=kv_mask)
    import flax.linen as nn

    return nn.dot_product_attention(
        query,
        key,
        value,
        mask=mask,
        dropout_rng=dropout_rng,
        dropout_rate=dropout_rate,
        broadcast_dropout=broadcast_dropout,
        deterministic=deterministic,
        dtype=dtype,
        precision=precision,
    )
