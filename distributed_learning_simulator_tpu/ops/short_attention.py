"""Short-sequence fused attention in the packed-QKV projection layout.

The complement of ``ops/fused_attention.py`` at the OTHER end of the
sequence axis.  The flash-style kernel there wins for T ≥ 1024, but the
zoo's encoder workhorses (ViT at seq 64, BERT-tiny at 128) spend their
attention time not in FLOPs — the score matrices are tiny — but in **XLA
layout copies**: splitting heads out of the ``[B, S, H·Dh]`` projection
and batching them for the MXU forces ``[B,S,H,Dh] ⇄ [B,H,S,Dh]``
relayouts of every Q/K/V/residual tensor, measured at 17-25% of the
whole ViT-small federated round on the v5e (BASELINE.md round-5 trace
table; the reference runs the same architecture through torch SDPA and
never sees this cost because cuDNN owns the layout there).

This kernel removes the copies by never leaving the projection layout:

* input is the packed ``[B, S, 3·H·Dh]`` output of ONE QKV matmul
  (torch ``nn.MultiheadAttention``'s ``in_proj`` packing: Q rows, then
  K, then V, each ``[S, H·Dh]`` with heads side by side);
* each grid step loads a VMEM block of ``bb`` batch elements, unrolls
  the (static) head loop, computes ``softmax(q_h k_hᵀ · Dh^-0.5) v_h``
  per head with f32 scores, and writes straight into the ``[S, H·Dh]``
  output block the next Dense consumes — heads are VMEM column slices,
  never HBM transposes;
* **MXU packing**: at ViT's S = 64 a single score matrix uses half the
  128×128 systolic array, so ``bb = 128 // S_pad`` batch elements are
  stacked into ONE ``[bb·S, bb·S]`` matmul per head — same MXU cycles,
  ``bb×`` fewer matmuls — with an in-kernel block-diagonal iota mask
  zeroing the cross-element quadrants (their probabilities are exactly
  0, which also makes every backward contraction block-correct);
* backward is one kernel in the same layout producing ``d(qkv)``
  directly (recompute-style: probabilities are re-formed from the saved
  input, nothing but the projection itself is kept as residual).

Sequences are padded to the sublane multiple and padded KEYS are masked
with an in-kernel iota compare; padded QUERY rows compute garbage that
the caller slices off.  ``kv_mask`` ([B, S] 1/0) handles text-model key
padding the same way.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused_attention import _interp, _mode

_NEG_INF = -1e30
MAX_SHORT_T = 1024  # hand-off point to the flash-style long-seq kernel
_VMEM_BUDGET = 13 * 1024 * 1024


def short_eligible(
    s: int, d_model: int, num_heads: int, itemsize: int = 2
) -> bool:
    """Can this kernel serve a ``[B, S, 3·d_model]`` packed projection?
    Head dim must be a clean lane fraction (64 or 128) and the whole
    per-block working set must fit VMEM."""
    if _mode() == "off":
        return False
    if d_model % num_heads:
        return False
    dh = d_model // num_heads
    if dh not in (64, 128) or d_model % 128:
        return False
    if s > MAX_SHORT_T:
        return False
    rows = max(_pad_rows(s), 128)  # bb packing targets 128 score rows
    working = 4 * d_model * rows * itemsize + 4 * rows * rows * 4
    return working <= _VMEM_BUDGET


def _pad_rows(s: int) -> int:
    return (s + 15) // 16 * 16


def _pick_bb(b: int, s_pad: int) -> int:
    """Batch elements stacked per score matmul: fill the 128-row MXU tile
    at short S (must divide the batch)."""
    bb = max(1, 128 // s_pad)
    while b % bb:
        bb -= 1
    return bb


def _pick_blk_b(b: int, s_pad: int, bb: int) -> int:
    """Batch elements per GRID STEP (a multiple of ``bb``).  Measured on
    the v5e ViT-small round: ONE stacked group per step wins — 1.655
    rounds/s vs 1.616 (2 groups/step) and 1.574 (4 groups/step); Mosaic's
    cross-step DMA/compute overlap beats in-step unrolling here, so the
    group loop in the kernels exists only for shapes where ``b`` is not
    divisible by ``bb`` stacking (it then runs a single group anyway)."""
    return bb


def _head_slices(qkv, d: int, dh: int, h: int):
    """Head ``h``'s (q, k, v) column slices of one packed block."""
    q = qkv[:, h * dh : (h + 1) * dh]
    k = qkv[:, d + h * dh : d + (h + 1) * dh]
    v = qkv[:, 2 * d + h * dh : 2 * d + (h + 1) * dh]
    return q, k, v


def _probs(q, k, mask_row, scale, s_true, s_pad):
    """f32 attention probabilities for one head (shared fwd/bwd).
    ``q``/``k`` are ``[bb·S_pad, Dh]``; rows/cols from different batch
    elements of the stack are masked to exact 0."""
    logits = (
        jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    rows = logits.shape[0]
    keep = None
    if rows > s_pad:  # block-diagonal mask across the bb stack
        r = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
        c = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        keep = (r // s_pad) == (c // s_pad)
    if s_true < s_pad:  # padded key columns
        c = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        pad_ok = (c % s_pad) < s_true
        keep = pad_ok if keep is None else (keep & pad_ok)
    if keep is not None:
        logits = jnp.where(keep, logits, _NEG_INF)
    if mask_row is not None:
        logits = jnp.where(mask_row > 0, logits, _NEG_INF)
    m = jnp.max(logits, axis=1, keepdims=True)
    p = jnp.exp(logits - m)
    return p / jnp.sum(p, axis=1, keepdims=True)


def _fwd_kernel(*refs, heads, dh, scale, s_true, s_pad, bb, masked):
    if masked:
        qkv_ref, mask_ref, out_ref = refs
    else:
        qkv_ref, out_ref = refs
        mask_ref = None
    width = qkv_ref.shape[2]
    d = heads * dh
    groups = qkv_ref.shape[0] // bb
    for g in range(groups):
        rows = slice(g * bb, (g + 1) * bb)
        qkv = qkv_ref[rows].reshape(bb * s_pad, width)
        mask_row = None if mask_ref is None else mask_ref[g : g + 1, :]
        for h in range(heads):
            q, k, v = _head_slices(qkv, d, dh, h)
            p = _probs(q, k, mask_row, scale, s_true, s_pad)
            out_h = jax.lax.dot_general(
                p.astype(qkv.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            out_ref[rows, :, h * dh : (h + 1) * dh] = out_h.astype(
                out_ref.dtype
            ).reshape(bb, s_pad, dh)


def _bwd_kernel(*refs, heads, dh, scale, s_true, s_pad, bb, masked):
    if masked:
        qkv_ref, mask_ref, do_ref, dqkv_ref = refs
    else:
        qkv_ref, do_ref, dqkv_ref = refs
        mask_ref = None
    width = qkv_ref.shape[2]
    d = heads * dh
    dt = dqkv_ref.dtype
    groups = qkv_ref.shape[0] // bb
    for g in range(groups):
        rows = slice(g * bb, (g + 1) * bb)
        qkv = qkv_ref[rows].reshape(bb * s_pad, width)
        do = do_ref[rows].reshape(bb * s_pad, d)
        mask_row = None if mask_ref is None else mask_ref[g : g + 1, :]
        for h in range(heads):
            q, k, v = _head_slices(qkv, d, dh, h)
            p = _probs(q, k, mask_row, scale, s_true, s_pad)
            do_h = do[:, h * dh : (h + 1) * dh]
            p_low = p.astype(qkv.dtype)
            dv = jax.lax.dot_general(
                p_low, do_h, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do_h, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # cross-element quadrants of dp are garbage, but p is exactly
            # 0 there, so ds (= p ⊙ (dp − rowsum(dp ⊙ p))) stays correct
            ds = p * (dp - jnp.sum(dp * p, axis=1, keepdims=True))
            ds = (ds * scale).astype(qkv.dtype)
            dq = jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk = jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dqkv_ref[rows, :, h * dh : (h + 1) * dh] = dq.astype(
                dt
            ).reshape(bb, s_pad, dh)
            dqkv_ref[rows, :, d + h * dh : d + (h + 1) * dh] = dk.astype(
                dt
            ).reshape(bb, s_pad, dh)
            dqkv_ref[
                rows, :, 2 * d + h * dh : 2 * d + (h + 1) * dh
            ] = dv.astype(dt).reshape(bb, s_pad, dh)


def _call(kernel, qkv, mask, extra, out_shape, *, heads, dh, s_true):
    """Shared pallas_call plumbing: ``blk_b`` batch elements per grid
    step, unrolled in-kernel as ``blk_b // bb`` MXU-packed groups."""
    b, s_pad, width = qkv.shape
    bb = _pick_bb(b, s_pad)
    blk_b = _pick_blk_b(b, s_pad, bb)
    masked = mask is not None
    operands = [qkv] + ([mask] if masked else []) + extra
    specs = [pl.BlockSpec((blk_b, s_pad, width), lambda i: (i, 0, 0))]
    if masked:
        # wrapper pre-flattens the mask to [B//bb, bb·S_pad]
        specs.append(
            pl.BlockSpec((blk_b // bb, bb * s_pad), lambda i: (i, 0))
        )
    specs += [
        pl.BlockSpec(
            (blk_b,) + x.shape[1:],
            lambda i, n=x.ndim: (i,) + (0,) * (n - 1),
        )
        for x in extra
    ]
    return pl.pallas_call(
        functools.partial(
            kernel,
            heads=heads,
            dh=dh,
            scale=dh**-0.5,
            s_true=s_true,
            s_pad=s_pad,
            bb=bb,
            masked=masked,
        ),
        grid=(b // blk_b,),
        in_specs=specs,
        out_specs=pl.BlockSpec(
            (blk_b,) + out_shape.shape[1:],
            lambda i: (i,) + (0,) * (len(out_shape.shape) - 1),
        ),
        out_shape=out_shape,
        interpret=_interp(_mode() == "interpret"),
    )(*operands)


def _flat_mask(kv_mask, b: int, s_pad: int):
    """[B, S_pad] → [B//bb, bb·S_pad] so the kernel reads a lane-major
    row vector per block (no in-kernel sublane→lane reshape)."""
    bb = _pick_bb(b, s_pad)
    return kv_mask.astype(jnp.float32).reshape(b // bb, bb * s_pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _short_attn(qkv, kv_mask, heads: int, s_true: int):
    out, _ = _short_fwd(qkv, kv_mask, heads, s_true)
    return out


def _short_fwd(qkv, kv_mask, heads: int, s_true: int):
    b, s_pad, width = qkv.shape
    d = width // 3
    mask = None if kv_mask is None else _flat_mask(kv_mask, b, s_pad)
    out = _call(
        _fwd_kernel,
        qkv,
        mask,
        [],
        jax.ShapeDtypeStruct((b, s_pad, d), qkv.dtype),
        heads=heads,
        dh=d // heads,
        s_true=s_true,
    )
    return out, (qkv, kv_mask)


def _short_bwd(heads: int, s_true: int, res, do):
    qkv, kv_mask = res
    b, s_pad, _ = qkv.shape
    mask = None if kv_mask is None else _flat_mask(kv_mask, b, s_pad)
    dqkv = _call(
        _bwd_kernel,
        qkv,
        mask,
        [do],
        jax.ShapeDtypeStruct(qkv.shape, qkv.dtype),
        heads=heads,
        dh=qkv.shape[2] // 3 // heads,
        s_true=s_true,
    )
    return dqkv, None


_short_attn.defvjp(_short_fwd, _short_bwd)


def short_attention(qkv, num_heads: int, kv_mask=None):
    """``softmax(QKᵀ·Dh^-0.5)V`` over a packed ``[B, S, 3·H·Dh]``
    projection, returning ``[B, S, H·Dh]``.  ``kv_mask``: optional
    ``[B, S]`` key-padding mask (>0 = attend).  Caller gates via
    :func:`short_eligible`."""
    b, s, width = qkv.shape
    s_pad = _pad_rows(s)
    if s_pad != s:
        qkv = jnp.pad(qkv, ((0, 0), (0, s_pad - s), (0, 0)))
        if kv_mask is not None:
            kv_mask = jnp.pad(kv_mask, ((0, 0), (0, s_pad - s)))
    if kv_mask is not None:
        kv_mask = kv_mask.astype(jnp.float32)
    out = _short_attn(qkv, kv_mask, num_heads, s)
    return out[:, :s, :]


__all__ = ["short_attention", "short_eligible", "MAX_SHORT_T"]
