"""Flat-parameter-dict ("pytree") utilities.

TPU-native equivalent of the reference's tensor helpers
(``cyy_torch_toolbox.tensor``: ``cat_tensors_to_vector``,
``decompose_tensor_to_list``, ``recursive_tensor_op``, and the ``TensorDict``
alias — see SURVEY.md §2.13).  Model parameters are represented everywhere as
a flat ``dict[str, jax.Array]`` keyed by "/"-joined module paths (mirroring
the reference's module-path-keyed ``TensorDict``), which makes block
partitioning (FedOBD), per-tensor dropout, and parameter diffs natural.
"""

from collections.abc import Mapping
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, jax.Array]


def flatten_nested(nested: Mapping[str, Any], sep: str = "/") -> Params:
    """Flatten a nested param dict (e.g. flax ``params``) into flat path keys."""
    out: Params = {}

    def rec(prefix: str, node: Any) -> None:
        if isinstance(node, Mapping):
            for k in sorted(node.keys()):
                rec(f"{prefix}{sep}{k}" if prefix else str(k), node[k])
        else:
            out[prefix] = node

    rec("", nested)
    return out


def unflatten_nested(flat: Mapping[str, Any], sep: str = "/") -> dict[str, Any]:
    """Inverse of :func:`flatten_nested`."""
    out: dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split(sep)
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out


def cat_params_to_vector(params: Mapping[str, jax.Array]) -> jax.Array:
    """Concatenate all tensors into one flat vector, keys sorted
    (reference: ``cat_tensors_to_vector`` used by ``gradient_worker.py``)."""
    return jnp.concatenate([jnp.ravel(params[k]) for k in sorted(params)])


def params_from_vector_like(vector: jax.Array, like: Params) -> Params:
    """Split a flat vector back into a param dict with ``like``'s shapes
    (reference: ``decompose_tensor_to_list``)."""
    out: Params = {}
    offset = 0
    for key in sorted(like):
        shape = like[key].shape
        size = int(np.prod(shape)) if shape else 1
        out[key] = jax.lax.dynamic_slice_in_dim(vector, offset, size).reshape(shape)
        offset += size
    return out


def params_diff(new: Params, old: Params) -> Params:
    return {k: new[k] - old[k] for k in new}


def params_add(base: Params, delta: Mapping[str, jax.Array]) -> Params:
    return {k: (base[k] + delta[k]) if k in delta else base[k] for k in base}


def params_scale(params: Params, scale) -> Params:
    return {k: v * scale for k, v in params.items()}


def params_zeros_like(params: Params) -> Params:
    return {k: jnp.zeros_like(v) for k, v in params.items()}


def params_l2(params: Params) -> jax.Array:
    return jnp.sqrt(sum(jnp.sum(jnp.square(v.astype(jnp.float32))) for v in params.values()))


def weighted_sum(param_list: list[Params], weights) -> Params:
    """``sum_i params_i * w_i`` over a python list of param dicts."""
    keys = param_list[0].keys()
    return {
        k: sum(p[k].astype(jnp.float32) * w for p, w in zip(param_list, weights))
        for k in keys
    }


def tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def param_nbytes(tree: Any) -> int:
    """Total payload bytes of a pytree of arrays
    (reference: ``get_message_size``, ``simulation_lib/message.py:52-62``)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * np.dtype(leaf.dtype).itemsize
    return total
