"""Flat-parameter-dict ("pytree") utilities.

TPU-native equivalent of the reference's tensor helpers
(``cyy_torch_toolbox.tensor``: ``cat_tensors_to_vector``,
``decompose_tensor_to_list``, ``recursive_tensor_op``, and the ``TensorDict``
alias — see SURVEY.md §2.13).  Model parameters are represented everywhere as
a flat ``dict[str, jax.Array]`` keyed by "/"-joined module paths (mirroring
the reference's module-path-keyed ``TensorDict``), which makes block
partitioning (FedOBD), per-tensor dropout, and parameter diffs natural.
"""

import dataclasses
import functools
from collections.abc import Mapping
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, jax.Array]


def flatten_nested(nested: Mapping[str, Any], sep: str = "/") -> Params:
    """Flatten a nested param dict (e.g. flax ``params``) into flat path keys."""
    out: Params = {}

    def rec(prefix: str, node: Any) -> None:
        if isinstance(node, Mapping):
            for k in sorted(node.keys()):
                rec(f"{prefix}{sep}{k}" if prefix else str(k), node[k])
        else:
            out[prefix] = node

    rec("", nested)
    return out


def unflatten_nested(flat: Mapping[str, Any], sep: str = "/") -> dict[str, Any]:
    """Inverse of :func:`flatten_nested`."""
    out: dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split(sep)
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out


def cat_params_to_vector(params: Mapping[str, jax.Array]) -> jax.Array:
    """Concatenate all tensors into one flat vector, keys sorted
    (reference: ``cat_tensors_to_vector`` used by ``gradient_worker.py``)."""
    return jnp.concatenate([jnp.ravel(params[k]) for k in sorted(params)])


def params_from_vector_like(vector: jax.Array, like: Params) -> Params:
    """Split a flat vector back into a param dict with ``like``'s shapes
    (reference: ``decompose_tensor_to_list``)."""
    out: Params = {}
    offset = 0
    for key in sorted(like):
        shape = like[key].shape
        size = int(np.prod(shape)) if shape else 1
        out[key] = jax.lax.dynamic_slice_in_dim(vector, offset, size).reshape(shape)
        offset += size
    return out


# --------------------------------------------------------------- ParamVec
# The server aggregation hot path's parameter representation: ONE contiguous
# float32 vector plus a static layout derived once per model.  The per-tensor
# walk (one astype+mul+add per tensor per worker — O(workers × tensors) tiny
# XLA dispatches per round) collapses to one fused program per upload plus
# one divide + one split per round.  The layout contract (also the wire
# contract for flat-encoded codec payloads, ops/quantization.py):
#
# * keys sorted lexicographically ("/"-joined module paths, same order as
#   ``cat_params_to_vector``);
# * each tensor raveled row-major (C order) and cast to float32;
# * ``offsets[i]`` is the start of ``keys[i]`` in the vector; scalars take
#   one slot; ``size`` is the total length.


@dataclasses.dataclass(frozen=True)
class ParamVecLayout:
    """Static (hashable) layout of a flat parameter vector."""

    keys: tuple[str, ...]
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[str, ...]
    offsets: tuple[int, ...]
    size: int

    @classmethod
    def of(cls, params: Mapping[str, Any]) -> "ParamVecLayout":
        keys = tuple(sorted(params))
        shapes: list[tuple[int, ...]] = []
        dtypes: list[str] = []
        offsets: list[int] = []
        offset = 0
        for key in keys:
            value = params[key]
            shape = tuple(int(s) for s in value.shape)
            shapes.append(shape)
            dtypes.append(str(value.dtype))
            offsets.append(offset)
            offset += int(np.prod(shape)) if shape else 1
        return cls(keys, tuple(shapes), tuple(dtypes), tuple(offsets), offset)

    def matches(self, params: Mapping[str, Any]) -> bool:
        """Keys AND shapes must agree — a same-size shape mismatch (e.g. a
        transposed kernel) would otherwise flatten into a silently
        misaligned sum where the per-tensor walk raised."""
        if tuple(sorted(params)) != self.keys:
            return False
        return all(
            tuple(int(s) for s in params[key].shape) == shape
            for key, shape in zip(self.keys, self.shapes)
        )

    def key_at(self, index: int) -> str:
        """The parameter name owning vector position ``index``."""
        pos = int(np.searchsorted(np.asarray(self.offsets), index, "right")) - 1
        return self.keys[max(pos, 0)]

    def split(self, vector: jax.Array, cast: bool = True) -> Params:
        """Traceable inverse of :func:`flatten_params`: static slices (no
        dynamic_slice walk, unlike ``params_from_vector_like``), reshaped to
        the recorded shapes and (with ``cast``) the recorded dtypes."""
        out: Params = {}
        for key, shape, dtype, offset in zip(
            self.keys, self.shapes, self.dtypes, self.offsets
        ):
            size = int(np.prod(shape)) if shape else 1
            leaf = jax.lax.slice_in_dim(vector, offset, offset + size).reshape(shape)
            out[key] = leaf.astype(dtype) if cast else leaf
        return out


def _flatten_f32(params: Mapping[str, jax.Array]) -> jax.Array:
    """Trace-level ParamVec flatten: sorted keys, row-major ravel, float32."""
    return jnp.concatenate(
        [jnp.ravel(params[k]).astype(jnp.float32) for k in sorted(params)]
    )


@jax.jit
def flatten_params(params: Params) -> jax.Array:
    """ParamVec flatten as ONE dispatch."""
    return _flatten_f32(params)


@jax.jit
def flat_weighted_vec(params: Params, weight) -> jax.Array:
    """``flatten(params) · w`` — the streaming accumulator's first term."""
    return _flatten_f32(params) * jnp.float32(weight)


@functools.partial(jax.jit, donate_argnums=(0,))
def flat_acc_add(acc: jax.Array, params: Params, weight) -> jax.Array:
    """``acc += flatten(params) · w`` with the accumulator buffer donated —
    THE streaming-FedAvg hot path: one fused dispatch per upload, XLA
    updates the accumulator in place (no per-round alloc churn).  The
    weight rides as a traced scalar, so distinct weights never retrace."""
    return acc + _flatten_f32(params) * jnp.float32(weight)


@jax.jit
def flat_scale(vec: jax.Array, scale) -> jax.Array:
    """One divide: the streaming finalize before the split."""
    return vec / jnp.float32(scale)


@functools.partial(jax.jit, static_argnums=(1, 2))
def split_flat_params(vec: jax.Array, layout: ParamVecLayout, cast: bool = True) -> Params:
    """One split back to the param dict via the static layout."""
    return layout.split(vec, cast=cast)


def _matvec_f32(mat: jax.Array, weights: jax.Array) -> jax.Array:
    """``w @ [K, D]`` in full float32 (TPU default matmul precision is
    bf16-ish — aggregation numerics need the HIGHEST pass), via the fused
    Pallas accumulator when the backend has it and the vector is tile-sized."""
    if jax.default_backend() == "tpu" and mat.shape[0] > 1 and mat.shape[1] >= 8 * 128:
        from .pallas_kernels import weighted_accum

        return weighted_accum(mat, weights.astype(jnp.float32))
    return jnp.einsum(
        "k,kd->d",
        weights.astype(jnp.float32),
        mat,
        precision=jax.lax.Precision.HIGHEST,
    )


def flat_stack_weighted_sum(
    stacked: Mapping[str, jax.Array], weights: jax.Array
) -> jax.Array:
    """``w @ [K, D]`` over a LEADING-AXIS-STACKED params tree (the shape a
    vmapped client chunk returns): sorted keys, each ``[K, *shape]`` leaf
    reshaped to ``[K, prod(shape)]`` float32 rows, one HIGHEST-precision
    matvec (:func:`_matvec_f32` — the fused Pallas accumulator on TPU).

    This is the bf16-residency aggregation epilogue: the ``[K]`` weight
    row contracts against ONE ``[K, D]`` matrix instead of broadcasting
    across every param-shaped tensor, and the single f32 convert rides
    the matvec input instead of per-leaf multiply/accumulate
    temporaries.  Returns the ``[D]`` float32 ParamVec (layout =
    ``ParamVecLayout.of`` of one row; split back via ``layout.split``)."""
    k = weights.shape[0]
    mat = jnp.concatenate(
        [
            jnp.reshape(stacked[key], (k, -1)).astype(jnp.float32)
            for key in sorted(stacked)
        ],
        axis=1,
    )
    return _matvec_f32(mat, weights)


@functools.partial(jax.jit, static_argnums=(2,))
def flat_weighted_params(
    param_dicts: tuple, weights: jax.Array, layout: ParamVecLayout
) -> Params:
    """Batch ParamVec aggregation as ONE dispatch: stack K uploads into a
    ``[K, D]`` matrix, one matvec, one split back through the layout (leaf
    dtypes restored)."""
    mat = jnp.stack([_flatten_f32(p) for p in param_dicts])
    return layout.split(_matvec_f32(mat, weights), cast=True)


#: K × D ceiling for the stacked batch matvec: beyond it the [K, D] float32
#: copy (a second whole-upload-set of HBM on top of the retained uploads)
#: costs more than the single-dispatch win, so the batch path degrades to
#: K streaming donated adds — same numerics, no stacked temporary
FLAT_BATCH_MAX_ELEMENTS = 1 << 28


def flat_weighted_avg_params(param_dicts, weights, layout: ParamVecLayout) -> Params:
    """The batch aggregation entry point: one stacked matvec for normal
    sizes, streaming donated accumulation when ``K × D`` would blow the
    memory budget (``FLAT_BATCH_MAX_ELEMENTS``)."""
    if len(param_dicts) * layout.size > FLAT_BATCH_MAX_ELEMENTS:
        acc = flat_weighted_vec(param_dicts[0], weights[0])
        for params, weight in zip(param_dicts[1:], weights[1:]):
            acc = flat_acc_add(acc, params, weight)
        return split_flat_params(acc, layout)
    return flat_weighted_params(
        tuple(param_dicts), jnp.asarray(weights, jnp.float32), layout
    )


def check_finite_vec(vec: jax.Array, layout: ParamVecLayout | None = None) -> None:
    """NaN guard on a ParamVec: ONE reduction on the happy path; only a
    failure pays the per-element walk to name the offending parameter."""
    if bool(jnp.all(jnp.isfinite(vec))):
        return
    bad = int(np.argmax(~np.asarray(jnp.isfinite(vec))))
    name = layout.key_at(bad) if layout is not None else f"vector[{bad}]"
    raise FloatingPointError(f"non-finite aggregated parameter {name}")


def params_diff(new: Params, old: Params) -> Params:
    return {k: new[k] - old[k] for k in new}


def params_add(base: Params, delta: Mapping[str, jax.Array]) -> Params:
    return {k: (base[k] + delta[k]) if k in delta else base[k] for k in base}


def params_scale(params: Params, scale) -> Params:
    return {k: v * scale for k, v in params.items()}


def params_zeros_like(params: Params) -> Params:
    return {k: jnp.zeros_like(v) for k, v in params.items()}


def params_l2(params: Params) -> jax.Array:
    return jnp.sqrt(sum(jnp.sum(jnp.square(v.astype(jnp.float32))) for v in params.values()))


def weighted_sum(param_list: list[Params], weights) -> Params:
    """``sum_i params_i * w_i`` over a python list of param dicts — one
    stacked ``[K, D]`` ParamVec matvec instead of a per-tensor mul/add walk.
    Leaves come back float32 (the historical contract of this helper)."""
    layout = ParamVecLayout.of(param_list[0])
    assert all(
        layout.matches(p) for p in param_list
    ), "inconsistent param keys/shapes"
    mat = jnp.stack([flatten_params(p) for p in param_list])
    vec = _matvec_f32(mat, jnp.asarray(list(weights), jnp.float32))
    return layout.split(vec, cast=False)


def tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_to_numpy(tree):
    """Host copies of a device pytree.  REAL copies, not ``np.asarray``
    views: on the cpu backend ``np.asarray`` of a device array aliases the
    device buffer, and a snapshot that aliases a later-donated buffer
    mutates under the donating program (the PR 3 parity incident —
    docs/jax_hazards.md, zero-copy-view)."""
    return jax.tree.map(lambda x: np.array(x, copy=True), tree)


def param_nbytes(tree: Any) -> int:
    """Total payload bytes of a pytree of arrays
    (reference: ``get_message_size``, ``simulation_lib/message.py:52-62``)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += int(leaf.size) * np.dtype(leaf.dtype).itemsize
    return total
