from .pytree import (
    Params,
    cat_params_to_vector,
    param_nbytes,
    params_add,
    params_diff,
    params_from_vector_like,
    params_l2,
    params_scale,
    params_zeros_like,
    tree_cast,
    tree_to_numpy,
    weighted_sum,
)

__all__ = [
    "Params",
    "cat_params_to_vector",
    "param_nbytes",
    "params_add",
    "params_diff",
    "params_from_vector_like",
    "params_l2",
    "params_scale",
    "params_zeros_like",
    "tree_cast",
    "tree_to_numpy",
    "weighted_sum",
]
