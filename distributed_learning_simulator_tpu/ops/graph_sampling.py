"""Neighbor-sampling primitives shared by both executors.

The reference bounds GNN fan-in through torch_geometric's ``NeighborLoader``
(``dataloader kwargs`` ``num_neighbor``, applied per sampled minibatch —
``simulation_lib/worker/graph_worker.py:98-101``).  On TPU the graph keeps a
static edge list; sampling is an **edge-mask transform**: cap the number of
active incoming edges per destination node at ``limit``.

Two implementations with identical semantics:

* :func:`cap_fan_in` — numpy, used by the threaded executor's host-side
  batch assembly (and fed_aas's per-round resampling);
* :func:`cap_fan_in_jax` — pure jax, O(E log E) sort-based, usable inside a
  jitted/scanned round program (the SPMD executor caps per minibatch inside
  the compiled round).
"""

import jax
import jax.numpy as jnp
import numpy as np


def cap_fan_in(
    base_mask: np.ndarray, dst: np.ndarray, limit: int, rng
) -> np.ndarray:
    """Cap incoming fan-in per destination node at ``limit``: random
    permutation, stable-sort by destination, keep rank-within-destination
    < limit (vectorized — edge lists are large)."""
    candidates = rng.permutation(np.nonzero(base_mask)[0])
    keep = np.zeros_like(base_mask, dtype=bool)
    if len(candidates):
        d = dst[candidates]
        by_dst = np.argsort(d, kind="stable")
        sorted_d = d[by_dst]
        first_idx = np.r_[0, np.nonzero(np.diff(sorted_d))[0] + 1]
        group_id = np.cumsum(np.r_[0, (np.diff(sorted_d) != 0).astype(np.int64)])
        rank = np.arange(len(sorted_d)) - first_idx[group_id]
        keep[candidates[by_dst[rank < limit]]] = True
    return keep


def cap_fan_in_jax(edge_mask, dst, limit: int, key) -> jnp.ndarray:
    """Jit-friendly fan-in cap: every active edge draws a uniform priority,
    edges are sorted (destination, priority) and the first ``limit`` active
    edges per destination survive.  Returns a float mask of the same shape
    as ``edge_mask``; inactive edges never survive."""
    n_edges = edge_mask.shape[0]
    active = edge_mask > 0
    priority = jax.random.uniform(key, (n_edges,))
    # inactive edges sort last within their destination segment
    priority = jnp.where(active, priority, 2.0)
    order = jnp.lexsort((priority, dst))
    sorted_dst = dst[order]
    # rank within each destination segment (sorted_dst is sorted, so the
    # first occurrence index comes from searchsorted against itself)
    first = jnp.searchsorted(sorted_dst, sorted_dst, side="left")
    rank = jnp.arange(n_edges) - first
    keep_sorted = (rank < limit) & (priority[order] < 1.5)
    keep = jnp.zeros(n_edges, edge_mask.dtype).at[order].set(
        keep_sorted.astype(edge_mask.dtype)
    )
    return keep


def minibatch_assignment(train_mask, batch_number: int, key) -> jnp.ndarray:
    """Jit-friendly balanced minibatch partition: rank the training nodes in
    a random order and deal them round-robin into ``batch_number`` batches
    (the reference's graph dataloader splits training nodes into
    ``batch_number`` near-equal shuffled batches per epoch,
    ``simulation_lib/worker/graph_worker.py:94-97``).  Returns an int32
    batch id per node; non-training nodes get id ``batch_number`` (never
    selected)."""
    n = train_mask.shape[0]
    r = jax.random.uniform(key, (n,))
    r = jnp.where(train_mask > 0, r, jnp.inf)
    order = jnp.argsort(r)  # training nodes first, random order
    pos = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    return jnp.where(train_mask > 0, pos % batch_number, batch_number).astype(
        jnp.int32
    )


__all__ = ["cap_fan_in", "cap_fan_in_jax", "minibatch_assignment"]
