"""Logging for the framework.

TPU-native stand-in for ``cyy_naive_lib.log`` (used across ~20 reference files,
e.g. ``simulation_lib/training.py``): one process-wide logger with colored
console output and optional per-run file handlers.
"""

import logging
import os
import sys
import threading

_LOGGER_NAME = "dls_tpu"
_lock = threading.Lock()
_file_handlers: dict[str, logging.FileHandler] = {}


class _ColorFormatter(logging.Formatter):
    COLORS = {
        logging.DEBUG: "\x1b[36m",
        logging.INFO: "\x1b[32m",
        logging.WARNING: "\x1b[33m",
        logging.ERROR: "\x1b[31m",
        logging.CRITICAL: "\x1b[41m",
    }
    RESET = "\x1b[0m"

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = self.COLORS.get(record.levelno, "")
            return f"{color}{msg}{self.RESET}"
        return msg


_FMT = "%(asctime)s %(levelname)s {%(processName)s} [%(filename)s:%(lineno)d] %(message)s"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    with _lock:
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(_ColorFormatter(_FMT, datefmt="%H:%M:%S"))
            logger.addHandler(handler)
            logger.setLevel(logging.INFO)
            logger.propagate = False
    return logger


def set_level(level: str | int) -> None:
    get_logger().setLevel(level)


def add_file_handler(path: str) -> None:
    """Attach a per-run log file (reference: ``add_file_handler(config.log_file)``)."""
    logger = get_logger()
    with _lock:
        if path in _file_handlers:
            return
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        handler = logging.FileHandler(path)
        handler.setFormatter(logging.Formatter(_FMT, datefmt="%Y-%m-%d %H:%M:%S"))
        logger.addHandler(handler)
        _file_handlers[path] = handler


def remove_file_handler(path: str) -> None:
    logger = get_logger()
    with _lock:
        handler = _file_handlers.pop(path, None)
        if handler is not None:
            logger.removeHandler(handler)
            handler.close()
