"""Wall-clock timing (reference: ``cyy_naive_lib.time_counter.TimeCounter``,
used at ``simulation_lib/training.py:88,136``)."""

import time


class TimeCounter:
    def __init__(self) -> None:
        self._start = time.monotonic()

    def reset_start_time(self) -> None:
        self._start = time.monotonic()

    def elapsed_seconds(self) -> float:
        return time.monotonic() - self._start

    def elapsed_milliseconds(self) -> float:
        return self.elapsed_seconds() * 1000.0
