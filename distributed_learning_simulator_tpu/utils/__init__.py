from .logging import add_file_handler, get_logger
from .timer import TimeCounter

__all__ = ["get_logger", "add_file_handler", "TimeCounter"]
