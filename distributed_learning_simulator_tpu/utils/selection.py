"""Client selection, deterministic in (seed, round).

One shared implementation so the threaded server (``server/server.py``) and
the SPMD fast path (``parallel/spmd.py``) pick identical client subsets for
identical configs (reference selection: ``server/server.py:123-131``).
"""

import random


def select_workers(
    seed: int, round_number: int, worker_number: int, k: int | None
) -> set[int]:
    if k is None or k >= worker_number:
        return set(range(worker_number))
    rng = random.Random(seed * 1_000_003 + round_number)
    return set(rng.sample(range(worker_number), k=k))
