"""Persistent XLA compilation cache.

First compile of the big round programs is slow (tens of seconds on TPU,
minutes on the CPU test mesh); the reference pays the analogous torch
warmup on every process start.  Caching compiled executables on disk makes
every process after the first start hot — notably ``bench.py`` and the
driver's repeated runs.  Opt out with ``DLS_TPU_NO_COMPILE_CACHE=1``.
"""

import os

_enabled = False


def enable_persistent_cache() -> None:
    global _enabled
    if _enabled or os.environ.get("DLS_TPU_NO_COMPILE_CACHE"):
        return
    import jax

    cache_dir = os.environ.get(
        "DLS_TPU_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "dls_tpu_xla"),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: the suite compiles many small programs
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # cache is an optimization, never a hard dependency
        pass
    _enabled = True
