"""Multi-round Shapley value (Song et al. style): per-round SV by exact
enumeration for small player counts, Monte-Carlo permutations otherwise
(reference surface: ``cyy_torch_algorithm.shapely_value.multiround_shapley_value``)."""

import numpy as np

from .base import ShapleyValueEngine, exact_shapley


class MultiRoundShapleyValue(ShapleyValueEngine):
    def __init__(
        self,
        players,
        last_round_metric: float = 0.0,
        exact_player_limit: int = 8,
        mc_permutations: int = 0,
        seed: int = 0,
    ) -> None:
        super().__init__(players, last_round_metric)
        self.exact_player_limit = exact_player_limit
        self.mc_permutations = mc_permutations
        self._rng = np.random.default_rng(seed)

    def compute(self, round_number: int) -> None:
        players = self.players
        n = len(players)
        if n <= self.exact_player_limit:
            sv = self._exact(players)
        else:
            sv = self._monte_carlo(players)
        # evaluate the full coalition so best-subset/last-round metrics exist
        self._metric(players)
        self._finish_round(round_number, sv)

    def _exact(self, players: list) -> dict:
        # all 2^n - 1 coalition metrics are known upfront — evaluate them as
        # one batched program instead of 2^n sequential aggregate+infer runs
        import itertools

        self._metric_many(
            set(subset)
            for r in range(1, len(players) + 1)
            for subset in itertools.combinations(players, r)
        )
        return exact_shapley(players, self._metric)

    def _monte_carlo(self, players: list) -> dict:
        n_perms = self.mc_permutations or max(2 * len(players), 30)
        # plain (non-truncated) permutation sampling touches every prefix of
        # every sampled permutation — also batchable upfront
        perms = [list(self._rng.permutation(players)) for _ in range(n_perms)]
        self._metric_many(
            {frozenset(perm[: i + 1]) for perm in perms for i in range(len(perm))}
        )
        contributions = {p: 0.0 for p in players}
        for perm in perms:
            prefix: set = set()
            prev = self._metric(prefix) if prefix else self.last_round_metric
            for player in perm:
                prefix = prefix | {player}
                current = self._metric(prefix)
                contributions[player] += current - prev
                prev = current
        return {p: v / n_perms for p, v in contributions.items()}
