"""Multi-round Shapley value (Song et al. style): per-round SV by exact
enumeration for small player counts, Monte-Carlo permutations otherwise
(reference surface: ``cyy_torch_algorithm.shapely_value.multiround_shapley_value``)."""

import numpy as np

from .base import ShapleyValueEngine, exact_shapley, monte_carlo_shapley


class MultiRoundShapleyValue(ShapleyValueEngine):
    def __init__(
        self,
        players,
        last_round_metric: float = 0.0,
        exact_player_limit: int = 8,
        mc_permutations: int = 0,
        seed: int = 0,
    ) -> None:
        super().__init__(players, last_round_metric)
        self.exact_player_limit = exact_player_limit
        self.mc_permutations = mc_permutations
        self._rng = np.random.default_rng(seed)

    def compute(self, round_number: int) -> None:
        players = self.players
        n = len(players)
        if n <= self.exact_player_limit:
            sv = self._exact(players)
        else:
            sv = self._monte_carlo(players)
        # evaluate the full coalition so best-subset/last-round metrics exist
        self._metric(players)
        self._finish_round(round_number, sv)

    def _exact(self, players: list) -> dict:
        return exact_shapley(players, self._metric)

    def _monte_carlo(self, players: list) -> dict:
        n_perms = self.mc_permutations or max(2 * len(players), 30)
        return monte_carlo_shapley(players, self._metric, n_perms, self._rng)
