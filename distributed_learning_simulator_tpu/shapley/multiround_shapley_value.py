"""Multi-round Shapley value (Song et al. style): per-round SV by exact
enumeration for small player counts, Monte-Carlo permutations otherwise
(reference surface: ``cyy_torch_algorithm.shapely_value.multiround_shapley_value``)."""

import itertools
import math

import numpy as np

from .base import ShapleyValueEngine


class MultiRoundShapleyValue(ShapleyValueEngine):
    def __init__(
        self,
        players,
        last_round_metric: float = 0.0,
        exact_player_limit: int = 8,
        mc_permutations: int = 0,
        seed: int = 0,
    ) -> None:
        super().__init__(players, last_round_metric)
        self.exact_player_limit = exact_player_limit
        self.mc_permutations = mc_permutations
        self._rng = np.random.default_rng(seed)

    def compute(self, round_number: int) -> None:
        players = self.players
        n = len(players)
        if n <= self.exact_player_limit:
            sv = self._exact(players)
        else:
            sv = self._monte_carlo(players)
        # evaluate the full coalition so best-subset/last-round metrics exist
        self._metric(players)
        self._finish_round(round_number, sv)

    def _exact(self, players: list) -> dict:
        n = len(players)
        sv = {p: 0.0 for p in players}
        for player in players:
            others = [p for p in players if p != player]
            for r in range(n):
                coeff = (
                    math.factorial(r) * math.factorial(n - r - 1) / math.factorial(n)
                )
                for subset in itertools.combinations(others, r):
                    marginal = self._metric(set(subset) | {player}) - self._metric(
                        set(subset)
                    )
                    sv[player] += coeff * marginal
        return sv

    def _monte_carlo(self, players: list) -> dict:
        n = len(players)
        n_perms = self.mc_permutations or max(2 * n, 30)
        contributions = {p: 0.0 for p in players}
        for _ in range(n_perms):
            perm = list(players)
            self._rng.shuffle(perm)
            v_prev = self._metric(())
            coalition: list = []
            for player in perm:
                coalition.append(player)
                v_cur = self._metric(coalition)
                contributions[player] += v_cur - v_prev
                v_prev = v_cur
        return {p: contributions[p] / n_perms for p in players}
