"""Hierarchical (two-level / Owen-style) Shapley value.

The reference ships only a config for this method
(``conf/hierarchical_sv/mnist.yaml``: ``part_number``, ``vp_size``; its
engine was removed from the snapshot — SURVEY.md §2.9).  Recreated from the
config surface as a two-level scheme with a-priori unions:

1. players are partitioned into ``part_number`` groups (round-robin; group
   size bounded by ``vp_size`` when given) — each group is one *virtual
   player*;
2. Shapley values are computed over the groups (metric of a set of groups =
   metric of the union of their members) — exactly up to
   ``exact_group_limit`` groups, by Monte-Carlo permutation sampling above;
3. within each group, member influence is measured *conditionally* — all
   other groups fully present — and the group's top-level value is split
   proportionally to each member's influence magnitude (stable even when
   signed intra-group marginals nearly cancel).

Metric-evaluation count drops from ``2^N`` to roughly
``2^G + G·2^(N/G)`` — the whole point of the hierarchy.
"""

import math
from collections.abc import Iterable

import numpy as np

from .base import ShapleyValueEngine, exact_shapley, monte_carlo_shapley


class HierarchicalShapleyValue(ShapleyValueEngine):
    def __init__(
        self,
        players: Iterable,
        last_round_metric: float = 0.0,
        part_number: int | None = None,
        vp_size: int | None = None,
        exact_group_limit: int = 10,
        mc_permutations: int = 0,
        seed: int = 0,
        **kwargs,
    ) -> None:
        super().__init__(players, last_round_metric)
        n = len(self.players)
        if part_number is None:
            if not vp_size:
                raise ValueError(
                    "Hierarchical_shapley_value needs algorithm_kwargs "
                    "part_number or vp_size (a positive group size)"
                )
            part_number = math.ceil(n / vp_size)
        if part_number <= 0:
            raise ValueError(f"part_number must be positive, got {part_number}")
        self.part_number = min(part_number, n)
        self.exact_group_limit = exact_group_limit
        self.mc_permutations = mc_permutations
        self._rng = np.random.default_rng(seed)
        self.groups: list[list] = [[] for _ in range(self.part_number)]
        for i, player in enumerate(self.players):
            self.groups[i % self.part_number].append(player)
        if vp_size is not None and any(len(g) > vp_size for g in self.groups):
            raise ValueError(
                f"{n} players in {self.part_number} groups exceeds "
                f"vp_size={vp_size}; raise part_number"
            )
        if max(len(g) for g in self.groups) > 12:
            raise ValueError(
                "intra-group exact SV over "
                f"{max(len(g) for g in self.groups)} members would blow up; "
                "use smaller groups (vp_size <= 12)"
            )

    def compute(self, round_number: int) -> None:
        group_ids = list(range(self.part_number))
        if getattr(self, "batch_metric_fn", None) is not None:
            # pre-evaluate every coalition the exact passes below will ask
            # for — one batched aggregate+infer program instead of
            # 2^part_number + Σ_g 2^|g| sequential ones
            import itertools

            wanted: list[set] = []
            if self.part_number <= self.exact_group_limit:
                for r in range(1, self.part_number + 1):
                    for combo in itertools.combinations(group_ids, r):
                        members: set = set()
                        for g in combo:
                            members.update(self.groups[g])
                        wanted.append(members)
            for g in group_ids:
                rest = {
                    p
                    for other in group_ids
                    if other != g
                    for p in self.groups[other]
                }
                for r in range(len(self.groups[g]) + 1):
                    for combo in itertools.combinations(self.groups[g], r):
                        subset = rest | set(combo)
                        if subset:
                            wanted.append(subset)
            self._metric_many(wanted)

        def group_metric(group_subset) -> float:
            members: set = set()
            for g in group_subset:
                members.update(self.groups[g])
            return self._metric(members)

        if self.part_number <= self.exact_group_limit:
            group_sv = exact_shapley(group_ids, group_metric)
        else:
            n_perms = self.mc_permutations or max(2 * self.part_number, 30)
            group_sv = monte_carlo_shapley(
                group_ids, group_metric, n_perms, self._rng
            )

        sv: dict = {}
        for g in group_ids:
            members = self.groups[g]
            rest: set = set()
            for other in group_ids:
                if other != g:
                    rest.update(self.groups[other])

            def member_metric(member_subset) -> float:
                return self._metric(rest | set(member_subset))

            intra = exact_shapley(members, member_metric)
            # split the group's value by influence magnitude: |intra| shares
            # are in [0, 1] and sum to 1, so a group whose signed marginals
            # nearly cancel cannot amplify member values
            denom = sum(abs(v) for v in intra.values())
            if denom < 1e-9:
                share = {m: 1.0 / len(members) for m in members}
            else:
                share = {m: abs(intra[m]) / denom for m in members}
            for m in members:
                sv[m] = group_sv[g] * share[m]

        # evaluate the full coalition so best-subset/last-round metrics exist
        self._metric(self.players)
        self._finish_round(round_number, sv)
