"""GTG-Shapley: Guided Truncation Gradient Shapley for FL participant
contribution (Liu et al., the paper behind the reference's
``gtg_shapley_train.sh`` workload).

Monte-Carlo permutation sampling with:

* **between-round truncation** — if this round's full-coalition metric moved
  less than ``round_trunc_threshold`` from last round, all SVs are 0;
* **within-permutation truncation** — once the running coalition's metric is
  within ``eps`` of the full-coalition metric, remaining marginals are 0;
* **guided sampling** — permutations are seeded round-robin so each player
  leads equally often;
* **convergence check** — stop when the rolling change of the SV estimate
  drops under ``convergence_threshold``.
"""

import itertools

import numpy as np

from ..utils.logging import get_logger
from .base import ShapleyValueEngine


class GTGShapleyValue(ShapleyValueEngine):
    def __init__(
        self,
        players,
        last_round_metric: float = 0.0,
        eps: float = 0.001,
        round_trunc_threshold: float = 0.001,
        convergence_threshold: float = 0.05,
        max_percentage_of_permutations: float = 1.0,
        seed: int = 0,
    ) -> None:
        super().__init__(players, last_round_metric)
        self.eps = eps
        self.round_trunc_threshold = round_trunc_threshold
        self.convergence_threshold = convergence_threshold
        self.max_percentage_of_permutations = max_percentage_of_permutations
        self._rng = np.random.default_rng(seed)

    #: runaway safety valve, NOT a sampling budget — the real stops are
    #: ``convergence_threshold`` and ``max_percentage_of_permutations``
    PERMUTATION_CEILING = 10_000

    def _max_permutations(self) -> int:
        n = len(self.players)
        total = 1
        for i in range(2, n + 1):
            total *= i
            if total > self.PERMUTATION_CEILING:
                break
        total = min(total, self.PERMUTATION_CEILING)
        return max(n, int(total * self.max_percentage_of_permutations))

    def compute(self, round_number: int) -> None:
        players = self.players
        n = len(players)
        full_metric = self._metric(players)
        if abs(full_metric - self.last_round_metric) <= self.round_trunc_threshold:
            get_logger().info(
                "round %s truncated (Δmetric %.5f)",
                round_number,
                full_metric - self.last_round_metric,
            )
            self._finish_round(round_number, {p: 0.0 for p in players})
            return

        contributions = {p: 0.0 for p in players}
        count = 0
        prev_estimate = None
        max_perms = self._max_permutations()
        for k in range(max_perms):
            perm = list(players)
            self._rng.shuffle(perm)
            # guided: rotate so player k%n leads
            lead = players[k % n]
            perm.remove(lead)
            perm.insert(0, lead)

            if getattr(self, "batch_metric_fn", None) is not None:
                # one program evaluates the whole permutation's prefixes;
                # the truncation rule below replays the sequential decisions
                # from the cached values, so the SVs are identical — and so
                # is ``choose_best_subset``: only prefixes the sequential
                # walk actually visits enter ``_considered``, never the
                # extra prefetched ones.  Only when a batch evaluator
                # exists — the sequential fallback would defeat
                # truncation's point
                self._metric_many(
                    {frozenset(perm[: i + 1]) for i in range(len(perm))}
                )
            v_prev = self.last_round_metric
            coalition: list = []
            truncated = False
            for player in perm:
                coalition.append(player)
                if truncated or abs(full_metric - v_prev) <= self.eps:
                    truncated = True
                    marginal = 0.0
                else:
                    v_cur = self._metric(coalition)
                    marginal = v_cur - v_prev
                    v_prev = v_cur
                contributions[player] += marginal
            count += 1

            estimate = np.array([contributions[p] / count for p in players])
            if prev_estimate is not None and count >= n:
                change = float(
                    np.abs(estimate - prev_estimate).sum()
                    / max(float(np.abs(estimate).sum()), 1e-12)
                )
                if change < self.convergence_threshold:
                    break
            prev_estimate = estimate

        sv = {p: contributions[p] / max(count, 1) for p in players}
        self._finish_round(round_number, sv)
