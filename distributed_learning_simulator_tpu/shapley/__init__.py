from .gtg_shapley_value import GTGShapleyValue
from .hierarchical_shapley_value import HierarchicalShapleyValue
from .multiround_shapley_value import MultiRoundShapleyValue

__all__ = [
    "GTGShapleyValue",
    "HierarchicalShapleyValue",
    "MultiRoundShapleyValue",
]
