from .gtg_shapley_value import GTGShapleyValue
from .hierarchical_shapley_value import HierarchicalShapleyValue
from .multiround_shapley_value import MultiRoundShapleyValue

#: hierarchical grouping knobs that live directly in ``algorithm_kwargs``
#: (``conf/hierarchical_sv/mnist.yaml``) rather than under ``sv_kwargs``
HIERARCHICAL_CONFIG_KEYS = ("part_number", "vp_size")


def sv_engine_kwargs(config, hierarchical: bool) -> dict:
    """Engine ctor kwargs beyond (players, last_round_metric) — the ONE
    definition shared by the threaded servers and the SPMD session, so both
    executors construct identically-configured engines."""
    kwargs = dict(config.algorithm_kwargs.get("sv_kwargs", {}))
    if hierarchical:
        for key in HIERARCHICAL_CONFIG_KEYS:
            if key in config.algorithm_kwargs:
                kwargs[key] = config.algorithm_kwargs[key]
    return kwargs


__all__ = [
    "GTGShapleyValue",
    "HierarchicalShapleyValue",
    "MultiRoundShapleyValue",
    "sv_engine_kwargs",
]
