from .gtg_shapley_value import GTGShapleyValue
from .multiround_shapley_value import MultiRoundShapleyValue

__all__ = ["GTGShapleyValue", "MultiRoundShapleyValue"]
