"""Shapley-value engine base.

TPU-native equivalents of the reference's external SV engines
(``cyy_torch_algorithm.shapely_value``; surface per SURVEY.md §2.13: ctor
``(players, last_round_metric)``, ``set_metric_function(cb)``,
``compute(round_number)``, ``.shapley_values``, ``.shapley_values_S``).  The
metric callback re-aggregates a player subset and runs central inference —
the framework batches those evals through one jitted eval program; the
engine itself is pure host logic with per-round subset-metric caching.
"""

from collections.abc import Callable, Iterable


def exact_shapley(players: list, metric: Callable[[set], float]) -> dict:
    """Textbook exact SV (≤ ~12 players) with a cached metric callable."""
    import itertools
    import math

    n = len(players)
    sv = {p: 0.0 for p in players}
    for player in players:
        others = [p for p in players if p != player]
        for r in range(n):
            coeff = math.factorial(r) * math.factorial(n - r - 1) / math.factorial(n)
            for subset in itertools.combinations(others, r):
                marginal = metric(set(subset) | {player}) - metric(set(subset))
                sv[player] += coeff * marginal
    return sv


def monte_carlo_shapley(
    players: list, metric: Callable[[set], float], n_permutations: int, rng
) -> dict:
    """Permutation-sampling SV estimate for player counts where exact
    enumeration blows up."""
    contributions = {p: 0.0 for p in players}
    for _ in range(n_permutations):
        perm = list(players)
        rng.shuffle(perm)
        prefix: set = set()
        prev = metric(prefix)
        for player in perm:
            prefix = prefix | {player}
            current = metric(prefix)
            contributions[player] += current - prev
            prev = current
    return {p: v / n_permutations for p, v in contributions.items()}


class ShapleyValueEngine:
    def __init__(self, players: Iterable, last_round_metric: float = 0.0) -> None:
        self.players: list = sorted(players)
        self.last_round_metric = float(last_round_metric)
        self.metric_fn: Callable[[Iterable], float] | None = None
        # round -> {player: sv}
        self.shapley_values: dict[int, dict] = {}
        # round -> {player: sv} restricted to the best-metric subset
        self.shapley_values_S: dict[int, dict] = {}
        self._cache: dict[frozenset, float] = {}
        # subsets the SEQUENTIAL evaluation order actually visits — the
        # batched prefetch fills ``_cache`` with prefixes a truncated walk
        # never evaluates, and the best-subset pick must not see those
        # (``choose_best_subset`` must behave identically on both paths)
        self._considered: set[frozenset] = set()

    def set_metric_function(self, fn: Callable[[Iterable], float]) -> None:
        self.metric_fn = fn

    def set_batch_metric_function(self, fn: Callable[[list], list]) -> None:
        """Optional fast path: evaluate MANY subsets in one call (the
        framework vmaps subset-aggregation + central inference into one
        program — SURVEY.md §7 hard-part 4 'batch subset evals')."""
        self.batch_metric_fn = fn

    def _metric_many(self, subsets: Iterable[Iterable]) -> None:
        """Populate the cache for all ``subsets`` at once when a batch
        metric is available; falls back to sequential calls."""
        missing = sorted(
            {frozenset(s) for s in subsets if s} - set(self._cache),
            key=sorted,
        )
        if not missing:
            return
        batch_fn = getattr(self, "batch_metric_fn", None)
        if batch_fn is None:
            for subset in missing:
                self._metric(subset)
            return
        values = batch_fn([tuple(sorted(s)) for s in missing])
        for subset, value in zip(missing, values):
            self._cache[subset] = float(value)

    def _metric(self, subset: Iterable) -> float:
        key = frozenset(subset)
        if not key:
            return self.last_round_metric
        self._considered.add(key)
        if key not in self._cache:
            assert self.metric_fn is not None
            self._cache[key] = float(self.metric_fn(tuple(sorted(key))))
        return self._cache[key]

    def _best_subset(self) -> frozenset:
        candidates = self._considered or set(self._cache)
        if not candidates:
            return frozenset()
        # deterministic tie-break (value, then lexicographic members) so the
        # pick cannot depend on cache-insertion order
        return max(
            candidates,
            key=lambda k: (self._cache[k], tuple(sorted(k, reverse=True))),
        )

    def compute(self, round_number: int) -> None:
        raise NotImplementedError

    def _finish_round(self, round_number: int, sv: dict) -> None:
        self.shapley_values[round_number] = dict(sv)
        best = self._best_subset()
        self.shapley_values_S[round_number] = {
            player: sv.get(player, 0.0) for player in sorted(best)
        }
        full_metric = self._cache.get(frozenset(self.players))
        if full_metric is not None:
            self.last_round_metric = full_metric
        self._cache.clear()
        self._considered.clear()
