"""Hyper-parameters and optimizer construction.

TPU-native equivalent of the toolbox hyper-parameter surface the reference
reads from YAML (``optimizer_name``, ``learning_rate``,
``learning_rate_scheduler_name``, ``momentum``, ``weight_decay`` — SURVEY.md
§2.2).  Optimizers are optax transforms; ``CosineAnnealingLR`` is a per-step
cosine schedule over the local run, matching torch's per-epoch cosine in the
limit.
"""

import dataclasses
from typing import Any

import optax


@dataclasses.dataclass
class HyperParameter:
    epoch: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 0.0
    optimizer_name: str = "SGD"
    learning_rate_scheduler_name: str = "CosineAnnealingLR"
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_config(cls, config) -> "HyperParameter":
        return cls(
            epoch=config.epoch,
            batch_size=config.batch_size,
            learning_rate=config.learning_rate,
            momentum=getattr(config, "momentum", 0.9),
            weight_decay=config.weight_decay,
            optimizer_name=config.optimizer_name,
            learning_rate_scheduler_name=config.learning_rate_scheduler_name,
            extra=dict(config.extra_hyper_parameters),
        )

    def make_schedule(self, total_steps: int):
        total_steps = max(1, total_steps)
        name = (self.learning_rate_scheduler_name or "").lower()
        if name in ("cosineannealinglr", "cosine"):
            # torch CosineAnnealingLR parity: the torch formula is PERIODIC in
            # the step count (optax.cosine_decay_schedule instead clamps to 0
            # past decay_steps).  The difference only shows when an optimizer
            # state outlives one schedule span — FedOBD phase 2 'reuse lr'
            # (method/fed_obd/worker.py) — where clamping froze training.
            import jax.numpy as jnp

            base = self.learning_rate

            def periodic_cosine(count):
                return base * 0.5 * (1.0 + jnp.cos(jnp.pi * count / total_steps))

            return periodic_cosine
        if name in ("", "none", "constant", "constantlr"):
            return optax.constant_schedule(self.learning_rate)
        if name in ("linearlr", "linear"):
            return optax.linear_schedule(self.learning_rate, 0.0, total_steps)
        raise KeyError(f"unknown lr scheduler {self.learning_rate_scheduler_name!r}")

    def make_optimizer(self, total_steps: int) -> optax.GradientTransformation:
        schedule = self.make_schedule(total_steps)
        name = self.optimizer_name.lower()
        parts = []
        if self.weight_decay:
            parts.append(optax.add_decayed_weights(self.weight_decay))
        if name == "sgd":
            if self.momentum:
                parts.append(optax.trace(decay=self.momentum, nesterov=False))
            parts.append(optax.scale_by_learning_rate(schedule))
        elif name == "adam":
            parts = [optax.scale_by_adam(), *parts, optax.scale_by_learning_rate(schedule)]
        elif name == "adamw":
            parts = [
                optax.scale_by_adam(),
                optax.add_decayed_weights(self.weight_decay),
                optax.scale_by_learning_rate(schedule),
            ]
        else:
            raise KeyError(f"unknown optimizer {self.optimizer_name!r}")
        return optax.chain(*parts)
