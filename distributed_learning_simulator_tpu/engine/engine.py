"""The compute engine: jitted train/eval programs.

This is the L0 layer of SURVEY.md §1 rebuilt TPU-first: where the reference
delegates to torch's eager batch loop (``Trainer.train()`` in
``cyy_torch_toolbox``), here an **epoch is one XLA program** — ``lax.scan``
over pre-batched, device-resident arrays, with the optimizer update fused in.
No per-batch host round-trips; hooks that need per-batch host visibility fall
back to a single-step program.

One ``ComputeEngine`` is shared by all workers of a task (same model/hyper
params ⇒ same compiled executables; compile once, run N clients).
"""

import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..models.registry import ModelContext
from ..ops.pytree import Params
from .hyper_parameter import HyperParameter


class ComputeEngine:
    def __init__(
        self,
        model_ctx: ModelContext,
        hyper_parameter: HyperParameter,
        total_steps: int,
        grad_sync_axis: str = "",
        grad_sync_fn: Any = None,
    ) -> None:
        self.model_ctx = model_ctx
        self.hyper_parameter = hyper_parameter
        self.total_steps = max(1, total_steps)
        # when the engine runs INSIDE a shard_map that shards the model's
        # compute (sequence parallelism: each device computes a partial
        # backward), gradients must be reduced over that axis before the
        # optimizer update — pmean here, with the model's pooling boundary
        # making pmean uniformly correct (parallel/collectives.py).
        # ``grad_sync_fn`` overrides with a per-leaf rule for layouts
        # where no uniform reduction is right (pipeline parallelism:
        # stage-sharded trunk leaves stay local, replicated leaves pmean
        # — parallel/spmd_pp.py derives why)
        self.grad_sync_axis = grad_sync_axis
        self.grad_sync_fn = grad_sync_fn
        self.optimizer = hyper_parameter.make_optimizer(self.total_steps)
        self.schedule = hyper_parameter.make_schedule(self.total_steps)
        # rematerialization for large client models (ViT/BERT-scale):
        # trade recompute for activation memory — the standard TPU lever
        # when HBM, not FLOPs, binds (extra_hyper_parameters: {remat: true})
        self.use_remat = bool(hyper_parameter.extra.get("remat", False))
        # named checkpoint policy (extra_hyper_parameters:
        # {remat_policy: dots_saveable}): resolved against
        # jax.checkpoint_policies, so `dots_saveable` keeps matmul
        # outputs resident (recompute only the cheap elementwise tail)
        # while `nothing_saveable` is the maximal-recompute bound.
        # Setting a policy implies remat; the bare `remat: true` path
        # (policy-less jax.checkpoint) is untouched and bit-exact.
        self.remat_policy = self._resolve_remat_policy(
            hyper_parameter.extra.get("remat_policy", "")
        )
        if self.remat_policy is not None:
            self.use_remat = True
        # opt-in buffer donation for the jitted entry points
        # (extra_hyper_parameters: {donate_buffers: true}): XLA reuses the
        # incoming params/opt_state buffers for the outputs, halving the
        # entry points' HBM footprint.  OFF by default because the threaded
        # executor's param buffers are shared with host-side caches
        # (ModelCache, best-model hooks) across rounds — only callers that
        # drop the old buffers every call (SPMD-style step-and-replace
        # loops) may turn it on.  Flip before first use of the cached
        # entry points.
        self.donate_buffers = bool(hyper_parameter.extra.get("donate_buffers", False))

    @staticmethod
    def _resolve_remat_policy(name):
        """``remat_policy`` name → the ``jax.checkpoint_policies``
        member, or None when unset.  Unknown names fail loudly with the
        valid vocabulary — a silently-ignored policy would report the
        OLD temp_bytes as a win."""
        if not name:
            return None
        policies = jax.checkpoint_policies
        policy = getattr(policies, str(name), None)
        if policy is None or not callable(policy):
            valid = sorted(
                p for p in dir(policies)
                if not p.startswith("_") and callable(getattr(policies, p))
            )
            raise ValueError(
                f"unknown remat_policy {name!r}; valid jax.checkpoint_policies"
                f" names: {valid}"
            )
        return policy

    # ---- pure functions (also used by the SPMD executor under vmap/shard_map)

    def init_params(self, seed: int) -> Params:
        return self.model_ctx.init(jax.random.PRNGKey(seed))

    def init_opt_state(self, params: Params):
        return self.optimizer.init(params)

    def loss_and_grad(self, params: Params, batch: dict, rng):
        def loss_call(params, batch, rng):
            return self.model_ctx.loss(
                params,
                batch,
                train=True,
                rngs={"dropout": rng} if rng is not None else None,
            )

        if self.use_remat:
            if self.remat_policy is not None:
                loss_call = jax.checkpoint(loss_call, policy=self.remat_policy)
            else:
                loss_call = jax.checkpoint(loss_call)
        return jax.value_and_grad(loss_call, has_aux=True)(params, batch, rng)

    def train_step_fn(self, params, opt_state, batch, rng):
        (loss, aux), grads = self.loss_and_grad(params, batch, rng)
        if self.grad_sync_fn is not None:
            grads = self.grad_sync_fn(grads)
        elif self.grad_sync_axis:
            grads = jax.lax.pmean(grads, self.grad_sync_axis)
        updates, new_opt_state = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # an all-padding batch (SPMD slot padding: shorter clients share the
        # longest client's batch count) must be a TRUE no-op — zero grads
        # still decay the momentum trace and advance the schedule count,
        # which the threaded executor (which never sees these batches)
        # would not do.  Cross-executor trajectory parity pins this.
        nonempty = aux["count"] > 0
        params = jax.tree.map(
            lambda n, o: jnp.where(nonempty, n, o), new_params, params
        )
        opt_state = jax.tree.map(
            lambda n, o: jnp.where(nonempty, n, o), new_opt_state, opt_state
        )
        metrics = {
            "loss": loss,
            "correct": aux["correct"],
            "count": aux["count"],
        }
        return params, opt_state, metrics, grads

    def train_epoch_fn(self, params, opt_state, batches, rng):
        """One epoch as a single scan; returns summed metrics."""

        def body(carry, batch):
            params, opt_state, rng = carry
            rng, step_rng = jax.random.split(rng)
            params, opt_state, metrics, _ = self.train_step_fn(
                params, opt_state, batch, step_rng
            )
            return (params, opt_state, rng), metrics

        (params, opt_state, _), metrics = jax.lax.scan(body, (params, opt_state, rng), batches)
        summed = {
            "loss_sum": jnp.sum(metrics["loss"] * metrics["count"]),
            "correct": jnp.sum(metrics["correct"]),
            "count": jnp.sum(metrics["count"]),
        }
        return params, opt_state, summed

    def eval_fn(self, params, batches):
        """Summed eval metrics over scanned batches.  This is also THE
        in-program evaluate: the horizon-fused SPMD sessions inline it
        inside their round scans (one fetch of ``[H]``-stacked sums per
        dispatch instead of a jitted eval per round) — keep it free of
        host callbacks and Python-side state."""

        def body(carry, batch):
            loss, aux = self.model_ctx.loss(params, batch, train=False)
            carry = {
                "loss_sum": carry["loss_sum"] + jnp.sum(aux["loss_sum"]),
                "correct": carry["correct"] + aux["correct"],
                "count": carry["count"] + aux["count"],
            }
            return carry, None

        init = {
            "loss_sum": jnp.float32(0),
            "correct": jnp.float32(0),
            "count": jnp.float32(0),
        }
        out, _ = jax.lax.scan(body, init, batches)
        return out

    def confusion_fn(self, params, batches):
        """Confusion matrix ``[num_classes, num_classes]`` (rows = true,
        cols = predicted) over scanned batches — the substrate for the
        reference's ``use_slow_performance_metrics`` extras (per-class
        accuracy, macro F1) computed on demand, off the fast path."""
        num_classes = self.model_ctx.num_classes

        cast = self.model_ctx._cast_for_compute  # same dtype as evaluate()

        def body(acc, batch):
            logits = self.model_ctx.apply(
                cast(params), cast(batch["input"]), train=False
            )
            pred = jnp.argmax(logits, axis=-1)
            true_oh = jax.nn.one_hot(batch["target"], num_classes)
            pred_oh = jax.nn.one_hot(pred, num_classes)
            mask = batch["mask"].astype(jnp.float32)
            return acc + jnp.einsum(
                "bt,bp->tp", true_oh * mask[:, None], pred_oh
            ), None

        init = jnp.zeros((num_classes, num_classes), jnp.float32)
        acc, _ = jax.lax.scan(body, init, batches)
        return acc

    def eval_single_fn(self, params, batch):
        loss, aux = self.model_ctx.loss(params, batch, train=False)
        return {
            "loss_sum": jnp.sum(aux["loss_sum"]),
            "correct": aux["correct"],
            "count": aux["count"],
        }

    # ---- jitted entry points (cached per engine instance)

    @functools.cached_property
    def train_epoch(self):
        # donation only on request (see donate_buffers above): default
        # callers share the params/opt_state buffers with host-side caches
        donate = (0, 1) if self.donate_buffers else ()
        return jax.jit(self.train_epoch_fn, donate_argnums=donate)

    @functools.cached_property
    def train_step(self):
        def step(params, opt_state, batch, rng):
            params, opt_state, metrics, _ = self.train_step_fn(params, opt_state, batch, rng)
            return params, opt_state, metrics

        donate = (0, 1) if self.donate_buffers else ()
        return jax.jit(step, donate_argnums=donate)

    @functools.cached_property
    def evaluate(self):
        return jax.jit(self.eval_fn)

    @functools.cached_property
    def confusion(self):
        return jax.jit(self.confusion_fn)

    @functools.cached_property
    def evaluate_single(self):
        return jax.jit(self.eval_single_fn)


def slow_metrics_from_confusion(confusion) -> dict[str, Any]:
    """Per-class accuracy (recall) and macro F1 from a confusion matrix —
    the ``use_slow_performance_metrics`` extras (the reference's toolbox
    computes these via torchmetrics when the flag is on)."""
    import numpy as np

    cm = np.asarray(confusion, np.float64)
    true_pos = np.diag(cm)
    per_class_total = cm.sum(axis=1)
    predicted = cm.sum(axis=0)
    per_class_acc = true_pos / np.maximum(per_class_total, 1.0)
    f1 = 2 * true_pos / np.maximum(per_class_total + predicted, 1.0)
    return {
        "per_class_accuracy": [round(float(a), 6) for a in per_class_acc],
        "macro_f1": float(f1.mean()),
    }


def maybe_slow_metrics(config, engine, params, batches) -> dict[str, Any]:
    """The ``use_slow_performance_metrics`` extras, or ``{}`` when the flag
    is off — one helper for every evaluate-then-record site."""
    if not config.use_slow_performance_metrics:
        return {}
    return slow_metrics_from_confusion(engine.confusion(params, batches))


def summarize_metrics(summed: dict[str, Any]) -> dict[str, float]:
    count = float(summed["count"])
    count = max(count, 1.0)
    return {
        "loss": float(summed["loss_sum"]) / count,
        "accuracy": float(summed["correct"]) / count,
        "count": count,
    }


def stacked_round_metrics(stacked: dict[str, Any]) -> list[dict[str, float]]:
    """Fan an ``[H]``-stacked summed-metrics tree (one ``eval_fn`` result
    per fused round) out into one :func:`summarize_metrics` dict per round.
    This is the horizon sessions' single host sync: ``np.asarray`` here
    fetches the whole stack in one device→host transfer."""
    import numpy as np

    host = {k: np.asarray(v) for k, v in stacked.items()}
    rounds = len(next(iter(host.values())))
    return [
        summarize_metrics({k: v[i] for k, v in host.items()})
        for i in range(rounds)
    ]
