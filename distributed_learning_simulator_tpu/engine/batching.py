"""Static-shape batching.

XLA requires static shapes; datasets whose size is not a multiple of the
batch size are padded with zero-weight samples (``mask``) instead of a ragged
final batch.  A "batched epoch" is a stacked pytree with leading dims
``[n_batches, batch_size, ...]`` fed to ``lax.scan``.
"""

import numpy as np

from ..data.collection import ArrayDataset
from ..ml_type import MachineLearningPhase as Phase


def make_epoch_batches(
    dataset: ArrayDataset,
    batch_size: int,
    rng: np.random.Generator | None = None,
) -> dict:
    """Return {"input": [n, B, ...], "target": [n, B], "mask": [n, B]}."""
    n = len(dataset)
    assert n > 0, "empty dataset"
    order = np.arange(n)
    if rng is not None:
        order = rng.permutation(order)
    n_batches = max(1, (n + batch_size - 1) // batch_size)
    padded = n_batches * batch_size
    pad = padded - n
    order = np.concatenate([order, np.zeros(pad, dtype=order.dtype)])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    # batch assembly via the native gather (one memcpy pass; falls back to
    # numpy fancy indexing when the C++ runtime is unavailable)
    from ..native import gather_rows

    inputs = gather_rows(dataset.inputs, order).reshape(
        n_batches, batch_size, *dataset.inputs.shape[1:]
    )
    targets = gather_rows(dataset.targets, order).reshape(n_batches, batch_size)
    return {
        "input": inputs,
        "target": targets,
        "mask": mask.reshape(n_batches, batch_size),
    }


def make_graph_batch(dataset: ArrayDataset, phase_mask_key: str = "mask") -> dict:
    """Graph datasets train full-batch: one 'batch' = the whole graph, with
    the phase mask as sample weights (transductive node classification)."""
    graph = dataset.inputs
    mask = graph[phase_mask_key].astype(np.float32)
    return {
        "input": {k: v for k, v in graph.items() if k != phase_mask_key},
        "target": dataset.targets,
        "mask": mask,
    }


def fixed_size_partition(indices: np.ndarray, size: int) -> tuple[np.ndarray, np.ndarray]:
    """Pad/truncate an index set to exactly ``size``, returning (indices, mask).

    Used by the SPMD fast path to give every client slot identical shapes.
    """
    n = len(indices)
    if n >= size:
        return indices[:size], np.ones(size, np.float32)
    pad = np.zeros(size - n, dtype=indices.dtype if n else np.int64)
    if n:
        pad = np.full(size - n, indices[0], dtype=indices.dtype)
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(size - n, np.float32)])
    return np.concatenate([indices, pad]), mask


__all__ = ["make_epoch_batches", "make_graph_batch", "fixed_size_partition", "Phase"]
