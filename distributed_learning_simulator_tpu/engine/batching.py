"""Static-shape batching.

XLA requires static shapes; datasets whose size is not a multiple of the
batch size are padded with zero-weight samples (``mask``) instead of a ragged
final batch.  A "batched epoch" is a stacked pytree with leading dims
``[n_batches, batch_size, ...]`` fed to ``lax.scan``.
"""

import numpy as np

from ..data.collection import ArrayDataset
from ..ml_type import MachineLearningPhase as Phase


def make_epoch_batches(
    dataset: ArrayDataset,
    batch_size: int,
    rng: np.random.Generator | None = None,
) -> dict:
    """Return {"input": [n, B, ...], "target": [n, B], "mask": [n, B]}."""
    n = len(dataset)
    assert n > 0, "empty dataset"
    order = np.arange(n)
    if rng is not None:
        order = rng.permutation(order)
    n_batches = max(1, (n + batch_size - 1) // batch_size)
    padded = n_batches * batch_size
    pad = padded - n
    order = np.concatenate([order, np.zeros(pad, dtype=order.dtype)])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    # batch assembly via the native gather (one memcpy pass; falls back to
    # numpy fancy indexing when the C++ runtime is unavailable)
    from ..native import gather_rows

    inputs = gather_rows(dataset.inputs, order).reshape(
        n_batches, batch_size, *dataset.inputs.shape[1:]
    )
    targets = gather_rows(dataset.targets, order).reshape(n_batches, batch_size)
    return {
        "input": inputs,
        "target": targets,
        "mask": mask.reshape(n_batches, batch_size),
    }


def make_graph_batch(dataset: ArrayDataset, phase_mask_key: str = "mask") -> dict:
    """Graph datasets train full-batch: one 'batch' = the whole graph, with
    the phase mask as sample weights (transductive node classification)."""
    graph = dataset.inputs
    mask = graph[phase_mask_key].astype(np.float32)
    return {
        "input": {k: v for k, v in graph.items() if k != phase_mask_key},
        "target": dataset.targets,
        "mask": mask,
    }


def make_graph_minibatches(
    batch: dict,
    batch_number: int,
    num_neighbor: int | None,
    rng: np.random.Generator,
) -> dict:
    """Split a full-graph batch into ``batch_number`` minibatches of training
    nodes (the reference's graph dataloader semantics:
    ``simulation_lib/worker/graph_worker.py:94-101`` — per-epoch shuffled
    near-equal node batches, optional ``num_neighbor`` fan-in sampling).

    The graph stays static-shape: each minibatch is the SAME graph with a
    different loss ``mask`` (that batch's training nodes) and, when
    ``num_neighbor`` is set, a per-batch fan-in-capped ``edge_mask``.
    Batch-invariant leaves are ``np.broadcast_to`` views — no host copies.
    """
    from ..ops.graph_sampling import cap_fan_in

    mask = np.asarray(batch["mask"])
    train_nodes = np.nonzero(mask)[0]
    order = rng.permutation(train_nodes)
    # ALWAYS batch_number batches, even if some come out empty: the
    # share_feature exchange is a synchronous all-worker barrier per batch,
    # so every worker must run the same batch count (the reference forces
    # equal counts the same way, graph_worker.py:94-97); an empty batch is a
    # zero mask (masked_ce_loss guards the 0-count divide)
    n_batches = max(1, int(batch_number))
    masks = np.zeros((n_batches, mask.shape[0]), np.float32)
    for b in range(n_batches):
        masks[b, order[b::n_batches]] = 1.0

    batch_inputs = dict(batch["input"])
    if num_neighbor is not None and "edge_mask" not in batch_inputs:
        batch_inputs["edge_mask"] = np.ones(
            np.asarray(batch_inputs["edge_index"]).shape[1], np.float32
        )
    inputs = {}
    for key, value in batch_inputs.items():
        value = np.asarray(value)
        if key == "edge_mask" and num_neighbor is not None:
            dst = np.asarray(batch["input"]["edge_index"])[1]
            capped = np.zeros((n_batches, value.shape[0]), value.dtype)
            for b in range(n_batches):
                capped[b] = cap_fan_in(
                    value.astype(bool), dst, int(num_neighbor), rng
                )
            inputs[key] = capped
        else:
            inputs[key] = np.broadcast_to(value[None], (n_batches, *value.shape))
    return {
        "input": inputs,
        "target": np.broadcast_to(
            np.asarray(batch["target"])[None],
            (n_batches, *np.asarray(batch["target"]).shape),
        ),
        "mask": masks,
    }


def fixed_size_partition(indices: np.ndarray, size: int) -> tuple[np.ndarray, np.ndarray]:
    """Pad/truncate an index set to exactly ``size``, returning (indices, mask).

    Used by the SPMD fast path to give every client slot identical shapes.
    """
    n = len(indices)
    if n >= size:
        return indices[:size], np.ones(size, np.float32)
    pad = np.zeros(size - n, dtype=indices.dtype if n else np.int64)
    if n:
        pad = np.full(size - n, indices[0], dtype=indices.dtype)
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(size - n, np.float32)])
    return np.concatenate([indices, pad]), mask


__all__ = [
    "make_epoch_batches",
    "make_graph_batch",
    "make_graph_minibatches",
    "fixed_size_partition",
    "Phase",
]
