from .engine import ComputeEngine
from .executor import Inferencer, Trainer
from .hyper_parameter import HyperParameter

__all__ = ["ComputeEngine", "Trainer", "Inferencer", "HyperParameter"]
