"""Trainer / Inferencer with hook points.

TPU-native equivalent of the toolbox ``Trainer``/``Inferencer`` surface the
reference imports everywhere (SURVEY.md §2.13): local training with hook
points (``ExecutorHookPoint``), performance metrics, parameter load/dump.
The hot loop is the jitted epoch scan in :class:`ComputeEngine`; hooks that
need per-batch host visibility (OPTIMIZER_STEP / AFTER_BATCH, used by the
reference's ``GradientWorker``/``GraphWorker``) automatically switch the
epoch to a per-step program.
"""

import time
from collections.abc import Callable
from typing import Any

import jax
import numpy as np

from ..data.collection import DatasetCollection
from ..ml_type import ExecutorHookPoint, MachineLearningPhase, StopExecutingException
from ..models.registry import ModelContext
from ..ops.pytree import Params
from ..utils.logging import get_logger
from .batching import make_epoch_batches, make_graph_batch, make_graph_minibatches
from .engine import ComputeEngine, maybe_slow_metrics, summarize_metrics
from .hyper_parameter import HyperParameter

_PER_STEP_POINTS = (
    ExecutorHookPoint.BEFORE_BATCH,
    ExecutorHookPoint.AFTER_BATCH,
    ExecutorHookPoint.OPTIMIZER_STEP,
)


def aligned_round_stream(seed: int, round_number: int, worker_id: int):
    """The SPMD executor's per-(round, client) rng, reproduced exactly
    (``parallel/spmd.py`` run loop: a split chain from ``PRNGKey(seed)``
    yields each round's rng; ``fold_in(round_rng, worker_id)`` yields the
    client stream).  The threaded executor feeds this to
    :meth:`Trainer.set_round_stream` so both executors train identical
    fed_avg trajectories (``tests/test_executor_matrix.py`` pins it)."""
    rng = jax.random.PRNGKey(seed)
    for _ in range(round_number):
        rng, round_rng = jax.random.split(rng)
    return jax.random.fold_in(round_rng, worker_id)


def obd_aligned_round_stream(
    seed: int, aggregate_index: int, worker_id: int, n_slots: int | None = None
):
    """The FedOBD SPMD session's per-(aggregate, client) rng
    (``parallel/spmd_obd.py`` run loop: a THREE-way split chain —
    ``rng, round_rng, bcast_rng`` per aggregate — with client streams
    from ``split(round_rng, n_slots)``).  ``n_slots`` must be the SPMD
    session's PADDED slot count: split prefixes are NOT slot-count-
    independent under jax's default non-partitionable threefry (a
    ``split(k, 2)`` prefix differs from ``split(k, 8)[:2]``), so replaying
    the stream needs the exact count the session split with.  When omitted
    it is derived from the default mesh the session would build — the
    slot count for ``worker_id + 1`` workers, correct whenever the worker
    count does not exceed one mesh's slot padding."""
    if n_slots is None:
        from ..parallel.mesh import client_slots, make_mesh

        n_slots = client_slots(worker_id + 1, make_mesh())
    rng = jax.random.PRNGKey(seed)
    round_rng = rng
    for _ in range(aggregate_index):
        rng, round_rng, _bcast = jax.random.split(rng, 3)
    return jax.random.split(round_rng, n_slots)[worker_id]


def obd_aligned_bcast_rng(seed: int, aggregate_index: int):
    """The FedOBD SPMD session's broadcast-codec rng for one aggregate —
    the third element of the same 3-way chain (the threaded server's
    quantized broadcast draws it so fed_obd_sq's QSGD distortion matches
    in-program)."""
    rng = jax.random.PRNGKey(seed)
    bcast = rng
    for _ in range(aggregate_index):
        rng, _round, bcast = jax.random.split(rng, 3)
    return bcast


class PerformanceMetric:
    def __init__(self) -> None:
        self.epoch_metrics: dict[int, dict[str, float]] = {}

    def record(self, epoch: int, metrics: dict[str, float]) -> None:
        self.epoch_metrics[epoch] = metrics

    def get_epoch_metric(self, epoch: int, name: str) -> float | None:
        return self.epoch_metrics.get(epoch, {}).get(name)

    @property
    def last(self) -> dict[str, float]:
        if not self.epoch_metrics:
            return {}
        return self.epoch_metrics[max(self.epoch_metrics)]


class ExecutorBase:
    """Shared machinery for Trainer and Inferencer."""

    def __init__(
        self,
        config,
        dataset_collection: DatasetCollection,
        model_ctx: ModelContext,
        engine: ComputeEngine,
        phase: MachineLearningPhase,
        seed: int = 0,
        name: str = "",
    ) -> None:
        self.config = config
        self.dataset_collection = dataset_collection
        self.model_ctx = model_ctx
        self.engine = engine
        self.phase = phase
        self.name = name
        self._seed = seed
        self._params: Params | None = None
        self.performance_metric = PerformanceMetric()
        self.visualizer_prefix = ""
        self._dataloader_kwargs: dict[str, Any] = {}

    def update_dataloader_kwargs(self, **kwargs: Any) -> None:
        """Reference ``Trainer.update_dataloader_kwargs`` — graph workers
        push ``batch_number``/``num_neighbor`` through this
        (``simulation_lib/worker/graph_worker.py:94-101``)."""
        self._dataloader_kwargs.update(kwargs)

    @property
    def hyper_parameter(self) -> HyperParameter:
        return self.engine.hyper_parameter

    # --- parameter surface (reference ModelUtil/Trainer surface) ---
    @property
    def params(self) -> Params:
        if self._params is None:
            self._params = self.engine.init_params(self._seed)
        return self._params

    def get_parameter_dict(self) -> Params:
        return dict(self.params)

    def load_parameter_dict(self, params: Params) -> None:
        self._params = dict(params)

    @property
    def dataset_size(self) -> int:
        return self.dataset_collection.dataset_size(self.phase)

    def set_visualizer_prefix(self, prefix: str) -> None:
        self.visualizer_prefix = prefix

    # device management is a no-op under single-controller JAX (the reference
    # needed a cross-process device lock, executor.py:41-96)
    def set_device(self, *args, **kwargs) -> None:
        pass

    def offload_from_device(self) -> None:
        pass

    def wait_stream(self) -> None:
        jax.block_until_ready(jax.tree.leaves(self.params))

    def _epoch_batches(self, phase: MachineLearningPhase, shuffle_seed: int | None):
        dataset = self.dataset_collection.get_dataset(phase)
        if self.dataset_collection.dataset_type == "graph" or isinstance(
            dataset.inputs, dict
        ):
            batch = make_graph_batch(dataset)
            batch_number = int(self._dataloader_kwargs.get("batch_number") or 1)
            num_neighbor = self._dataloader_kwargs.get("num_neighbor")
            if shuffle_seed is not None and (
                batch_number > 1 or num_neighbor is not None
            ):
                # the reference's graph dataloader: per-epoch shuffled node
                # minibatches + neighbor sampling (graph_worker.py:94-101)
                return make_graph_minibatches(
                    batch,
                    batch_number,
                    num_neighbor,
                    np.random.default_rng(shuffle_seed),
                )
            return jax.tree.map(lambda x: np.asarray(x)[None], batch)  # 1-batch epoch
        rng = None if shuffle_seed is None else np.random.default_rng(shuffle_seed)
        return make_epoch_batches(dataset, self.hyper_parameter.batch_size, rng)


class Trainer(ExecutorBase):
    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, phase=MachineLearningPhase.Training, **kwargs)
        self._hooks: dict[ExecutorHookPoint, dict[str, Callable]] = {}
        self._disabled_hooks: set[str] = set()
        self._opt_state = None
        self._rng = jax.random.PRNGKey(self._seed + 0x5EED)
        self._epoch_counter = 0  # cumulative epochs across rounds
        self._round_stream = None  # SPMD-aligned rng for the next round
        #: the quant rng the aligned stream reserved this round (the key
        #: the SPMD local_train hands its in-program codec) — a worker
        #: passes it to its quantized endpoint for codec parity (fed_paq)
        self.reserved_quant_rng = None
        self.batch_loss_log_enabled = True

    def set_round_stream(self, rng) -> None:
        """Arm the next ``train()`` call with an SPMD-aligned rng stream
        (:func:`aligned_round_stream`): epoch rngs split exactly like
        ``scan_local_epochs`` (a quant rng is reserved first, matching
        ``local_train``), and per-epoch shuffling is disabled — the SPMD
        path trains the stacked sampler-order batches every epoch, and
        batch parity is part of trajectory parity.  One-shot: cleared when
        consumed."""
        self._round_stream = rng

    # --- hook API (reference Trainer.append_named_hook/remove_hook/...) ---
    def append_named_hook(
        self, hook_point: ExecutorHookPoint, name: str, fn: Callable
    ) -> None:
        self._hooks.setdefault(hook_point, {})[name] = fn

    def remove_named_hook(self, name: str, hook_point: ExecutorHookPoint | None = None) -> None:
        points = [hook_point] if hook_point else list(self._hooks)
        for point in points:
            self._hooks.get(point, {}).pop(name, None)

    def has_hook(self, hook_point: ExecutorHookPoint) -> bool:
        return any(
            name not in self._disabled_hooks
            for name in self._hooks.get(hook_point, {})
        )

    def disable_hook(self, name: str) -> None:
        self._disabled_hooks.add(name)

    def enable_hook(self, name: str) -> None:
        self._disabled_hooks.discard(name)

    def _fire(self, hook_point: ExecutorHookPoint, **kwargs) -> None:
        for name, fn in list(self._hooks.get(hook_point, {}).items()):
            if name in self._disabled_hooks:
                continue
            fn(executor=self, hook_point=hook_point, **kwargs)

    # --- optimizer state ---
    @property
    def opt_state(self):
        if self._opt_state is None:
            self._opt_state = self.engine.init_opt_state(self.params)
        return self._opt_state

    def reset_optimizer(self) -> None:
        self._opt_state = None

    def load_parameter_dict(self, params: Params, reuse_learning_rate: bool = False) -> None:
        """Reference ``load_parameters`` (``util/model.py:6-23``): loading new
        global params rebuilds the optimizer unless lr state is reused
        (FedOBD phase 2)."""
        super().load_parameter_dict(params)
        if not reuse_learning_rate:
            self._opt_state = None

    # --- the round-local training loop ---
    def train(self, **kwargs) -> None:
        hp = self.hyper_parameter
        self._fire(ExecutorHookPoint.BEFORE_EXECUTE)
        per_step = any(self.has_hook(p) for p in _PER_STEP_POINTS)
        aligned, self._round_stream = self._round_stream, None
        self.reserved_quant_rng = None
        if aligned is not None:
            train_rng, quant_rng = jax.random.split(aligned)
            aligned_epoch_rngs = jax.random.split(train_rng, hp.epoch)
            self.reserved_quant_rng = quant_rng
        try:
            for epoch in range(1, hp.epoch + 1):
                start = time.monotonic()
                self._epoch_counter += 1
                shuffle_seed = (
                    None
                    if aligned is not None
                    else self._seed * 100003 + self._epoch_counter
                )
                batches = self._epoch_batches(self.phase, shuffle_seed)
                self._fire(ExecutorHookPoint.BEFORE_EPOCH, epoch=epoch)
                if aligned is not None:
                    epoch_rng = aligned_epoch_rngs[epoch - 1]
                else:
                    self._rng, epoch_rng = jax.random.split(self._rng)
                # graph minibatch epochs stack batch-invariant leaves as
                # zero-copy broadcast VIEWS; the jitted scan would transfer
                # them densely (graph × batch_number on device), so step
                # batch-by-batch instead — each step uploads one graph copy
                graph_minibatch = (
                    isinstance(batches["input"], dict)
                    and batches["target"].shape[0] > 1
                )
                if per_step or graph_minibatch:
                    summed = self._train_epoch_per_step(batches, epoch, epoch_rng)
                else:
                    params, opt_state, summed = self.engine.train_epoch(
                        self.params, self.opt_state, batches, epoch_rng
                    )
                    self._params, self._opt_state = params, opt_state
                metrics = summarize_metrics(summed)
                metrics["duration"] = time.monotonic() - start
                self.performance_metric.record(self._epoch_counter, metrics)
                if self.batch_loss_log_enabled or self.config is None or self.config.debug:
                    get_logger().info(
                        "%s epoch %d loss %.4f acc %.4f (%.2fs)",
                        self.visualizer_prefix or self.name,
                        epoch,
                        metrics["loss"],
                        metrics["accuracy"],
                        metrics["duration"],
                    )
                self._fire(
                    ExecutorHookPoint.AFTER_EPOCH, epoch=epoch, epoch_metrics=metrics
                )
            self._fire(ExecutorHookPoint.AFTER_EXECUTE)
        except StopExecutingException:
            get_logger().debug("%s stopped by hook", self.name)

    def _train_epoch_per_step(self, batches, epoch: int, epoch_rng) -> dict:
        n_batches = batches["target"].shape[0]
        totals = {"loss_sum": 0.0, "correct": 0.0, "count": 0.0}
        step_rngs = jax.random.split(epoch_rng, n_batches)
        for i in range(n_batches):
            batch = jax.tree.map(lambda x: x[i], batches)
            self._fire(
                ExecutorHookPoint.BEFORE_BATCH, epoch=epoch, batch_index=i, batch=batch
            )
            if self.has_hook(ExecutorHookPoint.OPTIMIZER_STEP):
                # the hook owns the optimizer step (reference GradientWorker
                # semantics, gradient_worker.py:50-116)
                self._fire(
                    ExecutorHookPoint.OPTIMIZER_STEP,
                    epoch=epoch,
                    batch_index=i,
                    batch=batch,
                    step_rng=step_rngs[i],
                )
                result = self.engine.evaluate_single(self.params, batch)
                summed = {
                    "loss_sum": result["loss_sum"],
                    "correct": result["correct"],
                    "count": result["count"],
                }
            else:
                params, opt_state, metrics = self.engine.train_step(
                    self.params, self.opt_state, batch, step_rngs[i]
                )
                self._params, self._opt_state = params, opt_state
                summed = {
                    "loss_sum": metrics["loss"] * metrics["count"],
                    "correct": metrics["correct"],
                    "count": metrics["count"],
                }
            for key in totals:
                totals[key] += float(summed[key])
            self._fire(
                ExecutorHookPoint.AFTER_BATCH,
                epoch=epoch,
                batch_index=i,
                batch=batch,
                batch_size=float(summed["count"]),
            )
        return totals


class Inferencer(ExecutorBase):
    def __init__(self, *args, phase=MachineLearningPhase.Test, **kwargs) -> None:
        super().__init__(*args, phase=phase, **kwargs)
        self._cached_batches = None

    def _eval_batches(self):
        """Eval batches under the ``cache_transforms`` policy (reference
        global knob, ``conf/global.yaml:1``): the split is fixed and the
        slicing deterministic, so "cpu" caches the host batch list across
        rounds and "device" keeps it device-resident (saves the per-round
        test-set re-upload on the threaded path — the SPMD executor always
        does this); "none" rebuilds every call."""
        cache = str(self.config.cache_transforms or "none").lower()
        if cache == "none":
            return self._epoch_batches(self.phase, shuffle_seed=None)
        if self._cached_batches is None:
            batches = self._epoch_batches(self.phase, shuffle_seed=None)
            if cache == "device":
                batches = jax.device_put(batches)
            self._cached_batches = batches
        return self._cached_batches

    def inference(self) -> dict[str, float]:
        batches = self._eval_batches()
        summed = self.engine.evaluate(self.params, batches)
        metrics = summarize_metrics(summed)
        metrics.update(
            maybe_slow_metrics(self.config, self.engine, self.params, batches)
        )
        self.performance_metric.record(len(self.performance_metric.epoch_metrics) + 1, metrics)
        return metrics
