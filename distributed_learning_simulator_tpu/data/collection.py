"""Dataset collections with phase splits.

TPU-native equivalent of the reference's toolbox ``DatasetCollection`` /
``ClassificationDatasetCollection`` surface (SURVEY.md §2.13): named datasets
with Training/Validation/Test splits, subsettable per worker.  Data lives as
host numpy arrays; the trainer engine moves (sharded) batches onto the mesh.
"""

import dataclasses
from typing import Any

import numpy as np

from ..ml_type import MachineLearningPhase


@dataclasses.dataclass
class ArrayDataset:
    """One split: ``inputs`` is an array or a dict of arrays (graph data),
    ``targets`` the labels."""

    inputs: Any
    targets: np.ndarray

    def __len__(self) -> int:
        if isinstance(self.inputs, dict):
            # graph split: effective size = nodes under the phase mask
            return int(self.inputs["mask"].sum())
        return int(len(self.targets))

    def subset(self, indices: np.ndarray) -> "ArrayDataset":
        if isinstance(self.inputs, dict):
            # graph datasets keep global shapes; a subset narrows the phase
            # mask to this worker's nodes (static shapes for XLA)
            mask = np.zeros_like(self.inputs["mask"])
            if len(indices):
                selected = indices[self.inputs["mask"][indices]]
                mask[selected] = True
            return ArrayDataset(
                inputs={**self.inputs, "mask": mask}, targets=self.targets
            )
        return ArrayDataset(inputs=self.inputs[indices], targets=self.targets[indices])


@dataclasses.dataclass
class DatasetCollection:
    name: str
    datasets: dict[MachineLearningPhase, ArrayDataset]
    num_classes: int
    input_shape: tuple[int, ...]
    dataset_type: str = "vision"  # vision | text | graph
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)

    def get_dataset(self, phase: MachineLearningPhase) -> ArrayDataset:
        return self.datasets[phase]

    def has_dataset(self, phase: MachineLearningPhase) -> bool:
        return phase in self.datasets

    def remove_dataset(self, phase: MachineLearningPhase) -> None:
        """Reference workers drop the Test (and usually Validation) splits
        locally (``aggregation_worker.py:25-40``)."""
        self.datasets.pop(phase, None)

    def dataset_size(self, phase: MachineLearningPhase) -> int:
        return len(self.datasets[phase])

    def subset(self, phase_indices: dict[MachineLearningPhase, np.ndarray]) -> "DatasetCollection":
        """A per-worker view holding only this worker's partition."""
        datasets = {}
        for phase, dataset in self.datasets.items():
            if phase in phase_indices:
                datasets[phase] = dataset.subset(phase_indices[phase])
            else:
                datasets[phase] = dataset
        return DatasetCollection(
            name=self.name,
            datasets=datasets,
            num_classes=self.num_classes,
            input_shape=self.input_shape,
            dataset_type=self.dataset_type,
            metadata=dict(self.metadata),
        )


def create_dataset_collection(config) -> DatasetCollection:
    from .registry import global_dataset_factory

    factory = global_dataset_factory.get(config.dataset_name)
    if factory is None:
        raise KeyError(
            f"unknown dataset {config.dataset_name!r}; known: {sorted(global_dataset_factory)}"
        )
    dc = factory(**dict(config.dataset_kwargs))
    if config.merge_validation_to_training_set and dc.has_dataset(
        MachineLearningPhase.Validation
    ):
        train = dc.get_dataset(MachineLearningPhase.Training)
        val = dc.get_dataset(MachineLearningPhase.Validation)
        if not isinstance(train.inputs, dict):
            dc.datasets[MachineLearningPhase.Training] = ArrayDataset(
                inputs=np.concatenate([train.inputs, val.inputs]),
                targets=np.concatenate([train.targets, val.targets]),
            )
            dc.remove_dataset(MachineLearningPhase.Validation)
    return dc
