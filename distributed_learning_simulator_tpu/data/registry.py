"""Dataset registry + deterministic synthetic generators.

The reference registers its datasets by importing ``cyy_torch_vision`` /
``cyy_torch_text`` / ``cyy_torch_graph`` for side effects
(``common_import.py:1-16``); dataset names come from ``conf/**`` YAMLs
(MNIST, CIFAR10/100, imdb, Coauthor_CS, Cora, ...).  This build runs in a
zero-egress environment, so each name maps to a **deterministic synthetic
generator** with the real dataset's shape/class structure (class-prototype +
noise, so models actually learn and accuracy curves are meaningful).  If real
data is present on disk (``$DLS_TPU_DATA_DIR/<name>.npz`` with ``x_train``,
``y_train``, ``x_test``, ``y_test``), it is used instead.
"""

import hashlib
from collections.abc import Callable

import numpy as np

from ..ml_type import MachineLearningPhase as Phase
from .collection import ArrayDataset, DatasetCollection

global_dataset_factory: dict[str, Callable[..., DatasetCollection]] = {}


def register_dataset(name: str):
    def deco(fn):
        global_dataset_factory[name] = fn
        return fn

    return deco


def _seed_for(name: str) -> int:
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")


def _try_load_real(name: str, **kwargs) -> DatasetCollection | None:
    """Real data from ``$DLS_TPU_DATA_DIR/<name>.npz`` (see ``data/real.py``
    for the schema and ``tools/ingest_data.py`` for producing it)."""
    from .real import load_real_collection

    return load_real_collection(name, **kwargs)


def _synthetic_vision(
    name: str,
    shape: tuple[int, ...],
    num_classes: int,
    train_size: int,
    val_size: int,
    test_size: int,
    noise: float = 0.35,
) -> DatasetCollection:
    """Class-prototype images + scale jitter + gaussian noise: linearly
    learnable, deterministic in the dataset name."""
    rng = np.random.default_rng(_seed_for(name))
    prototypes = rng.normal(0.0, 1.0, size=(num_classes, *shape)).astype(np.float32)

    def make(n: int, split_salt: int) -> ArrayDataset:
        r = np.random.default_rng(_seed_for(name) + split_salt)
        labels = r.integers(0, num_classes, size=n).astype(np.int32)
        scale = r.uniform(0.6, 1.4, size=(n,) + (1,) * len(shape)).astype(np.float32)
        x = prototypes[labels] * scale + r.normal(0, noise, size=(n, *shape)).astype(np.float32)
        return ArrayDataset(x.astype(np.float32), labels)

    return DatasetCollection(
        name=name,
        datasets={
            Phase.Training: make(train_size, 1),
            Phase.Validation: make(val_size, 2),
            Phase.Test: make(test_size, 3),
        },
        num_classes=num_classes,
        input_shape=shape,
        dataset_type="vision",
    )


def _vision_factory(name: str, shape: tuple[int, ...], num_classes: int, default_train: int):
    @register_dataset(name)
    def factory(
        train_size: int = default_train,
        val_size: int = 0,
        test_size: int = 0,
        **_: object,
    ) -> DatasetCollection:
        real = _try_load_real(name)
        if real is not None:
            return real
        val_size_ = val_size or max(256, train_size // 8)
        test_size_ = test_size or max(512, train_size // 4)
        return _synthetic_vision(name, shape, num_classes, train_size, val_size_, test_size_)

    return factory


# shapes/class-counts mirror the real datasets named in the reference's conf/**
_vision_factory("MNIST", (28, 28, 1), 10, 4096)
_vision_factory("FashionMNIST", (28, 28, 1), 10, 4096)
_vision_factory("CIFAR10", (32, 32, 3), 10, 4096)
_vision_factory("CIFAR100", (32, 32, 3), 100, 8192)
_vision_factory("IMAGENET", (64, 64, 3), 100, 8192)


def _synthetic_text(
    name: str,
    num_classes: int,
    vocab_size: int,
    max_len: int,
    train_size: int,
    val_size: int,
    test_size: int,
) -> DatasetCollection:
    """Class-dependent unigram token distributions over a shared vocab; pad=0."""
    seed = _seed_for(name)
    rng = np.random.default_rng(seed)
    # each class boosts a random subset of "topic" tokens
    logits = rng.normal(0, 1.0, size=(num_classes, vocab_size)).astype(np.float64)
    logits[:, 0] = -np.inf  # pad token never sampled
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)

    def make(n: int, salt: int) -> ArrayDataset:
        r = np.random.default_rng(seed + salt)
        labels = r.integers(0, num_classes, size=n).astype(np.int32)
        lengths = r.integers(max_len // 4, max_len + 1, size=n)
        tokens = np.zeros((n, max_len), dtype=np.int32)
        for c in range(num_classes):
            idx = np.nonzero(labels == c)[0]
            if idx.size == 0:
                continue
            draws = r.choice(vocab_size, size=(idx.size, max_len), p=probs[c])
            tokens[idx] = draws
        mask = np.arange(max_len)[None, :] < lengths[:, None]
        tokens = np.where(mask, tokens, 0).astype(np.int32)
        return ArrayDataset(tokens, labels)

    return DatasetCollection(
        name=name,
        datasets={
            Phase.Training: make(train_size, 11),
            Phase.Validation: make(val_size, 12),
            Phase.Test: make(test_size, 13),
        },
        num_classes=num_classes,
        input_shape=(max_len,),
        dataset_type="text",
        metadata={"vocab_size": vocab_size, "max_len": max_len, "pad_id": 0},
    )


def _text_factory(name: str, num_classes: int, default_train: int):
    @register_dataset(name)
    def factory(
        max_len: int = 300,
        vocab_size: int = 20000,
        train_size: int = default_train,
        val_size: int = 0,
        test_size: int = 0,
        tokenizer: dict | None = None,
        **_: object,
    ) -> DatasetCollection:
        from .tokenizer import resolve_tokenizer_type

        real = _try_load_real(name, max_len=max_len)
        if real is not None:
            # validate/dispatch dataset_kwargs.tokenizer (reference
            # conf/fed_avg/imdb.yaml:16-18) against the ingested export
            real.metadata["tokenizer"] = resolve_tokenizer_type(
                tokenizer, real.metadata
            )
            return real
        resolve_tokenizer_type(tokenizer, None)  # reject unknown types loudly
        val_size_ = val_size or max(256, train_size // 8)
        test_size_ = test_size or max(512, train_size // 4)
        return _synthetic_text(
            name, num_classes, vocab_size, max_len, train_size, val_size_, test_size_
        )

    return factory


_text_factory("imdb", 2, 4096)
_text_factory("IMDB", 2, 4096)
_text_factory("AGNews", 4, 8192)


def _synthetic_graph(
    name: str,
    num_nodes: int,
    num_features: int,
    num_classes: int,
    avg_degree: int = 10,
    homophily: float = 0.8,
) -> DatasetCollection:
    """Stochastic-block-model node-classification graph with class-prototype
    features (synthetic stand-ins for Cora / Coauthor-CS / ... named in
    ``conf/fed_gnn``/``conf/fed_aas``)."""
    seed = _seed_for(name)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=num_nodes).astype(np.int32)
    prototypes = rng.normal(0, 1.0, size=(num_classes, num_features)).astype(np.float32)
    x = prototypes[labels] + rng.normal(0, 0.6, size=(num_nodes, num_features)).astype(np.float32)

    n_edges = num_nodes * avg_degree
    src = rng.integers(0, num_nodes, size=2 * n_edges)
    # homophilous wiring: with prob `homophily` rewire dst into same class
    dst = rng.integers(0, num_nodes, size=2 * n_edges)
    same = rng.random(2 * n_edges) < homophily
    by_class = [np.nonzero(labels == c)[0] for c in range(num_classes)]
    for c in range(num_classes):
        idx = np.nonzero(same & (labels[src] == c))[0]
        if idx.size and by_class[c].size:
            dst[idx] = rng.choice(by_class[c], size=idx.size)
    keep = src != dst
    edge_index = np.stack([src[keep], dst[keep]])[:, :n_edges]
    # symmetrize
    edge_index = np.concatenate([edge_index, edge_index[::-1]], axis=1).astype(np.int32)

    perm = rng.permutation(num_nodes)
    n_train = int(num_nodes * 0.6)
    n_val = int(num_nodes * 0.2)
    masks = {}
    for phase, sl in (
        (Phase.Training, perm[:n_train]),
        (Phase.Validation, perm[n_train : n_train + n_val]),
        (Phase.Test, perm[n_train + n_val :]),
    ):
        mask = np.zeros(num_nodes, dtype=bool)
        mask[sl] = True
        masks[phase] = mask

    datasets = {
        phase: ArrayDataset(
            inputs={"x": x, "edge_index": edge_index, "mask": masks[phase]},
            targets=labels,
        )
        for phase in masks
    }
    return DatasetCollection(
        name=name,
        datasets=datasets,
        num_classes=num_classes,
        input_shape=(num_features,),
        dataset_type="graph",
        metadata={"num_nodes": num_nodes, "num_edges": int(edge_index.shape[1])},
    )


def _graph_factory(name: str, num_nodes: int, num_features: int, num_classes: int):
    @register_dataset(name)
    def factory(
        num_nodes_: int = 0, num_features_: int = 0, **_: object
    ) -> DatasetCollection:
        real = _try_load_real(name)
        if real is not None:
            return real
        return _synthetic_graph(
            name, num_nodes_ or num_nodes, num_features_ or num_features, num_classes
        )

    return factory


# real datasets' class counts; node/feature counts scaled down for synthetic runs
_graph_factory("Cora", 2048, 128, 7)
_graph_factory("PubMed", 2048, 128, 3)
_graph_factory("Coauthor_CS", 4096, 128, 15)
_graph_factory("dblp", 2048, 128, 4)
_graph_factory("reddit", 4096, 128, 41)
_graph_factory("Reddit", 4096, 128, 41)
_graph_factory("yelp", 4096, 128, 10)
_graph_factory("AmazonProduct", 4096, 128, 12)
_graph_factory("amazonproduct", 4096, 128, 12)


@register_dataset("CitationFull")
def _citation_full(name: str = "DBLP", **kwargs: object) -> DatasetCollection:
    """Reference ``conf/fed_aas/dblp.yaml`` selects a CitationFull sub-dataset
    via ``dataset_kwargs: {name: DBLP}`` (torch_geometric CitationFull)."""
    class_counts = {"DBLP": 4, "Cora": 70, "Cora_ML": 7, "CiteSeer": 6, "PubMed": 3}
    return _synthetic_graph(
        f"CitationFull_{name}", 2048, 128, class_counts.get(str(name), 4)
    )
