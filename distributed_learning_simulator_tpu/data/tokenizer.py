"""Deterministic vocab-file tokenizer.

The reference tokenizes IMDB with spacy (``conf/fed_avg/imdb.yaml:16-18``,
``dataset_kwargs.tokenizer.type: spacy``); that requires a model download,
so this build uses a deterministic regex word tokenizer — the SAME one
``tools/ingest_data.py`` used to build the dataset, guaranteeing train-time
and inference-time token ids agree.  The vocab rides in the dataset npz
(``vocab`` key) or any one-word-per-line text file.
"""

import re

import numpy as np

from ..utils.logging import get_logger

_WORD_RE = re.compile(r"[a-z0-9']+")

#: tokenizer types the config surface accepts
#: (``dataset_kwargs.tokenizer.type``; the reference's IMDB configs say
#: ``spacy`` — ``conf/fed_avg/imdb.yaml:16-18``)
KNOWN_TOKENIZER_TYPES = ("spacy", "regex")


def resolve_tokenizer_type(
    tokenizer_kwargs: dict | None, metadata: dict | None = None
) -> str | None:
    """Validate and dispatch ``dataset_kwargs.tokenizer``.

    ``spacy`` resolves to the ingested npz's PRE-TOKENIZED ids when the
    dataset was exported with spacy token ids (``tools/ingest_data.py
    --tokenized-json``, metadata ``tokenizer_type == "spacy"``) — real-IMDB
    ids then match the reference's exactly.  Without such an export the
    deterministic regex tokenizer stands in (zero egress: no spacy model
    download) and says so loudly.  Unknown types are rejected rather than
    silently dropped (same loud-failure standard as ``cache_transforms``).
    """
    if not tokenizer_kwargs:
        return None
    if isinstance(tokenizer_kwargs, str):  # shorthand: `tokenizer: spacy`
        tokenizer_kwargs = {"type": tokenizer_kwargs}
    requested = str(tokenizer_kwargs.get("type", "regex")).lower()
    if requested not in KNOWN_TOKENIZER_TYPES:
        raise ValueError(
            f"dataset_kwargs.tokenizer.type must be one of "
            f"{KNOWN_TOKENIZER_TYPES}, got {requested!r}"
        )
    ingested = (metadata or {}).get("tokenizer_type")
    if requested == "spacy" and ingested != "spacy":
        get_logger().warning(
            "tokenizer.type=spacy requested but the dataset carries no "
            "spacy-tokenized export (ingest with --tokenized-json to match "
            "reference ids); using the deterministic regex tokenizer"
        )
        return "regex"
    return requested

PAD_ID = 0
UNK_ID = 1
N_SPECIALS = 2


def tokenize(text: str) -> list[str]:
    return _WORD_RE.findall(text.lower().replace("<br />", " "))


class VocabTokenizer:
    """text → fixed-length int32 id rows, pad=0/unk=1, deterministic."""

    def __init__(self, vocab: list[str], max_len: int = 300) -> None:
        self.vocab = list(vocab)
        self.max_len = int(max_len)
        self._index = {w: i + N_SPECIALS for i, w in enumerate(self.vocab)}

    @classmethod
    def from_file(cls, path: str, max_len: int = 300) -> "VocabTokenizer":
        with open(path, encoding="utf8") as f:
            vocab = [line.strip() for line in f if line.strip()]
        return cls(vocab, max_len)

    @classmethod
    def from_dataset(cls, dataset_collection) -> "VocabTokenizer":
        meta = dataset_collection.metadata
        if not meta.get("vocab"):
            raise ValueError(
                f"dataset {dataset_collection.name!r} carries no vocab "
                "(synthetic datasets have none; ingest real data first)"
            )
        return cls(meta["vocab"], meta.get("max_len", 300))

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + N_SPECIALS

    def encode(self, text: str) -> np.ndarray:
        ids = [self._index.get(t, UNK_ID) for t in tokenize(text)[: self.max_len]]
        out = np.full(self.max_len, PAD_ID, np.int32)
        out[: len(ids)] = ids
        return out

    def encode_batch(self, texts: list[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts])

    def decode(self, ids) -> list[str]:
        words = []
        for token_id in np.asarray(ids).tolist():
            if token_id == PAD_ID:
                continue
            if token_id == UNK_ID:
                words.append("<unk>")
            elif 0 <= token_id - N_SPECIALS < len(self.vocab):
                words.append(self.vocab[token_id - N_SPECIALS])
        return words
