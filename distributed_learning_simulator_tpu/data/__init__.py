from .collection import ArrayDataset, DatasetCollection, create_dataset_collection
from .registry import global_dataset_factory, register_dataset

__all__ = [
    "ArrayDataset",
    "DatasetCollection",
    "create_dataset_collection",
    "global_dataset_factory",
    "register_dataset",
]
