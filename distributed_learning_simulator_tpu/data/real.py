"""Real-dataset loading from ``$DLS_TPU_DATA_DIR``.

The reference trains on real MNIST/CIFAR/IMDB/Coauthor-CS via the
``cyy_torch_vision`` / ``cyy_torch_text`` / ``cyy_torch_graph`` registries
(``/root/reference/simulation_lib/method/common_import.py:1-2``).  This
build runs zero-egress, so real data enters through a documented on-disk
schema instead: ``$DLS_TPU_DATA_DIR/<dataset_name>.npz``, produced by
``tools/ingest_data.py`` from the standard distribution formats (MNIST
idx, CIFAR pickle batches, aclImdb text, planetoid pickles).

Three schemas, detected by key inspection:

**vision / tabular** (``kind`` absent or ``b"vision"``)::

    x_train [N,...]  uint8 or float32   y_train [N] int
    x_test  [M,...]                     y_test  [M] int
    x_val/y_val      optional (otherwise test is split in half)
    mean/std [C]     optional float32; uint8 inputs become
                     ((x/255) - mean) / std at load time

**text** (``kind == b"text"``)::

    x_train [N,L] int  (token ids, 0 = pad)   y_train [N] int
    x_test  [M,L] int                         y_test  [M] int
    vocab_size, max_len, pad_id   scalars
    vocab [V] unicode             optional, index-aligned with token ids
                                  (feeds the GloVe embedding loader)

**graph** (``kind == b"graph"``)::

    x [N,F] float32        edge_index [2,E] int
    y [N] int              train_mask/val_mask/test_mask [N] bool
"""

import os

import numpy as np

from ..ml_type import MachineLearningPhase as Phase
from .collection import ArrayDataset, DatasetCollection


def data_dir() -> str:
    return os.environ.get("DLS_TPU_DATA_DIR", "")


def real_path(name: str) -> str | None:
    base = data_dir()
    if not base:
        return None
    path = os.path.join(base, f"{name}.npz")
    if os.path.isfile(path):
        return path
    # case-insensitive fallback: config aliases differ in case from the
    # ingested file name (dataset_name: IMDB vs ingested imdb.npz)
    want = f"{name}.npz".lower()
    try:
        entries = os.listdir(base)
    except OSError:
        return None
    for entry in entries:
        if entry.lower() == want:
            return os.path.join(base, entry)
    return None


def _as_str(value) -> str:
    value = np.asarray(value)
    item = value.item() if value.shape == () else value
    if isinstance(item, bytes):
        return item.decode()
    return str(item)


def _halve_split(name: str, x_test, y_test):
    """Deterministically shuffle before halving test into val/test — ingested
    test splits can be label-sorted (aclImdb writes all pos then all neg), so
    a sequential halving would yield single-class val and test sets."""
    rng = np.random.default_rng(
        int.from_bytes(name.encode()[:4].ljust(4, b"\0"), "little")
    )
    order = rng.permutation(len(x_test))
    x_test, y_test = x_test[order], y_test[order]
    n_val = max(1, len(x_test) // 2)
    return x_test[:n_val], y_test[:n_val], x_test[n_val:], y_test[n_val:]


def _normalize(x: np.ndarray, blob) -> np.ndarray:
    if x.dtype == np.uint8:
        x = x.astype(np.float32) / 255.0
        if "mean" in blob and "std" in blob:
            mean = np.asarray(blob["mean"], np.float32)
            std = np.asarray(blob["std"], np.float32)
            x = (x - mean) / std
        return x
    return x.astype(np.float32)


def _vision_collection(name: str, blob) -> DatasetCollection:
    x_train = _normalize(blob["x_train"], blob)
    y_train = np.asarray(blob["y_train"], np.int32)
    x_test = _normalize(blob["x_test"], blob)
    y_test = np.asarray(blob["y_test"], np.int32)
    if "x_val" in blob:
        x_val = _normalize(blob["x_val"], blob)
        y_val = np.asarray(blob["y_val"], np.int32)
    else:
        x_val, y_val, x_test, y_test = _halve_split(name, x_test, y_test)
    num_classes = int(max(y_train.max(), y_test.max())) + 1
    return DatasetCollection(
        name=name,
        datasets={
            Phase.Training: ArrayDataset(x_train, y_train),
            Phase.Validation: ArrayDataset(x_val, y_val),
            Phase.Test: ArrayDataset(x_test, y_test),
        },
        num_classes=num_classes,
        input_shape=tuple(x_train.shape[1:]),
        dataset_type="vision",
        metadata={"real": True},
    )


def _fit_length(tokens: np.ndarray, max_len: int, pad_id: int) -> np.ndarray:
    if tokens.shape[1] == max_len:
        return tokens
    if tokens.shape[1] > max_len:
        return tokens[:, :max_len]
    out = np.full((tokens.shape[0], max_len), pad_id, tokens.dtype)
    out[:, : tokens.shape[1]] = tokens
    return out


def _text_collection(name: str, blob, max_len: int | None) -> DatasetCollection:
    pad_id = int(blob["pad_id"]) if "pad_id" in blob else 0
    stored_len = int(blob["max_len"]) if "max_len" in blob else blob["x_train"].shape[1]
    want_len = int(max_len) if max_len else stored_len
    x_train = _fit_length(np.asarray(blob["x_train"], np.int32), want_len, pad_id)
    x_test = _fit_length(np.asarray(blob["x_test"], np.int32), want_len, pad_id)
    y_train = np.asarray(blob["y_train"], np.int32)
    y_test = np.asarray(blob["y_test"], np.int32)
    vocab_size = (
        int(blob["vocab_size"])
        if "vocab_size" in blob
        else int(max(x_train.max(), x_test.max())) + 1
    )
    x_val, y_val, x_test, y_test = _halve_split(name, x_test, y_test)
    metadata = {
        "real": True,
        "vocab_size": vocab_size,
        "max_len": want_len,
        "pad_id": pad_id,
    }
    if "vocab" in blob:
        metadata["vocab"] = [str(w) for w in blob["vocab"]]
    if "tokenizer_type" in blob:
        # which tokenizer produced the ids (e.g. "spacy" for a
        # pre-tokenized export matching the reference's ids)
        metadata["tokenizer_type"] = str(blob["tokenizer_type"])
    num_classes = int(max(y_train.max(), y_test.max())) + 1
    return DatasetCollection(
        name=name,
        datasets={
            Phase.Training: ArrayDataset(x_train, y_train),
            Phase.Validation: ArrayDataset(x_val, y_val),
            Phase.Test: ArrayDataset(x_test, y_test),
        },
        num_classes=num_classes,
        input_shape=(want_len,),
        dataset_type="text",
        metadata=metadata,
    )


def _graph_collection(name: str, blob) -> DatasetCollection:
    x = np.asarray(blob["x"], np.float32)
    edge_index = np.asarray(blob["edge_index"], np.int32)
    y = np.asarray(blob["y"], np.int32)
    masks = {
        Phase.Training: np.asarray(blob["train_mask"], bool),
        Phase.Validation: np.asarray(blob["val_mask"], bool),
        Phase.Test: np.asarray(blob["test_mask"], bool),
    }
    datasets = {
        phase: ArrayDataset(
            inputs={"x": x, "edge_index": edge_index, "mask": mask}, targets=y
        )
        for phase, mask in masks.items()
    }
    return DatasetCollection(
        name=name,
        datasets=datasets,
        num_classes=int(y.max()) + 1,
        input_shape=(x.shape[1],),
        dataset_type="graph",
        metadata={
            "real": True,
            "num_nodes": int(x.shape[0]),
            "num_edges": int(edge_index.shape[1]),
        },
    )


def load_word_vectors(word_vector_name: str) -> tuple[list[str], np.ndarray] | None:
    """Pretrained word vectors from ``$DLS_TPU_DATA_DIR``.

    The reference's ``word_vector_name: glove.6B.100d``
    (``conf/fed_avg/imdb.yaml:14``) downloads GloVe through torchtext; here
    the vectors come from ``tools/ingest_data.py glove``, stored as
    ``glove.<dim>d.npz {words, vectors}``.  Accepts either the exact name
    (``glove.6B.100d.npz``) or the dimension-keyed ingest output
    (``glove.100d.npz``)."""
    base = data_dir()
    if not base or not word_vector_name:
        return None
    candidates = [f"{word_vector_name}.npz"]
    tail = word_vector_name.rsplit(".", 1)[-1]  # "100d"
    if tail.endswith("d") and tail[:-1].isdigit():
        candidates.append(f"glove.{tail}.npz")
    for cand in candidates:
        path = os.path.join(base, cand)
        if os.path.isfile(path):
            with np.load(path) as blob:
                return (
                    [str(w) for w in blob["words"]],
                    np.asarray(blob["vectors"], np.float32),
                )
    return None


def glove_embedding_override(
    word_vector_name: str,
    vocab: list[str],
    embed_key: str,
    n_specials: int = 2,
):
    """Build a ``ModelContext.param_override`` that replaces embed-table rows
    with pretrained vectors for every vocab word the GloVe file covers
    (specials and out-of-GloVe words keep their random init).  Returns None
    when the vectors are absent or the dimension mismatches."""
    loaded = load_word_vectors(word_vector_name)
    if loaded is None:
        return None
    words, vectors = loaded
    index = {w: i for i, w in enumerate(words)}
    rows = [
        (token_id + n_specials, index[token])
        for token_id, token in enumerate(vocab)
        if token in index
    ]
    if not rows:
        return None
    dst = np.asarray([r[0] for r in rows])
    # keep only the needed rows — the closure lives as long as the
    # ModelContext, and the full GloVe matrix is ~160MB-2.6GB
    needed = vectors[np.asarray([r[1] for r in rows])].copy()
    dim = int(vectors.shape[1])
    del vectors, words, index

    def override(params):
        from ..utils.logging import get_logger

        table = np.asarray(params[embed_key])
        if table.shape[1] != dim:
            get_logger().warning(
                "word vectors %s have dim %d but embed table is %s; skipping",
                word_vector_name,
                dim,
                table.shape,
            )
            return params
        in_bounds = dst < table.shape[0]
        table = table.copy()
        table[dst[in_bounds]] = needed[in_bounds]
        get_logger().info(
            "initialized %d/%d embedding rows from %s",
            int(in_bounds.sum()),
            table.shape[0],
            word_vector_name,
        )
        return {**params, embed_key: table}

    return override


def load_real_collection(
    name: str, *, max_len: int | None = None
) -> DatasetCollection | None:
    """Load ``$DLS_TPU_DATA_DIR/<name>.npz`` if present, else None.

    Schema is detected from the ``kind`` key (written by
    ``tools/ingest_data.py``), falling back to key inspection for
    hand-rolled files."""
    path = real_path(name)
    if path is None:
        return None
    with np.load(path, allow_pickle=False) as blob:
        if "kind" in blob:
            kind = _as_str(blob["kind"])
        elif "edge_index" in blob:
            kind = "graph"
        elif "vocab_size" in blob or "vocab" in blob or "pad_id" in blob:
            kind = "text"
        else:
            # kind-less + no text markers = the original hand-rolled vision
            # schema (x_train/y_train/x_test/y_test); int features stay a
            # vision-style float32 collection, NOT token ids
            kind = "vision"
        if kind == "graph":
            return _graph_collection(name, blob)
        if kind == "text":
            return _text_collection(name, blob, max_len)
        return _vision_collection(name, blob)
