"""Message protocol.

TPU-native equivalent of ``simulation_lib/message.py:10-62``.  Messages carry
host-side control metadata; parameter payloads are flat dicts of (device
resident) jax arrays — they are handed over by reference inside one process,
never serialized through pipes like the reference's pickled tensor dicts.
"""

import dataclasses
import os
from typing import Any

import numpy as np

from .ops.pytree import Params, param_nbytes


@dataclasses.dataclass(kw_only=True)
class Message:
    other_data: dict[str, Any] = dataclasses.field(default_factory=dict)
    in_round: bool = False  # doesn't advance the round counter
    end_training: bool = False


@dataclasses.dataclass(kw_only=True)
class ParameterMessageBase(Message):
    is_initial: bool = False


@dataclasses.dataclass(kw_only=True)
class ParameterMessage(ParameterMessageBase):
    parameter: Params
    dataset_size: int = 0

    def complete(self, old_parameter: Params) -> "ParameterMessage":
        """Fill missing keys from the old global params (partial uploads from
        FedOBD block dropout — reference ``message.py:26-29``)."""
        for key, value in old_parameter.items():
            if key not in self.parameter:
                self.parameter[key] = value
        return self


@dataclasses.dataclass(kw_only=True)
class DeltaParameterMessage(ParameterMessageBase):
    delta_parameter: Params
    dataset_size: int = 0

    def restore(self, old_parameter: Params) -> ParameterMessage:
        """Add deltas onto the old params (reference ``message.py:37-49``)."""
        parameter = {
            k: old_parameter[k] + self.delta_parameter[k] for k in self.delta_parameter
        }
        for key, value in old_parameter.items():
            parameter.setdefault(key, value)
        return ParameterMessage(
            parameter=parameter,
            dataset_size=self.dataset_size,
            other_data=self.other_data,
            in_round=self.in_round,
            end_training=self.end_training,
        )


@dataclasses.dataclass(kw_only=True)
class ParameterFileMessage(ParameterMessageBase):
    """Path-only variant (declared in the reference, ``message.py:32-34``)."""

    path: str
    dataset_size: int = 0

    def load(self) -> ParameterMessage:
        with np.load(self.path) as blob:
            return ParameterMessage(
                parameter={k: blob[k] for k in blob.files},
                dataset_size=self.dataset_size,
                other_data=self.other_data,
            )

    @staticmethod
    def dump(parameter: Params, path: str, **kwargs) -> "ParameterFileMessage":
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        np.savez(path, **{k: np.asarray(v) for k, v in parameter.items()})
        return ParameterFileMessage(path=path, **kwargs)


def get_message_size(message: Message) -> int:
    """Payload bytes of a message (reference ``get_message_size``,
    ``message.py:52-62``).  Encoded (quantized) payloads report their
    compressed wire size via their ``nbytes`` property."""
    total = 0
    for field in dataclasses.fields(message):
        value = getattr(message, field.name)
        if isinstance(value, dict):
            total += param_nbytes(value)
        elif hasattr(value, "nbytes"):
            total += int(value.nbytes)
    return total
