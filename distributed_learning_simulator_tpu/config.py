"""Config system.

TPU-native equivalent of the reference's ``DistributedTrainingConfig``
(``simulation_lib/config.py:16-104``) plus the imported surface of the toolbox
``Config`` it extends (dataset/model/hyper-parameter fields — SURVEY.md §2.2).
The YAML surface is kept compatible: the same ``conf/<algo>/<dataset>.yaml``
files, merged under ``conf/global.yaml``, with hydra-style ``++key=value``
dotted overrides and the reference's single-key-nesting unwrap trick
(``config.py:93-94``: ``++fed_avg.round=1`` style files).
"""

import copy
import dataclasses
import datetime
import os
import uuid
from typing import Any

import yaml

from .utils.logging import get_logger, set_level

CONF_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "conf")


@dataclasses.dataclass
class DistributedTrainingConfig:
    # --- dataset / model (toolbox Config surface) ---
    dataset_name: str = ""
    model_name: str = ""
    dataset_kwargs: dict[str, Any] = dataclasses.field(default_factory=dict)
    model_kwargs: dict[str, Any] = dataclasses.field(default_factory=dict)
    # --- hyper parameters ---
    optimizer_name: str = "SGD"
    batch_size: int = 64
    epoch: int = 1
    learning_rate: float = 0.01
    learning_rate_scheduler_name: str = "CosineAnnealingLR"
    momentum: float = 0.9
    weight_decay: float = 0.0
    use_amp: bool = False
    extra_hyper_parameters: dict[str, Any] = dataclasses.field(default_factory=dict)
    # --- federated fields (reference config.py:16-35) ---
    distributed_algorithm: str = ""
    worker_number: int = 1
    parallel_number: int = 0  # threaded executor: max concurrent local training loops (0 = unbounded)
    round: int = 1
    dataset_sampling: str = "iid"
    dataset_sampling_kwargs: dict[str, Any] = dataclasses.field(default_factory=dict)
    distribute_init_parameters: bool = True
    limited_resource: bool = False
    endpoint_kwargs: dict[str, Any] = dataclasses.field(default_factory=dict)
    algorithm_kwargs: dict[str, Any] = dataclasses.field(default_factory=dict)
    exp_name: str = ""
    log_file: str = ""
    # --- global flags (conf/global.yaml) ---
    # reference knob for where dataset transforms are cached (cpu/device).
    # Here transforms are pre-applied at ingest and splits live as host
    # arrays, so "cpu" (the default) is always effectively on; "device"
    # additionally keeps epoch batches device-resident — which the SPMD
    # executor does unconditionally.  Unknown values are rejected at load.
    cache_transforms: str = "cpu"
    log_level: str = "INFO"
    debug: bool = False
    save_performance_metric: bool = False
    use_slow_performance_metrics: bool = False
    merge_validation_to_training_set: bool = False
    # --- framework-specific (TPU build) ---
    seed: int = 0
    executor: str = "auto"  # auto | spmd | sequential
    save_dir: str = ""
    checkpoint_every_round: bool = True
    # round checkpoint cadence: write aggregated_model/round_N.npz every N
    # rounds (the run's final round is always written so the exit state
    # stays resumable).  0 = auto — every round for per-round dispatch
    # (the legacy cadence), every horizon boundary when
    # algorithm_kwargs.round_horizon fuses rounds.  Resume lands on the
    # latest round with BOTH a checkpoint and a record row, so a sparse
    # cadence simply re-trains the un-checkpointed tail.
    checkpoint_every: int = 0
    profile: bool = False  # capture a jax profiler trace under save_dir/profile
    # stall watchdog for the threaded executor's message fabric: abort the
    # task when NO message moves for this many seconds (0 = disabled; size
    # it well above the longest per-round local training time)
    watchdog_seconds: float = 0.0
    # fault-tolerance layer (util/faults.py::FaultPlan): seeded client
    # dropout / straggler / corrupt-update / process-kill injection, the
    # device-side update guard (update_guard / max_update_norm), threaded
    # worker-fault demotion (client_faults_nonfatal), and the
    # train_with_recovery retry budget (max_restarts /
    # restart_backoff_seconds).  Empty = no failure model, bit-exact
    # legacy behavior.  algorithm_kwargs.min_client_quorum gates how few
    # survivors a round may aggregate over.
    fault_tolerance: dict[str, Any] = dataclasses.field(default_factory=dict)
    # multi-host bring-up: retry jax.distributed.initialize this many times
    # with exponential backoff before raising a diagnostic naming the
    # unreachable coordinator (parallel/mesh.py::initialize_multihost)
    multihost_init_retries: int = 0
    # roundtrace telemetry (util/telemetry.py::TraceRecorder): structured
    # span/event JSONL under <save_dir>/server/trace.jsonl — round/horizon/
    # eval spans, per-dispatch + per-host-sync events, jit-cache `compile`
    # events, fault events, optional per-round jax.profiler windows
    # (`profile_rounds: [a, b]`).  Empty/`enabled: false` = bit-exact
    # no-op (no file, no record fields, zero dispatches either way).
    # Unknown keys raise.  Read with `python -m tools.tracedump`; see
    # docs/observability.md.
    telemetry: dict[str, Any] = dataclasses.field(default_factory=dict)

    def load_config_and_process(self, overrides: dict[str, Any] | None = None) -> None:
        """Derive ``save_dir``/``log_file`` the way the reference does
        (``config.py:36-54``: ``session/<algo>/<dataset>_<sampling>/<model>/<date>/<uuid>``)."""
        if overrides:
            apply_overrides(self, overrides)
        cache = str(self.cache_transforms or "none").lower()
        if cache not in ("cpu", "device", "none"):
            raise ValueError(
                f"cache_transforms must be cpu|device|none, got "
                f"{self.cache_transforms!r}"
            )
        if not self.save_dir:
            date = datetime.datetime.now().strftime("%Y-%m-%d_%H_%M_%S")
            task_name = f"{self.dataset_name}_{self.dataset_sampling}"
            if self.exp_name:
                task_name = f"{self.exp_name}_{task_name}"
            self.save_dir = os.path.join(
                "session",
                self.distributed_algorithm,
                task_name,
                self.model_name,
                date,
                str(uuid.uuid4()),
            )
        if not self.log_file:
            self.log_file = os.path.join("log", self.save_dir.replace(os.sep, "_") + ".log")
        set_level(self.log_level)

    def create_practitioners(self):
        """Partition the dataset over ``worker_number`` practitioners
        (reference ``config.py:55-72``)."""
        from .practitioner import create_practitioners

        return create_practitioners(self)

    def create_dataset_collection(self):
        from .data import create_dataset_collection

        return create_dataset_collection(self)

    def replace(self, **kwargs) -> "DistributedTrainingConfig":
        new = copy.deepcopy(self)
        for k, v in kwargs.items():
            setattr(new, k, v)
        return new


_FIELD_NAMES = {f.name for f in dataclasses.fields(DistributedTrainingConfig)}
_DICT_FIELDS = {
    f.name
    for f in dataclasses.fields(DistributedTrainingConfig)
    if f.default_factory is dict  # type: ignore[comparison-overlap]
}


def _coerce(value: str) -> Any:
    """Parse a ``++key=value`` override string into a python value."""
    try:
        return yaml.safe_load(value)
    except yaml.YAMLError:
        return value


def apply_overrides(config: DistributedTrainingConfig, overrides: dict[str, Any]) -> None:
    for dotted, value in overrides.items():
        parts = dotted.split(".")
        if parts[0] not in _FIELD_NAMES:
            raise KeyError(f"unknown config key: {dotted}")
        if len(parts) == 1:
            setattr(config, parts[0], value)
        else:
            node = getattr(config, parts[0])
            if not isinstance(node, dict):
                raise KeyError(f"cannot set nested key on non-dict field: {dotted}")
            for part in parts[1:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = value


def _merge_conf_dict(config: DistributedTrainingConfig, conf: dict[str, Any]) -> None:
    # single-key nesting unwrap (reference config.py:93-94)
    while "dataset_name" not in conf and len(conf) == 1:
        conf = next(iter(conf.values()))
    for key, value in conf.items():
        if key not in _FIELD_NAMES:
            get_logger().warning("ignoring unknown config key %s", key)
            continue
        if key in _DICT_FIELDS and isinstance(value, dict):
            merged = dict(getattr(config, key))
            merged.update(value)
            setattr(config, key, merged)
        else:
            setattr(config, key, value)


def load_config_from_file(
    config_file: str,
    global_conf_path: str | None = None,
    overrides: dict[str, Any] | None = None,
) -> DistributedTrainingConfig:
    """Load one YAML file merged over ``conf/global.yaml``
    (reference ``load_config_from_file``, ``config.py:98-104``)."""
    config = DistributedTrainingConfig()
    if global_conf_path is None:
        candidate = os.path.join(CONF_DIR, "global.yaml")
        global_conf_path = candidate if os.path.isfile(candidate) else None
    if global_conf_path:
        with open(global_conf_path, encoding="utf8") as f:
            global_conf = yaml.safe_load(f) or {}
        _merge_conf_dict(config, global_conf)
    with open(config_file, encoding="utf8") as f:
        conf = yaml.safe_load(f) or {}
    _merge_conf_dict(config, conf)
    if overrides:
        apply_overrides(config, overrides)
    config.load_config_and_process()
    return config


def parse_cli_args(argv: list[str]) -> tuple[str, dict[str, Any]]:
    """Parse ``--config-name <name> ++a.b=c ...`` hydra-style arguments
    (reference CLI surface: ``test.sh:2``)."""
    config_name = ""
    overrides: dict[str, Any] = {}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "--config-name":
            config_name = argv[i + 1]
            i += 2
        elif arg.startswith("--config-name="):
            config_name = arg.split("=", 1)[1]
            i += 1
        elif arg.startswith("++") or arg.startswith("+"):
            body = arg.lstrip("+")
            key, _, value = body.partition("=")
            overrides[key] = _coerce(value)
            i += 1
        else:
            raise ValueError(f"unrecognized argument: {arg}")
    if not config_name:
        raise ValueError("--config-name is required")
    return config_name, overrides


def load_config(argv: list[str], conf_dir: str | None = None) -> DistributedTrainingConfig:
    """Full CLI loader (reference ``load_config``, ``config.py:91-95``)."""
    config_name, overrides = parse_cli_args(argv)
    conf_dir = conf_dir or CONF_DIR
    path = os.path.join(conf_dir, config_name)
    if not path.endswith(".yaml"):
        path += ".yaml"
    # strip the algorithm prefix from override keys (``++fed_avg.round=1`` form,
    # reference test.sh:2); the prefix mirrors the conf subdirectory name
    cleaned: dict[str, Any] = {}
    for key, value in overrides.items():
        parts = key.split(".")
        if parts[0] not in _FIELD_NAMES and len(parts) > 1 and parts[1] in _FIELD_NAMES:
            key = ".".join(parts[1:])
        cleaned[key] = value
    return load_config_from_file(path, overrides=cleaned)
