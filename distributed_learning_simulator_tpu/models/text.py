"""Text models (flax.linen).

``TransformerClassificationModel`` mirrors the reference's IMDB classifier
(``conf/fed_avg/imdb.yaml``: d_model=100, nhead=5, num_encoder_layer=2,
max_len=300, GloVe word vectors).  When ``word_vector_name`` is set and the
ingested GloVe npz + dataset vocab are present under ``$DLS_TPU_DATA_DIR``
(``tools/ingest_data.py glove``), the embed table is initialized from the
pretrained vectors; otherwise embeddings are learned from scratch (same
shape — zero egress means no download path).
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from .registry import ModelContext, example_batch, register_model


def sinusoidal_positions(max_len: int, d_model: int) -> np.ndarray:
    pos = np.arange(max_len)[:, None]
    dim = np.arange(0, d_model, 2)[None, :]
    angle = pos / np.power(10000.0, dim / d_model)
    enc = np.zeros((max_len, d_model), dtype=np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)[:, : enc[:, 1::2].shape[1]]
    return enc


def masked_mean_pool(x, pad_mask):
    """Mean over non-pad positions; safe when a row is all padding."""
    denom = jnp.maximum(pad_mask.sum(axis=1, keepdims=True), 1)
    return (x * pad_mask[..., None]).sum(axis=1) / denom


class EncoderLayer(nn.Module):
    """Post-LN transformer encoder layer.

    Shared between the reference-parity IMDB classifier (relu, dropout after
    the FFN activation) and the BERT family (gelu, dropout on the attention
    output and after the second FFN dense) — the two placements are toggled
    rather than duplicated.  An ``ffn`` submodule replaces the dense FFN
    entirely (called as ``ffn(x, pad_mask)``, dropout then applied on its
    output) — how the MoE family reuses this layer instead of re-wiring
    attention/LN/residual.
    """

    d_model: int
    nhead: int
    dim_feedforward: int
    dropout_rate: float = 0.1
    activation: str = "relu"  # "relu" | "gelu"
    attn_out_dropout: bool = False
    ffn_dropout_on_output: bool = False
    ffn: nn.Module | None = None

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        from ..ops.fused_attention import attention_fn

        attn_mask = pad_mask[:, None, None, :]  # [B, 1, 1, L] keyed on keys
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.nhead,
            qkv_features=self.d_model,
            deterministic=not train,
            dropout_rate=self.dropout_rate,
            # Pallas fused attention for long sequences on TPU; flax's
            # XLA path below the measured crossover (same param tree)
            attention_fn=attention_fn,
        )(x, x, mask=attn_mask)
        if self.attn_out_dropout:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        x = nn.LayerNorm()(x + y)
        if self.ffn is not None:
            y = self.ffn(x, pad_mask)
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        else:
            y = nn.Dense(self.dim_feedforward)(x)
            y = nn.gelu(y) if self.activation == "gelu" else nn.relu(y)
            if not self.ffn_dropout_on_output:
                y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
            y = nn.Dense(self.d_model)(y)
            if self.ffn_dropout_on_output:
                y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm()(x + y)


class TransformerClassifier(nn.Module):
    vocab_size: int
    num_classes: int
    d_model: int = 100
    nhead: int = 5
    num_encoder_layer: int = 2
    max_len: int = 300
    pad_id: int = 0

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        pad_mask = tokens != self.pad_id  # [B, L]
        x = nn.Embed(self.vocab_size, self.d_model)(tokens)
        # cast the f32 numpy constant to x's dtype: under use_amp the embed
        # output is bf16 and an f32 addend would silently promote the whole
        # encoder stack back to f32
        x = x + sinusoidal_positions(self.max_len, self.d_model)[
            None, : tokens.shape[1]
        ].astype(x.dtype)
        for _ in range(self.num_encoder_layer):
            x = EncoderLayer(self.d_model, self.nhead, 4 * self.d_model)(
                x, pad_mask, train=train
            )
        pooled = masked_mean_pool(x, pad_mask)
        return nn.Dense(self.num_classes)(pooled)


@register_model("TransformerClassificationModel", "transformerclassificationmodel")
def _transformer(
    dataset_collection,
    d_model: int = 100,
    nhead: int = 5,
    num_encoder_layer: int = 2,
    max_len: int = 0,
    word_vector_name: str = "",
    **kwargs,
) -> ModelContext:
    meta = dataset_collection.metadata
    module = TransformerClassifier(
        vocab_size=meta.get("vocab_size", 20000),
        num_classes=dataset_collection.num_classes,
        d_model=d_model,
        nhead=nhead,
        num_encoder_layer=num_encoder_layer,
        max_len=max_len or meta.get("max_len", 300),
        pad_id=meta.get("pad_id", 0),
    )
    # pretrained embedding init when both the ingested vectors and the
    # dataset's vocab are on disk (reference: word_vector_name, torchtext
    # GloVe download at conf/fed_avg/imdb.yaml:14)
    param_override = None
    if word_vector_name and meta.get("vocab"):
        from ..data.real import glove_embedding_override

        param_override = glove_embedding_override(
            word_vector_name, meta["vocab"], "Embed_0/embedding"
        )
    return ModelContext(
        name="TransformerClassificationModel",
        module=module,
        example_input=example_batch(dataset_collection),
        num_classes=dataset_collection.num_classes,
        dataset_type="text",
        param_override=param_override,
    )
