"""Text models (flax.linen).

``TransformerClassificationModel`` mirrors the reference's IMDB classifier
(``conf/fed_avg/imdb.yaml``: d_model=100, nhead=5, num_encoder_layer=2,
max_len=300, GloVe word vectors).  When ``word_vector_name`` is set and the
ingested GloVe npz + dataset vocab are present under ``$DLS_TPU_DATA_DIR``
(``tools/ingest_data.py glove``), the embed table is initialized from the
pretrained vectors; otherwise embeddings are learned from scratch (same
shape — zero egress means no download path).
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from .registry import ModelContext, example_batch, register_model


def sinusoidal_positions(max_len: int, d_model: int) -> np.ndarray:
    pos = np.arange(max_len)[:, None]
    dim = np.arange(0, d_model, 2)[None, :]
    angle = pos / np.power(10000.0, dim / d_model)
    enc = np.zeros((max_len, d_model), dtype=np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)[:, : enc[:, 1::2].shape[1]]
    return enc


def masked_mean_pool(x, pad_mask):
    """Mean over non-pad positions; safe when a row is all padding."""
    denom = jnp.maximum(pad_mask.sum(axis=1, keepdims=True), 1)
    return (x * pad_mask[..., None]).sum(axis=1) / denom


class EncoderLayer(nn.Module):
    """Post-LN transformer encoder layer.

    Shared between the reference-parity IMDB classifier (relu, dropout after
    the FFN activation) and the BERT family (gelu, dropout on the attention
    output and after the second FFN dense) — the two placements are toggled
    rather than duplicated.  An ``ffn`` submodule replaces the dense FFN
    entirely (called as ``ffn(x, pad_mask)``, dropout then applied on its
    output) — how the MoE family reuses this layer instead of re-wiring
    attention/LN/residual.
    """

    d_model: int
    nhead: int
    dim_feedforward: int
    dropout_rate: float = 0.1
    activation: str = "relu"  # "relu" | "gelu"
    attn_out_dropout: bool = False
    ffn_dropout_on_output: bool = False
    ffn: nn.Module | None = None

    @nn.compact
    def __call__(self, x, pad_mask, train: bool = False):
        from .attention import FusedSelfAttention

        attn_mask = pad_mask[:, None, None, :]  # [B, 1, 1, L] keyed on keys
        # packed-QKV attention in the [B, H, S, Dh] layout (attention.py);
        # long sequences auto-route to the Pallas fused kernel
        y = FusedSelfAttention(
            num_heads=self.nhead,
            dropout_rate=self.dropout_rate,
        )(x, mask=attn_mask, train=train)
        if self.attn_out_dropout:
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        x = nn.LayerNorm()(x + y)
        if self.ffn is not None:
            y = self.ffn(x, pad_mask)
            y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        else:
            y = nn.Dense(self.dim_feedforward)(x)
            y = nn.gelu(y) if self.activation == "gelu" else nn.relu(y)
            if not self.ffn_dropout_on_output:
                y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
            y = nn.Dense(self.d_model)(y)
            if self.ffn_dropout_on_output:
                y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm()(x + y)


class TransformerClassifier(nn.Module):
    """``pipeline_stages`` switches the encoder trunk to a STACKED layout
    (one ``[num_encoder_layer, ...]`` parameter pytree, every layer
    homogeneous) executed in microbatches: sequentially when
    ``pp_mesh is None`` or ``pipeline_stages == 1``, as a GPipe schedule
    over the mesh's ``pp`` axis otherwise (``parallel/pipeline.py`` —
    ``lax.ppermute`` stage handoffs, one ``lax.scan`` of ticks).  Both
    executions share parameters AND per-(layer, microbatch) dropout
    streams, so ``stages=S`` matches ``stages=1`` to float accumulation
    order (``tests/test_pipeline_config.py``).  ``pipeline_stages=0``
    (default) keeps the original per-layer module layout."""

    vocab_size: int
    num_classes: int
    d_model: int = 100
    nhead: int = 5
    num_encoder_layer: int = 2
    max_len: int = 300
    pad_id: int = 0
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    pp_mesh: Any = None
    #: inside an enclosing shard_map: pipeline by axis name — the SPMD
    #: session owns the one shard_map and this module sees its LOCAL
    #: trunk slice (parallel/spmd_pp.py; mirrors long_context's sp_axis)
    pp_axis: str = ""

    def _layer(self) -> EncoderLayer:
        return EncoderLayer(self.d_model, self.nhead, 4 * self.d_model)

    def _trunk_stacked(self, x, pad_mask, train: bool):
        import jax
        from jax import lax

        n_layers = self.num_encoder_layer
        stages = self.pipeline_stages
        if n_layers % stages:
            raise ValueError(
                f"pipeline_stages={stages} must divide "
                f"num_encoder_layer={n_layers}"
            )
        layer = self._layer()
        batch, seq, width = x.shape

        # in pp_axis mode this module sees its device's LOCAL stage slice
        # of the stacked trunk (the session's in_specs shard the leading
        # layer axis) — declare the local shape so flax's param-shape
        # check matches; real initialization always happens through the
        # unsharded central model (pp_axis="")
        init_layers = n_layers // stages if self.pp_axis else n_layers

        def init_trunk(rng):
            def init_one(r):
                return layer.init(
                    {"params": r},
                    jnp.zeros((1, seq, width), jnp.float32),
                    jnp.ones((1, seq), bool),
                    train=False,
                )["params"]

            return jax.vmap(init_one)(jax.random.split(rng, init_layers))

        trunk = self.param("trunk", init_trunk)
        base_rng = (
            self.make_rng("dropout") if train else jax.random.PRNGKey(0)
        )

        n_micro = self.pipeline_microbatches or stages
        if batch % n_micro:
            # the engine's batches are uniformly padded (make_epoch_batches),
            # so the only legitimate non-divisible batch is init's [1]
            # example — anything else is a config error, not a fallback
            if batch > 1:
                raise ValueError(
                    f"batch size {batch} is not divisible by "
                    f"pipeline_microbatches={n_micro}"
                )
            n_micro = 1

        def apply_layer(x_mb, valid_mb, p_j, rng_mb, global_layer):
            rngs = (
                {"dropout": jax.random.fold_in(rng_mb, global_layer)}
                if train
                else None
            )
            return layer.apply(
                {"params": p_j}, x_mb, valid_mb, train=train, rngs=rngs
            )

        from ..parallel.pipeline import split_microbatches

        micro_in = split_microbatches({"x": x, "pad": ~pad_mask}, n_micro)
        xs, pads = micro_in["x"], micro_in["pad"]
        rngs_mb = jax.vmap(jax.random.fold_in, (None, 0))(
            base_rng, jnp.arange(n_micro)
        )

        lps = n_layers // stages
        pp_axis = self.pp_axis or "pp"

        def stage_fn(params_here, carry):
            # carry["pad"] is nonzero on PAD positions (uint8: the schedule
            # psums the carry, which rejects bools) so the bubble ticks'
            # all-zeros feed means "everything valid" — an all-False
            # validity mask would drive softmax to NaN and poison the
            # masked-out gradients through jnp.where
            s_idx = lax.axis_index(pp_axis)
            valid = carry["pad"] == 0

            def body(xc, inp):
                j, p_j = inp
                g = s_idx * lps + j
                return apply_layer(xc, valid, p_j, carry["rng"], g), None

            out, _ = lax.scan(body, carry["x"], (jnp.arange(lps), params_here))
            return {"x": out, "pad": carry["pad"], "rng": carry["rng"]}

        if self.pp_axis:
            # session-owned shard_map (parallel/spmd_pp.py): ``trunk``
            # here is this device's LOCAL [lps, ...] stage slice (the
            # session's in_specs shard the leading layer axis over pp);
            # symmetric_out makes the session's per-leaf grad-sync rule
            # exact (pipeline_body's docstring derives it)
            if stages <= 1:
                raise ValueError("pp_axis mode requires pipeline_stages > 1")
            from ..parallel.pipeline import pipeline_body

            micro = {"x": xs, "pad": pads.astype(jnp.uint8), "rng": rngs_mb}
            result = pipeline_body(
                stage_fn,
                trunk,
                micro,
                axis_name=self.pp_axis,
                n_stages=stages,
                params_local=True,
                symmetric_out=True,
            )
            return result["x"].reshape(batch, seq, width)

        if self.pp_mesh is None or stages == 1 or n_micro == 1:

            def run_mb(args):
                x_mb, pad_mb, rng_mb = args

                def body(xc, inp):
                    j, p_j = inp
                    return apply_layer(xc, ~pad_mb, p_j, rng_mb, j), None

                out, _ = lax.scan(body, x_mb, (jnp.arange(n_layers), trunk))
                return out

            out = lax.map(run_mb, (xs, pads, rngs_mb))
            return out.reshape(batch, seq, width)

        from ..parallel.pipeline import pipeline_apply

        stage_params = jax.tree.map(
            lambda p: p.reshape(stages, lps, *p.shape[1:]), trunk
        )

        micro = {"x": xs, "pad": pads.astype(jnp.uint8), "rng": rngs_mb}
        result = pipeline_apply(stage_fn, stage_params, micro, self.pp_mesh)
        return result["x"].reshape(batch, seq, width)

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        pad_mask = tokens != self.pad_id  # [B, L]
        x = nn.Embed(self.vocab_size, self.d_model)(tokens)
        # cast the f32 numpy constant to x's dtype: under use_amp the embed
        # output is bf16 and an f32 addend would silently promote the whole
        # encoder stack back to f32
        x = x + sinusoidal_positions(self.max_len, self.d_model)[
            None, : tokens.shape[1]
        ].astype(x.dtype)
        if self.pipeline_stages:
            x = self._trunk_stacked(x, pad_mask, train)
        else:
            for _ in range(self.num_encoder_layer):
                x = EncoderLayer(self.d_model, self.nhead, 4 * self.d_model)(
                    x, pad_mask, train=train
                )
        pooled = masked_mean_pool(x, pad_mask)
        return nn.Dense(self.num_classes)(pooled)


@register_model("TransformerClassificationModel", "transformerclassificationmodel")
def _transformer(
    dataset_collection,
    d_model: int = 100,
    nhead: int = 5,
    num_encoder_layer: int = 2,
    max_len: int = 0,
    word_vector_name: str = "",
    pipeline_stages: int = 0,
    pipeline_microbatches: int = 0,
    pp_mesh: Any = None,
    pp_axis: str = "",
    **kwargs,
) -> ModelContext:
    meta = dataset_collection.metadata
    module = TransformerClassifier(
        vocab_size=meta.get("vocab_size", 20000),
        num_classes=dataset_collection.num_classes,
        d_model=d_model,
        nhead=nhead,
        num_encoder_layer=num_encoder_layer,
        max_len=max_len or meta.get("max_len", 300),
        pad_id=meta.get("pad_id", 0),
        pipeline_stages=pipeline_stages,
        pipeline_microbatches=pipeline_microbatches,
        pp_mesh=pp_mesh,
        pp_axis=pp_axis,
    )
    # pretrained embedding init when both the ingested vectors and the
    # dataset's vocab are on disk (reference: word_vector_name, torchtext
    # GloVe download at conf/fed_avg/imdb.yaml:14)
    param_override = None
    if word_vector_name and meta.get("vocab"):
        from ..data.real import glove_embedding_override

        param_override = glove_embedding_override(
            word_vector_name, meta["vocab"], "Embed_0/embedding"
        )
    return ModelContext(
        name="TransformerClassificationModel",
        module=module,
        example_input=example_batch(dataset_collection),
        num_classes=dataset_collection.num_classes,
        dataset_type="text",
        param_override=param_override,
    )
