"""Vision Transformer family (flax.linen).

BASELINE.json's FedOBD headline config is "ViT-Base CIFAR-100, block-dropout
compression" — the reference zoo reaches ViT through ``cyy_torch_vision``'s
import-time registry (``common_import.py:1-2``); here the family is
first-party.  Design is TPU-first: all matmul dims are MXU-friendly
multiples of 128 for the base size, patch embedding is a strided Conv
(lowered to one big matmul), pre-LN blocks so residuals stay in
``compute_dtype`` (bf16 under ``use_amp``) without LayerNorm re-centering
the main path, and mean pooling instead of a CLS token so the sequence
length stays a static power of two.

For FedOBD block decomposition each ``Block_i`` submodule is one dropout
unit, matching the reference's transformer-encoder-layer block type
(``method/fed_obd/obd_algorithm.py:33-86``).
"""

import flax.linen as nn

from .registry import ModelContext, example_batch, register_model


class MlpBlock(nn.Module):
    mlp_dim: int
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        d_model = x.shape[-1]
        y = nn.Dense(self.mlp_dim)(x)
        y = nn.gelu(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        y = nn.Dense(d_model)(y)
        return nn.Dropout(self.dropout_rate, deterministic=not train)(y)


class ViTBlock(nn.Module):
    """Pre-LN transformer encoder block (ViT style)."""

    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        from .attention import FusedSelfAttention

        y = nn.LayerNorm()(x)
        # packed-QKV attention in the [B, H, S, Dh] layout (the flax MHA
        # einsum layout costs 17% of the round in copies — attention.py);
        # long patch sequences auto-route to the Pallas fused kernel
        y = FusedSelfAttention(
            num_heads=self.num_heads,
            dropout_rate=self.dropout_rate,
        )(y, train=train)
        x = x + nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        y = nn.LayerNorm()(x)
        return x + MlpBlock(self.mlp_dim, self.dropout_rate)(y, train=train)


class VisionTransformer(nn.Module):
    num_classes: int
    patch_size: int = 4
    d_model: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.patch_size
        x = nn.Conv(
            self.d_model, (p, p), strides=(p, p), padding="VALID", name="patch_embed"
        )(x)
        batch = x.shape[0]
        x = x.reshape(batch, -1, self.d_model)  # [B, N_patches, D]
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], self.d_model),
        )
        x = x + pos
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        for i in range(self.num_layers):
            x = ViTBlock(
                self.num_heads,
                self.mlp_dim,
                self.dropout_rate,
                name=f"Block_{i}",
            )(x, train=train)
        x = nn.LayerNorm(name="encoder_norm")(x)
        x = x.mean(axis=1)  # global average pool over patches
        return nn.Dense(self.num_classes, name="head")(x)


def _auto_patch(image_size: int) -> int:
    """ViT-Base uses 16px patches at 224; small inputs (CIFAR) use 4."""
    return 16 if image_size >= 128 else 4


def _make_vit(dataset_collection, *, d_model, num_layers, num_heads, mlp_dim, name,
              patch_size=0, dropout_rate=0.0):
    example = example_batch(dataset_collection)
    image_size = example.shape[1]
    module = VisionTransformer(
        num_classes=dataset_collection.num_classes,
        patch_size=patch_size or _auto_patch(image_size),
        d_model=d_model,
        num_layers=num_layers,
        num_heads=num_heads,
        mlp_dim=mlp_dim,
        dropout_rate=dropout_rate,
    )
    return ModelContext(
        name=name,
        module=module,
        example_input=example,
        num_classes=dataset_collection.num_classes,
    )


@register_model("vit_base", "ViT-Base", "vit-b")
def _vit_base(dataset_collection, patch_size: int = 0, dropout_rate: float = 0.0,
              **kwargs) -> ModelContext:
    return _make_vit(
        dataset_collection,
        d_model=768, num_layers=12, num_heads=12, mlp_dim=3072,
        name="vit_base", patch_size=patch_size, dropout_rate=dropout_rate,
    )


@register_model("vit_b_16", "vit_base_patch16")
def _vit_b_16(dataset_collection, dropout_rate: float = 0.0, **kwargs) -> ModelContext:
    # the /16 name pins the patch size regardless of input resolution
    return _make_vit(
        dataset_collection,
        d_model=768, num_layers=12, num_heads=12, mlp_dim=3072,
        name="vit_b_16", patch_size=16, dropout_rate=dropout_rate,
    )


@register_model("vit_small", "ViT-Small")
def _vit_small(dataset_collection, patch_size: int = 0, dropout_rate: float = 0.0,
               **kwargs) -> ModelContext:
    return _make_vit(
        dataset_collection,
        d_model=384, num_layers=12, num_heads=6, mlp_dim=1536,
        name="vit_small", patch_size=patch_size, dropout_rate=dropout_rate,
    )


@register_model("vit_tiny", "ViT-Tiny")
def _vit_tiny(dataset_collection, patch_size: int = 0, dropout_rate: float = 0.0,
              **kwargs) -> ModelContext:
    # test-scale variant: same topology, toy widths
    return _make_vit(
        dataset_collection,
        d_model=32, num_layers=2, num_heads=2, mlp_dim=64,
        name="vit_tiny", patch_size=patch_size or 8, dropout_rate=dropout_rate,
    )
